"""Benchmark entry point (run by the driver on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures training throughput (examples/sec) of the flagship model's jitted
train step on MNIST-shaped data. The reference publishes no numbers
(BASELINE.md), so vs_baseline is reported against a recorded local CPU-era
reference point once established; 1.0 until then.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax

    from __graft_entry__ import _flagship
    from deeplearning4j_tpu.datasets.dataset import DataSet

    net = _flagship()

    batch = 1024
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)]
    ds = DataSet(x, y)

    # warmup (compile)
    for _ in range(3):
        net.fit_batch(ds)
    jax.block_until_ready(net.params)

    steps = 50
    t0 = time.perf_counter()
    for _ in range(steps):
        net.fit_batch(ds)
    jax.block_until_ready(net.params)
    dt = time.perf_counter() - t0

    examples_per_sec = steps * batch / dt
    print(json.dumps({
        "metric": "mnist_mlp_train_throughput",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
