"""Benchmark entry point (run by the driver on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Measures the jitted train step of the BASELINE.md configs with
device-resident minibatches (host->device transfer is the input
pipeline's job — AsyncDataSetIterator overlaps it; here we measure the
training step the way the reference's cuDNN-path benchmarks do):

- mnist_mlp   f32  batch 1024 (round-1 continuity metric)
- lenet       bf16 batch 256  (baseline #1, conv stack)
- resnet50    bf16 batch 256  (baseline #2, the north-star: img/sec/chip + MFU)
- char_rnn    bf16 batch 32 x seq 64 (baseline #3, LSTM scan)

Timing: ``fit_batch_repeated`` fuses n steps into ONE XLA execution by
lax.scan (removes per-step host dispatch); each window is ended by a
device->host scalar read (the only reliable execution barrier through a
remote-TPU tunnel, where block_until_ready can return before the queue
drains). The window n is GROWN until one window takes >= 150 ms of wall
time, then step time = min over 3 repeat windows of (window / n). The
single dispatch+barrier overhead (~1 ms) is amortized below 1%, and the
result can only overestimate step time — never the round-2 failure mode
where a sub-resolution slope printed 0.0 ms / MFU > 1. A guard refuses to
report MFU outside (0, 1].

MFU = measured FLOP/s / peak FLOP/s, with per-step FLOPs taken from XLA's
own cost model (jit(...).lower(...).compile().cost_analysis()['flops'])
and peak from the device kind (bf16 matmul peak). The primary line is
ResNet-50 images/sec/chip; vs_baseline is achieved MFU / 0.40 (the
BASELINE.md acceptance bar — the reference publishes no numbers).
"""

from __future__ import annotations

import json
import os
import tempfile
import time

import numpy as np

from deeplearning4j_tpu.utils.perf import peak_flops as _peak_flops


_MIN_WINDOW_S = 0.15
_REPEATS = 3


def calibrated_step_time(net, ds, *, min_window_s=_MIN_WINDOW_S,
                         repeats=_REPEATS, scan0=20, max_n=50000):
    """Honest steady-state step time via ``fit_batch_repeated``.

    Grows the scan window until one window takes >= ``min_window_s`` of
    wall time, then returns ``(min over repeats of window/n, n)``.
    fit_batch_repeated compiles a fresh scan per distinct n, so after each
    growth the first window is a throwaway (pays compile) and only the
    SECOND is timed — otherwise compile time satisfies the floor and the
    loop exits with a sub-floor window (round-2 failure mode). Shared by
    bench.py and scripts/perf_probe.py."""
    net.fit_batch(ds)  # compile the single step
    float(net.score_value)

    def window(n):
        """One scanned n-step execution with a host-read barrier; wall time."""
        t0 = time.perf_counter()
        net.fit_batch_repeated(ds, n)
        float(net.score_value)
        return time.perf_counter() - t0

    n = scan0
    window(n)  # compile the scanned step, absorb stragglers
    while True:
        dt = window(n)
        if dt >= min_window_s or n >= max_n:
            # confirm on the timed repeats: ONE straggler-inflated growth
            # window must not lock in a sub-floor n (the min-of-repeats
            # is what gets published, so IT must clear the floor)
            best = min(window(n) for _ in range(repeats))
            if best >= min_window_s or n >= max_n:
                return best / n, n
            dt = best  # under-floor: grow from the honest number
        n = max(n * 2, int(n * min_window_s / max(dt, 1e-3) * 1.3))
        window(n)  # throwaway: compile at the new n


def _bench_net(net, features, labels, *, scan_len=20, is_graph: bool):
    """Warm up, time fit_batch with device-resident data, and pull per-step
    FLOPs from the compiled step's cost analysis."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet

    x = jnp.asarray(features)
    y = jnp.asarray(labels)
    ds = MultiDataSet([x], [y]) if is_graph else DataSet(x, y)

    sec_per_step, n = calibrated_step_time(net, ds, scan0=scan_len)

    flops = None
    try:
        flops = net.step_cost_analysis(ds)["flops"] or None
    except Exception:
        pass

    batch = int(x.shape[0])
    out = {
        "step_ms": round(1000.0 * sec_per_step, 3),
        "examples_per_sec": round(batch / sec_per_step, 1),
        "batch": batch,
        "timing_window_steps": n,
    }
    peak = _peak_flops(jax.devices()[0])
    if flops is not None:
        out["step_gflops"] = round(flops / 1e9, 2)
        if peak:
            mfu = flops / sec_per_step / peak
            if 0.0 < mfu <= 1.0:
                out["mfu"] = round(mfu, 4)
            else:
                # a physically impossible MFU means the timing or the cost
                # model is broken — refuse to publish it
                out["mfu_invalid"] = round(mfu, 4)
    return out


def bench_host_loop(batch: int = 1024, n_batches: int = 32,
                    epochs: int = 4) -> dict:
    """Host-loop round: full ``net.fit`` steps/sec on the mnist MLP, with
    the device step time (calibrated via ``fit_batch_repeated``)
    subtracted out — the published per-step *host overhead* is what the
    async runtime (prefetch + lazy score sync + chunked scan dispatch)
    exists to remove, and a regression here is invisible to the
    device-true ``mnist_mlp`` entry. Reports the legacy per-batch loop
    (async_prefetch/device_prefetch off, multi_step=1) next to the
    pipelined defaults; the speedup is host-side only, so it is large on
    a model whose compiled step is tiny and honest about that."""
    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator

    rng = np.random.default_rng(0)
    # a real input pipeline: per-batch host prep is a shuffled gather out
    # of the full arrays (ArrayDataSetIterator), the work AsyncDataSet-
    # Iterator exists to overlap — pre-built DataSets would give the
    # prefetch thread nothing to do and understate the pipelined loop
    x = rng.normal(size=(batch * n_batches, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch * n_batches)]
    it = ArrayDataSetIterator(x, y, batch_size=batch, shuffle=True, seed=0)
    steps = epochs * n_batches
    ds0 = DataSet(x[:batch], y[:batch])

    def fit_time(net, **fit_kw):
        net.fit(it, epochs=1, **fit_kw)   # warm-up: compile + stragglers
        float(net.score_value)
        best = float("inf")
        for _ in range(2):                # best-of-2: shave scheduler noise
            t0 = time.perf_counter()
            net.fit(it, epochs=epochs, **fit_kw)
            float(net.score_value)        # execution barrier
            best = min(best, time.perf_counter() - t0)
        return best / steps

    sec_per_step, _ = calibrated_step_time(zoo.mnist_mlp(), ds0, scan0=100)
    legacy = fit_time(zoo.mnist_mlp(), async_prefetch=False,
                      device_prefetch=False, multi_step=1)
    pipelined = fit_time(zoo.mnist_mlp())
    return {
        "batch": batch,
        "steps_timed": steps,
        "device_step_ms": round(1000.0 * sec_per_step, 4),
        "legacy_steps_per_sec": round(1.0 / legacy, 1),
        "pipelined_steps_per_sec": round(1.0 / pipelined, 1),
        "legacy_host_overhead_ms":
            round(1000.0 * max(legacy - sec_per_step, 0.0), 4),
        "pipelined_host_overhead_ms":
            round(1000.0 * max(pipelined - sec_per_step, 0.0), 4),
        "fit_speedup": round(legacy / pipelined, 2),
    }


def bench_trace_overhead(batch: int = 1024, n_batches: int = 32,
                         epochs: int = 4) -> dict:
    """Tracing-overhead guard: full ``net.fit`` steps/sec on the mnist
    MLP with the span tracer disabled vs enabled at default sampling
    (the observability acceptance bar is < 3% regression). Uses the same
    shuffled-gather input pipeline and best-of-2 fit_time as
    ``bench_host_loop`` so the two entries stay comparable; host-heavy
    per-batch dispatch is the WORST case for tracer overhead (4 spans
    per step against a tiny compiled step), so a pass here bounds the
    accelerator configs too."""
    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.observability.trace import Tracer, set_tracer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch * n_batches, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch * n_batches)]
    it = ArrayDataSetIterator(x, y, batch_size=batch, shuffle=True, seed=0)
    steps = epochs * n_batches

    def fit_time(net):
        net.fit(it, epochs=1)             # warm-up: compile + stragglers
        float(net.score_value)
        best = float("inf")
        for _ in range(2):                # best-of-2: shave scheduler noise
            t0 = time.perf_counter()
            net.fit(it, epochs=epochs)
            float(net.score_value)        # execution barrier
            best = min(best, time.perf_counter() - t0)
        return best / steps

    prev = set_tracer(Tracer(enabled=False))
    try:
        off = fit_time(zoo.mnist_mlp())
        set_tracer(Tracer(enabled=True))  # default capacity + sampling
        on = fit_time(zoo.mnist_mlp())
    finally:
        set_tracer(prev)
    overhead_pct = (on - off) / off * 100.0
    return {
        "batch": batch,
        "steps_timed": steps,
        "steps_per_sec_tracer_off": round(1.0 / off, 1),
        "steps_per_sec_tracer_on": round(1.0 / on, 1),
        "overhead_pct": round(overhead_pct, 3),
        "overhead_ok": overhead_pct < 3.0,
    }


def bench_goodput_overhead(batch: int = 1024, n_batches: int = 32,
                           epochs: int = 4) -> dict:
    """Goodput-engine overhead guard: full ``net.fit`` steps/sec with the
    efficiency ledger disabled (DL4J_TPU_GOODPUT=0 path) vs enabled —
    the ledger rides the tracer sink, counts steps, derives FLOPs once,
    and must stay under the same 3% budget the tracer honors. Same
    mnist-MLP / best-of-2 harness as ``bench_trace_overhead``, with the
    tracer ON in both arms so only the ledger's delta is measured."""
    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.observability import goodput
    from deeplearning4j_tpu.observability.trace import Tracer, set_tracer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch * n_batches, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch * n_batches)]
    it = ArrayDataSetIterator(x, y, batch_size=batch, shuffle=True, seed=0)
    steps = epochs * n_batches

    def fit_time(net):
        net.fit(it, epochs=1)             # warm-up: compile + stragglers
        float(net.score_value)
        best = float("inf")
        for _ in range(2):                # best-of-2: shave scheduler noise
            t0 = time.perf_counter()
            net.fit(it, epochs=epochs)
            float(net.score_value)        # execution barrier
            best = min(best, time.perf_counter() - t0)
        return best / steps

    prev_tracer = set_tracer(Tracer(enabled=True))
    goodput.set_enabled(False)
    try:
        off = fit_time(zoo.mnist_mlp())
        goodput.set_enabled(True)
        on = fit_time(zoo.mnist_mlp())
    finally:
        goodput.set_enabled(True)
        set_tracer(prev_tracer)
    overhead_pct = (on - off) / off * 100.0
    return {
        "batch": batch,
        "steps_timed": steps,
        "steps_per_sec_ledger_off": round(1.0 / off, 1),
        "steps_per_sec_ledger_on": round(1.0 / on, 1),
        "overhead_pct": round(overhead_pct, 3),
        "overhead_ok": overhead_pct < 3.0,
    }


def bench_identity_overhead(batch: int = 1024, n_batches: int = 32,
                            epochs: int = 4) -> dict:
    """Fleet-identity overhead guard: full ``net.fit`` steps/sec with
    the cross-process observability plane OFF (no flight recorder, bare
    tracer) vs ON (flight-recorder sink receiving every span, identity
    run-marker + heartbeat/instance gauges live). These are all the
    per-step costs ISSUE 8 added to the training hot path — federation
    pushes and scoreboard renders happen off-path — and the acceptance
    bar is < 1% regression. Same mnist-MLP best-of-2 harness as
    ``bench_trace_overhead``, tracer ON in both arms so only the
    identity plane's delta is measured."""
    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.observability import flightrec
    from deeplearning4j_tpu.observability.trace import Tracer, set_tracer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch * n_batches, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch * n_batches)]
    it = ArrayDataSetIterator(x, y, batch_size=batch, shuffle=True, seed=0)
    steps = epochs * n_batches

    def fit_time(net):
        net.fit(it, epochs=1)             # warm-up: compile + stragglers
        float(net.score_value)
        best = float("inf")
        for _ in range(2):                # best-of-2: shave scheduler noise
            t0 = time.perf_counter()
            net.fit(it, epochs=epochs)
            float(net.score_value)        # execution barrier
            best = min(best, time.perf_counter() - t0)
        return best / steps

    flightrec.uninstall_flight_recorder()
    prev_tracer = set_tracer(Tracer(enabled=True))
    try:
        off = fit_time(zoo.mnist_mlp())
        flightrec.install_flight_recorder(dir=tempfile.mkdtemp(
            prefix="bench_flight_"))
        on = fit_time(zoo.mnist_mlp())
    finally:
        flightrec.uninstall_flight_recorder()
        set_tracer(prev_tracer)
    overhead_pct = (on - off) / off * 100.0
    return {
        "batch": batch,
        "steps_timed": steps,
        "steps_per_sec_identity_off": round(1.0 / off, 1),
        "steps_per_sec_identity_on": round(1.0 / on, 1),
        "overhead_pct": round(overhead_pct, 3),
        "overhead_ok": overhead_pct < 1.0,
    }


def bench_lockcheck_overhead(batch: int = 1024, n_batches: int = 32,
                             epochs: int = 4, rounds: int = 3) -> dict:
    """Lock-order-detector overhead guard: full ``net.fit`` steps/sec
    with raw locks vs analysis/lockorder-instrumented locks (every
    ``threading.Lock``/``RLock`` wrapped, acquisition edges recorded,
    hold spans timed — the regime the whole pytest suite runs under by
    default, see ANALYSIS.md). The acceptance bar is < 3%: training's
    hot path is jitted compute, so the wrapper cost must stay in the
    host-dispatch noise.

    Instrumentation attaches at lock *allocation*, so each arm's
    net+iterator is built once under that arm's factory, then the two
    arms are timed back-to-back in paired rounds and the MEDIAN per-round
    overhead reported — a sequential A-then-B layout (like the other
    overhead entries) confounds the delta with process-lifetime drift
    (allocator/cache aging), which on this host-heavy loop dwarfs the
    real wrapper cost."""
    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.analysis import lockorder
    from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.observability.trace import Tracer, set_tracer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch * n_batches, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch * n_batches)]
    steps = epochs * n_batches

    def build():
        it = ArrayDataSetIterator(x, y, batch_size=batch, shuffle=True,
                                  seed=0)
        net = zoo.mnist_mlp()
        net.fit(it, epochs=1)             # warm-up: compile + stragglers
        float(net.score_value)
        return net, it

    def fit_time(net, it):
        t0 = time.perf_counter()
        net.fit(it, epochs=epochs)
        float(net.score_value)            # execution barrier
        return (time.perf_counter() - t0) / steps

    was_installed = lockorder.installed()
    prev_tracer = set_tracer(Tracer(enabled=True))
    try:
        lockorder.uninstall()
        net_off, it_off = build()         # raw locks
        lockorder.install()
        net_on, it_on = build()           # instrumented locks
        lockorder.uninstall()             # arms differ only by their locks
        overheads, offs, ons = [], [], []
        for _ in range(rounds):
            off = fit_time(net_off, it_off)
            on = fit_time(net_on, it_on)
            offs.append(off)
            ons.append(on)
            overheads.append((on - off) / off * 100.0)
    finally:
        if was_installed:
            lockorder.install()
        set_tracer(prev_tracer)
    overhead_pct = sorted(overheads)[len(overheads) // 2]
    return {
        "batch": batch,
        "steps_timed": steps,
        "rounds": rounds,
        "steps_per_sec_lockcheck_off": round(1.0 / min(offs), 1),
        "steps_per_sec_lockcheck_on": round(1.0 / min(ons), 1),
        "overhead_pct_rounds": [round(p, 3) for p in overheads],
        "overhead_pct": round(overhead_pct, 3),
        "overhead_ok": overhead_pct < 3.0,
    }


def bench_sched_overhead(rows: int = 4, pairs: int = 2000,
                         trials: int = 5) -> dict:
    """Scheduling-core overhead guard (SERVING.md §Traffic engine):
    in-process ``ModelServer.predict`` round trips with the default
    ``SchedulingCore`` on vs ``scheduler=False`` — the legacy
    header-less path, the one every existing client rides. The
    admission fast path costs ~2us against a ~600us predict round
    trip, so the signal is small and the measurement design is the
    whole problem: ONE server toggles ``fleet.scheduler`` between
    arms (identical process, jit cache, device thread — nothing
    differs but the admission branch) and the arms alternate EVERY
    CALL in ABBA order, so the condvar round trip's second-scale OS
    drift and any order bias cancel at the finest grain. Each trial
    reports median(paired diffs)/median(off) — robust to the
    carrier's heavy wakeup-latency tail — and the gated figure is
    the mean over trials. An A/A control trial (both arms scheduler
    off) is reported alongside so a noisy run is visible as such.
    The acceptance bar is < 3%."""
    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.scheduling.core import SchedulingCore
    from deeplearning4j_tpu.serving.server import ModelServer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(rows, 784)).astype(np.float32)
    net = zoo.mnist_mlp()
    net.init(seed=5)
    srv = ModelServer(net, warmup=False, batch_window_ms=0.0,
                      scheduler=False)
    np.asarray(srv.predict(x))            # warm-up: compile
    sched = SchedulingCore()              # default: no quotas

    def call(arm):
        srv.fleet.scheduler = arm
        t0 = time.perf_counter()
        srv.predict(x)
        return time.perf_counter() - t0

    def trial(arm_a, arm_b, n):
        diffs, offs = [], []
        for p in range(n):
            if p % 2 == 0:                # ABBA: order bias cancels
                o = call(arm_a)
                b = call(arm_b)
            else:
                b = call(arm_b)
                o = call(arm_a)
            diffs.append(b - o)
            offs.append(o)
        diffs.sort()
        offs.sort()
        med_off = offs[len(offs) // 2]
        return diffs[len(diffs) // 2] / med_off * 100.0, med_off

    try:
        for _ in range(50):               # both arms warm
            call(None)
            call(sched)
        aa_pct, _ = trial(None, None, pairs)
        trial_pcts, med_offs = [], []
        for _ in range(trials):
            pct, med_off = trial(None, sched, pairs)
            trial_pcts.append(pct)
            med_offs.append(med_off)
    finally:
        srv.stop()
    overhead_pct = sum(trial_pcts) / len(trial_pcts)
    return {
        "config": "sched_overhead",
        "rows": rows, "pairs_per_trial": pairs, "trials": trials,
        "predict_median_us_sched_off": round(
            sum(med_offs) / len(med_offs) * 1e6, 1),
        "aa_control_pct": round(aa_pct, 3),
        "overhead_pct_trials": [round(p, 3) for p in trial_pcts],
        "overhead_pct": round(overhead_pct, 3),
        "overhead_ok": overhead_pct < 3.0,
    }


def bench_input_pipeline(batch: int = 1024, n_batches: int = 32,
                         epochs: int = 4) -> dict:
    """Input-pipeline round: full ``net.fit`` steps/sec and records/sec
    through a datapipe Pipeline (shuffle window + batch + worker
    prefetch) vs the bare ``ArrayDataSetIterator`` gather — plus the
    pipeline's own stall fraction (consumer wall-clock blocked on data)
    and the checkpointing overhead question: the same run with pipeline
    metrics/spans attached must stay within the observability budget
    (< 3%). Uses the mnist MLP + best-of-2 fit_time like the host_loop
    entry so the three host-side rounds stay comparable."""
    from deeplearning4j_tpu import datapipe, zoo
    from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.observability.trace import Tracer, set_tracer

    rng = np.random.default_rng(0)
    n = batch * n_batches
    x = rng.normal(size=(n, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
    steps = epochs * n_batches

    def make_pipe():
        return (datapipe.from_arrays(x, y)
                .shuffle(window=4 * batch, seed=0)
                .batch(batch, drop_last=True)
                .prefetch(2))

    def fit_time(net, source):
        net.fit(source, epochs=1)         # warm-up: compile + stragglers
        float(net.score_value)
        best = float("inf")
        for _ in range(2):                # best-of-2: shave scheduler noise
            if not getattr(source, "auto_epochs", False):
                source.reset()
            t0 = time.perf_counter()
            net.fit(source, epochs=epochs)
            float(net.score_value)        # execution barrier
            best = min(best, time.perf_counter() - t0)
        return best / steps

    bare_it = ArrayDataSetIterator(x, y, batch_size=batch, shuffle=True,
                                   seed=0, drop_last=True)
    bare = fit_time(zoo.mnist_mlp(), bare_it)

    prev = set_tracer(Tracer(enabled=False))
    try:
        pipe_off = make_pipe()
        piped_off = fit_time(zoo.mnist_mlp(), pipe_off)
        pipe_off.close()
        set_tracer(Tracer(enabled=True))  # spans + metrics collectors live
        pipe_on = make_pipe()
        piped_on = fit_time(zoo.mnist_mlp(), pipe_on)
        snap = pipe_on.stats.snapshot()
        pipe_on.close()
    finally:
        set_tracer(prev)
    obs_pct = (piped_on - piped_off) / piped_off * 100.0
    return {
        "batch": batch,
        "steps_timed": steps,
        "bare_steps_per_sec": round(1.0 / bare, 1),
        "pipeline_steps_per_sec": round(1.0 / piped_off, 1),
        "bare_records_per_sec": round(batch / bare, 1),
        "pipeline_records_per_sec": round(batch / piped_off, 1),
        "pipeline_vs_bare_pct": round((piped_off - bare) / bare * 100.0, 2),
        "stall_fraction": round(snap["stall_fraction"], 4),
        "observability_overhead_pct": round(obs_pct, 3),
        "observability_overhead_ok": obs_pct < 3.0,
    }


def run_config(name: str) -> dict:
    """Build + time one named config (runs inside its own process)."""
    from deeplearning4j_tpu import zoo

    rng = np.random.default_rng(0)
    if name == "host_loop":
        return bench_host_loop()
    if name == "trace_overhead":
        return bench_trace_overhead()
    if name == "goodput_overhead":
        return bench_goodput_overhead()
    if name == "identity_overhead":
        return bench_identity_overhead()
    if name == "lockcheck_overhead":
        return bench_lockcheck_overhead()
    if name == "sched_overhead":
        return bench_sched_overhead()
    if name == "input_pipeline":
        return bench_input_pipeline()
    if name == "mnist_mlp":
        return _bench_net(
            zoo.mnist_mlp(),
            rng.normal(size=(1024, 784)).astype(np.float32),
            np.eye(10, dtype=np.float32)[rng.integers(0, 10, 1024)],
            scan_len=100, is_graph=False)
    if name == "lenet":
        return _bench_net(
            zoo.lenet(),
            rng.normal(size=(256, 28, 28, 1)).astype(np.float32),
            np.eye(10, dtype=np.float32)[rng.integers(0, 10, 256)],
            scan_len=50, is_graph=False)
    if name == "resnet50":
        return _bench_net(
            zoo.resnet50(),
            rng.normal(size=(256, 224, 224, 3)).astype(np.float32),
            np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, 256)],
            scan_len=20, is_graph=True)
    if name in ("char_rnn", "char_rnn_b256"):
        # b=32 is the reference's example shape (latency-capped at ~8% MFU
        # — the [32,512] recurrent matmul fills a quarter of the MXU's
        # rows); b=256 is the saturated-batch capability number that makes
        # Pallas-LSTM-kernel regressions visible (PERF.md round 4 section 5)
        b = 256 if name == "char_rnn_b256" else 32
        ids = rng.integers(0, 80, (b, 64))
        out = _bench_net(
            zoo.char_rnn(vocab_size=80, hidden=512, n_layers=2),
            np.eye(80, dtype=np.float32)[ids],
            np.eye(80, dtype=np.float32)[rng.integers(0, 80, (b, 64))],
            scan_len=20, is_graph=False)
        # tokens/sec is the natural unit for the LSTM
        out["tokens_per_sec"] = round(out["examples_per_sec"] * 64, 1)
        return out
    if name == "transformer":
        # gpt_mini training fit: the attention-workload MFU entry
        # (PERF.md §14). Per-step FLOPs come from the same XLA cost-model
        # ledger as every other entry, so the published MFU is measured,
        # not the 6*N*D estimate.
        b, t, vocab = 8, 128, 80
        ids = rng.integers(0, vocab, (b, t))
        out = _bench_net(
            zoo.gpt_mini(vocab_size=vocab, width=256, n_layers=4,
                         n_heads=4, max_len=t),
            np.eye(vocab, dtype=np.float32)[ids],
            np.eye(vocab, dtype=np.float32)[
                rng.integers(0, vocab, (b, t))],
            scan_len=10, is_graph=False)
        out["tokens_per_sec"] = round(out["examples_per_sec"] * t, 1)
        return out
    if name == "serving":
        # inference-path throughput: the continuous-batching HTTP server
        # vs the lock-serialized per-request baseline, closed-loop
        # single-row clients (scripts/serve_bench.py has the full
        # 1/8/64-concurrency report; this is the fast tracked entry)
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "scripts", "serve_bench.py")
        spec = importlib.util.spec_from_file_location("serve_bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rep = mod.bench_serving(concurrencies=(16,), requests_per_client=10)
        c16 = rep["coalesced"]["c16"]
        return {
            "rows_per_sec": c16.get("rows_per_sec"),
            "p50_ms": c16.get("p50_ms"),
            "p99_ms": c16.get("p99_ms"),
            "bit_identical": c16.get("bit_identical"),
            "speedup_vs_serialized": rep.get("speedup_c16"),
            "coalesce_rows_per_batch":
                rep["metrics"]["coalesce_rows_per_batch"],
            "compile_count": rep["metrics"]["compile_count"],
            "model": rep["model"],
        }
    if name == "decode":
        # sessionful decode goodput: the chunked-prefill + COW
        # prefix-sharing serving arm (scripts/serve_bench.py --decode has
        # the full TRANSFORMER_r02 report; this is the fast tracked entry)
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "scripts", "serve_bench.py")
        spec = importlib.util.spec_from_file_location("serve_bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rep = mod.bench_decode(sessions=6, gen_tokens=12)
        return {k: rep.get(k) for k in (
            "decode_tokens_per_sec", "inter_token_p50_ms",
            "inter_token_p99_ms", "decode_bit_identical", "logits_exact",
            "chunk_interleave_ratio", "pool_dedup_ratio",
            "compile_delta_after_warm", "model")}
    if name == "speculative":
        # speculative decode goodput: copy-task-trained gpt_mini target +
        # gpt_mini_draft, draft-on vs draft-off tokens/sec on the same
        # trained nets (scripts/serve_bench.py --decode --speculative has
        # the full TRANSFORMER_r03 report; this is the fast tracked entry)
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "scripts", "serve_bench.py")
        spec = importlib.util.spec_from_file_location("serve_bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rep = mod.bench_decode_speculative(sessions=4, gen_tokens=12,
                                           fit_steps=30)
        return {k: rep.get(k) for k in (
            "decode_tokens_per_sec", "spec_off_tokens_per_sec",
            "spec_speedup_vs_off", "spec_accept_tokens_per_step",
            "spec_rounds", "spec_accepted", "spec_rejected",
            "spec_bit_identical", "compile_delta_after_warm", "model",
            "draft_model")}
    if name == "mixed_precision":
        return bench_mixed_precision()
    raise ValueError(f"unknown bench config '{name}'")


def bench_mixed_precision(batch: int = 256, serve_rows: int = 2048) -> dict:
    """Mixed-precision round (PRECISION.md / PERF.md §10): the SAME model
    (lenet) trained under the f32 policy vs the bf16 policy — identical
    topology, batch, and data, so the steps/sec ratio isolates what the
    dtype policy buys — plus the serving forward's rows/sec in each
    precision (the coalesced-bucket shape the server runs). On XLA:CPU
    bf16 is emulated and the ratio is expected near (or below) 1.0; on
    TPU/GPU backends the same entry reports the real half-width win."""
    import jax.numpy as jnp

    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.datasets.dataset import DataSet

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    ds = DataSet(jnp.asarray(x), jnp.asarray(y))
    xs = jnp.asarray(rng.normal(size=(serve_rows, 28, 28, 1)), jnp.float32)

    out = {"model": "lenet", "batch": batch}
    for key, policy in (("f32", zoo.F32), ("bf16", zoo.BF16)):
        net = zoo.lenet(dtype=policy)
        net.init(seed=42)
        sec_per_step, n = calibrated_step_time(net, ds, scan0=50)
        out[f"{key}_step_ms"] = round(1000.0 * sec_per_step, 3)
        out[f"{key}_examples_per_sec"] = round(batch / sec_per_step, 1)
        out[f"{key}_timing_window_steps"] = n
        # serving forward: one warm-up compile, then min-of-3 timed runs
        net.output(xs).block_until_ready()
        best = min(_timed(lambda: net.output(xs).block_until_ready())
                   for _ in range(3))
        out[f"{key}_serving_rows_per_sec"] = round(serve_rows / best, 1)
    out["train_speedup_bf16"] = round(
        out["f32_step_ms"] / out["bf16_step_ms"], 3)
    out["serving_speedup_bf16"] = round(
        out["bf16_serving_rows_per_sec"] / out["f32_serving_rows_per_sec"],
        3)
    return out


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


_CONFIGS = ("mnist_mlp", "lenet", "resnet50", "char_rnn", "char_rnn_b256",
            "transformer", "serving", "decode", "speculative", "host_loop",
            "trace_overhead", "goodput_overhead", "identity_overhead",
            "lockcheck_overhead", "sched_overhead", "input_pipeline",
            "mixed_precision")


def main():
    # Each config runs in its OWN subprocess: one process's leftover HBM
    # allocations and allocator state measurably distort the next config's
    # timings (resnet50's ~9.4 GB resident slowed the char_rnn windows 4x
    # when run in-process). The child re-invokes this file with the config
    # name and prints that config's JSON.
    import subprocess
    import sys

    if len(sys.argv) > 1:  # child mode
        print(json.dumps(run_config(sys.argv[1])))
        return

    results = {}
    for name in _CONFIGS:
        # a failing/hanging/garbled config must cost only ITS entry, never
        # the whole run — that is the point of per-config isolation. One
        # retry absorbs transient remote-compile tunnel drops ("response
        # body closed"), which are environment weather, not code.
        for attempt in (0, 1):
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), name],
                    capture_output=True, text=True, timeout=1800)
            except subprocess.TimeoutExpired:
                results[name] = {"error": "timeout after 1800s"}
                break
            if proc.returncode != 0:
                results[name] = {"error": proc.stderr.strip()[-500:]}
                # retry only the transient tunnel signatures — a
                # deterministic crash must not cost a second full run
                if attempt == 0 and any(
                        sig in proc.stderr for sig in
                        ("response body closed", "DEADLINE_EXCEEDED",
                         "UNAVAILABLE")):
                    continue
                break
            try:
                results[name] = json.loads(
                    proc.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                # deterministic output problem — no retry
                results[name] = {"error": "child produced no JSON: "
                                 + proc.stdout.strip()[-300:]}
                break
            break

    primary = results.get("resnet50", {})
    mfu = primary.get("mfu")
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": primary.get("examples_per_sec", 0.0),
        "unit": "images/sec/chip",
        # BASELINE.md bar: >=40% MFU (reference publishes no numbers).
        # vs_baseline = achieved/0.40; 0.0 when MFU could not be measured
        # honestly (never fabricate parity).
        "vs_baseline": round(mfu / 0.40, 3) if mfu else 0.0,
        "extra": results,
    }))


if __name__ == "__main__":
    main()
