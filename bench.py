"""Benchmark entry point (run by the driver on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Measures the jitted train step of the BASELINE.md configs with
device-resident minibatches (host->device transfer is the input
pipeline's job — AsyncDataSetIterator overlaps it; here we measure the
training step the way the reference's cuDNN-path benchmarks do):

- mnist_mlp   f32  batch 1024 (round-1 continuity metric)
- lenet       bf16 batch 256  (baseline #1, conv stack)
- resnet50    bf16 batch 256  (baseline #2, the north-star: img/sec/chip + MFU)
- char_rnn    bf16 batch 32 x seq 64 (baseline #3, LSTM scan)

Timing is slope-based: run two window sizes via ``fit_batch_repeated``
(n steps fused into ONE XLA execution by lax.scan — removes per-step host
dispatch), each window ended by a device->host scalar read (the only
reliable execution barrier through a remote-TPU tunnel, where
block_until_ready can return before the queue drains), and take
(t_large - t_small) / (n_large - n_small). This cancels the fixed
barrier/dispatch cost and reports honest steady-state device step time.

MFU = measured FLOP/s / peak FLOP/s, with per-step FLOPs taken from XLA's
own cost model (jit(...).lower(...).compile().cost_analysis()['flops'])
and peak from the device kind (bf16 matmul peak). The primary line is
ResNet-50 images/sec/chip; vs_baseline is achieved MFU / 0.40 (the
BASELINE.md acceptance bar — the reference publishes no numbers).
"""

from __future__ import annotations

import json
import time

import numpy as np

# bf16 matmul peak FLOP/s by device kind prefix (public spec numbers)
_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e: 197 TFLOP/s bf16
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6": 918e12,        # trillium
}


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "")
    for prefix, peak in _PEAK_FLOPS.items():
        if kind.startswith(prefix):
            return peak
    return None


def _bench_net(net, features, labels, *, scan_len=20, is_graph: bool):
    """Warm up, time fit_batch with device-resident data, and pull per-step
    FLOPs from the compiled step's cost analysis."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet

    x = jnp.asarray(features)
    y = jnp.asarray(labels)
    ds = MultiDataSet([x], [y]) if is_graph else DataSet(x, y)

    net.fit_batch(ds)  # compile the single step (also used for FLOP count)
    float(net.score_value)

    n = scan_len

    def window(k):
        """k back-to-back scan executions, one host-read barrier at the
        end; returns wall time."""
        t0 = time.perf_counter()
        for _ in range(k):
            net.fit_batch_repeated(ds, n)
        float(net.score_value)
        return time.perf_counter() - t0

    window(1)  # compile the scanned step, absorb stragglers
    t1 = window(1)
    t3 = window(3)
    sec_per_step = max((t3 - t1) / (2 * n), 1e-9)

    flops = None
    try:
        rng = jax.random.PRNGKey(0)
        it = jnp.asarray(0, jnp.int32)
        if is_graph:
            args = (net.params, net.state, net.opt_state, it,
                    {net.conf.network_inputs[0]: x}, [y], {}, None, rng)
        else:
            args = (net.params, net.state, net.opt_state, it, x, y,
                    None, None, rng)
        cost = net._train_step.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        if cost:
            flops = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass

    batch = int(x.shape[0])
    out = {
        "step_ms": round(1000.0 * sec_per_step, 3),
        "examples_per_sec": round(batch / sec_per_step, 1),
        "batch": batch,
    }
    peak = _peak_flops(jax.devices()[0])
    if flops is not None:
        out["step_gflops"] = round(flops / 1e9, 2)
        if peak:
            out["mfu"] = round(flops / sec_per_step / peak, 4)
    return out


def main():
    import jax

    from deeplearning4j_tpu import zoo

    rng = np.random.default_rng(0)
    results = {}

    # --- MLP (round-1 continuity) ---------------------------------------
    net = zoo.mnist_mlp()
    results["mnist_mlp"] = _bench_net(
        net,
        rng.normal(size=(1024, 784)).astype(np.float32),
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, 1024)],
        scan_len=100, is_graph=False)

    # --- LeNet (baseline #1) --------------------------------------------
    net = zoo.lenet()
    results["lenet"] = _bench_net(
        net,
        rng.normal(size=(256, 28, 28, 1)).astype(np.float32),
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, 256)],
        scan_len=50, is_graph=False)

    # --- ResNet-50 (baseline #2, primary) -------------------------------
    net = zoo.resnet50()
    results["resnet50"] = _bench_net(
        net,
        rng.normal(size=(256, 224, 224, 3)).astype(np.float32),
        np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, 256)],
        scan_len=10, is_graph=True)

    # --- GravesLSTM char-RNN (baseline #3) ------------------------------
    net = zoo.char_rnn(vocab_size=80, hidden=512, n_layers=2)
    ids = rng.integers(0, 80, (32, 64))
    results["char_rnn"] = _bench_net(
        net,
        np.eye(80, dtype=np.float32)[ids],
        np.eye(80, dtype=np.float32)[rng.integers(0, 80, (32, 64))],
        scan_len=20, is_graph=False)
    # tokens/sec is the natural unit for the LSTM
    results["char_rnn"]["tokens_per_sec"] = round(
        results["char_rnn"]["examples_per_sec"] * 64, 1)

    primary = results["resnet50"]
    mfu = primary.get("mfu")
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": primary["examples_per_sec"],
        "unit": "images/sec/chip",
        # BASELINE.md bar: >=40% MFU (reference publishes no numbers)
        "vs_baseline": round(mfu / 0.40, 3) if mfu else 1.0,
        "extra": results,
    }))


if __name__ == "__main__":
    main()
