"""Data-parallel training over a device mesh + checkpoint/resume.

Single-process multi-device: works on a TPU slice, or anywhere via a
virtual CPU mesh. For MULTI-HOST, launch one copy of this script per
host with JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID
set and call `distributed.initialize()` first (see
deeplearning4j_tpu/parallel/distributed.py and tests/test_multihost.py
for a complete 2-process example).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     JAX_PLATFORMS=cpu python examples/distributed_data_parallel.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.datasets import ArrayDataSetIterator
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.updater import Adam
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.utils.checkpoint import (
        restore_multi_layer_network, save_checkpoint)

    print("devices:", jax.devices())
    mesh = make_mesh({"data": len(jax.devices())})

    rng = np.random.default_rng(0)
    centers = rng.normal(0, 2.5, (10, 64))
    labels = rng.integers(0, 10, 4096)
    x = (centers[labels] + rng.normal(0, 1, (4096, 64))).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[labels]

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
            .list()
            .layer(Dense(n_in=64, n_out=128, activation="relu"))
            .layer(Output(n_out=10, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.use_mesh(mesh)   # batches shard over 'data'; XLA all-reduces grads

    net.fit(ArrayDataSetIterator(x, y, batch_size=512, drop_last=True),
            epochs=3)
    print("accuracy:", net.evaluate(DataSet(x, y)).accuracy())

    ckpt = save_checkpoint(net, "/tmp/dl4j_tpu_example_ckpt/step_final")
    resumed = restore_multi_layer_network(ckpt, mesh=mesh)
    print("resumed at iteration", resumed.iteration,
          "accuracy:", resumed.evaluate(DataSet(x, y)).accuracy())


if __name__ == "__main__":
    main()
