"""N training processes -> ONE live dashboard (remote stats routing).

The reference's remote-UI story (workers post stats through a
StatsStorageRouter to one Play server's remote module,
RemoteFlowIterationListener.java:42) rendered TPU-native: this script
starts the dashboard (ui.UIServer), spawns two worker processes that each
train their own model with
``StatsListener(storage=RemoteStatsStorageRouter(url))``, and leaves the
dashboard up so you can watch both workers' score curves and parameter
histograms side by side.

Run: python examples/remote_dashboard.py
(then open the printed URL; Ctrl-C to stop)
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_WORKER = r"""
import sys, os
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import Dense, Output
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.datasets import ArrayDataSetIterator
from deeplearning4j_tpu.ui import StatsListener, RemoteStatsStorageRouter

worker_id, url = sys.argv[1], sys.argv[2]
rng = np.random.default_rng(abs(hash(worker_id)) % 2**31)
centers = rng.normal(0, 2.0, (5, 32))
labels = rng.integers(0, 5, 2048)
x = (centers[labels] + rng.normal(0, 1, (2048, 32))).astype(np.float32)
y = np.eye(5, dtype=np.float32)[labels]
conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-3)).list()
        .layer(Dense(n_in=32, n_out=64, activation="relu"))
        .layer(Output(n_out=5, activation="softmax", loss="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()
router = RemoteStatsStorageRouter(url)
net.set_listeners(StatsListener(router, frequency=2,
                                session_id="cluster_run",
                                worker_id=worker_id))
net.fit(ArrayDataSetIterator(x, y, batch_size=64), epochs=10)
router.flush()
print(worker_id, "done; posted", router.posted, flush=True)
"""


def main():
    from deeplearning4j_tpu.ui import UIServer

    server = UIServer.get_instance(port=int(os.environ.get("UI_PORT", 0)))
    print("dashboard:", server.url, flush=True)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _WORKER.format(repo=repo)
    procs = [subprocess.Popen([sys.executable, "-c", script,
                               f"worker_{i}", server.url])
             for i in range(2)]
    for p in procs:
        p.wait()

    with urllib.request.urlopen(
            server.url + "api/updates?session=cluster_run",
            timeout=30) as r:
        u = json.loads(r.read().decode())
    for wid, series in sorted(u["workers"].items()):
        print(f"{wid}: {len(series['iterations'])} updates, "
              f"score {series['scores'][0]:.3f} -> {series['scores'][-1]:.3f}")

    if os.environ.get("DL4J_TPU_EXAMPLE_NONINTERACTIVE"):
        server.stop()
        return
    print("dashboard stays up — Ctrl-C to exit")
    try:
        while True:
            time.sleep(5)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
