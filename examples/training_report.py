"""Build a standalone training report from UI components.

Demonstrates the deeplearning4j-ui-components tier (`ui/components.py`):
train a small classifier, then compose ONE self-contained HTML page from
typed components — score curve (ChartLine), per-phase timing
(ChartTimeline via parallel/stats.py), evaluation tables + ROC charts
(eval/tools.py emits through the same library), and a parameter
histogram — no external assets, viewable anywhere.

Run: python examples/training_report.py [out.html]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else "/tmp/training_report.html"

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.datasets import ArrayDataSetIterator, DataSet
    from deeplearning4j_tpu.eval import ROCMultiClass
    from deeplearning4j_tpu.eval.tools import (evaluation_components,
                                               roc_components)
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.updater import Adam
    from deeplearning4j_tpu.optimize.listeners import (
        CollectScoresIterationListener)
    from deeplearning4j_tpu.parallel.stats import (TrainingStatsCollector,
                                                   summary_table,
                                                   timeline_component)
    from deeplearning4j_tpu.ui.components import (ChartHistogram, ChartLine,
                                                  ComponentText,
                                                  DecoratorAccordion,
                                                  render_components_to_file)

    # ---- data + model ---------------------------------------------------
    rng = np.random.default_rng(0)
    centers = rng.normal(0, 3.0, (3, 16))
    idx = rng.integers(0, 3, 1024)
    x = (centers[idx] + rng.normal(0, 1, (1024, 16))).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[idx]

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
            .list()
            .layer(Dense(n_in=16, n_out=64, activation="relu"))
            .layer(Output(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    scores = CollectScoresIterationListener()
    net.set_listeners(scores)

    # ---- train, timing phases like the distributed trainers do ----------
    col = TrainingStatsCollector("worker_0")
    it = ArrayDataSetIterator(x, y, batch_size=128, shuffle=True, seed=1)
    for _ in range(4):
        with col.time_phase("fit"):
            net.fit(it, epochs=1)
        with col.time_phase("average"):
            pass  # single process: the DCN average is a no-op here

    # ---- evaluate -------------------------------------------------------
    ev = net.evaluate(DataSet(x, y))
    probs = np.asarray(net.output(x))
    roc = ROCMultiClass()
    roc.eval(y, probs)

    # ---- compose the report --------------------------------------------
    curve = ChartLine("Training score", xlabel="iteration", ylabel="score")
    curve.add_series("score", [i for i, _ in scores.scores],
                     [s for _, s in scores.scores])
    w = np.asarray(net.params["layer_0"]["W"]).ravel()
    comps = [
        ComponentText(f"MLP 16-64-3 on synthetic blobs — accuracy "
                      f"{ev.accuracy():.4f}"),
        curve,
        summary_table(col.events),
        timeline_component(col.events, title="Training phases"),
        DecoratorAccordion(
            "Evaluation", *evaluation_components(ev),
            roc_components(roc.rocs[0], title="class 0")),
        ChartHistogram.of(w, n_bins=40, title="layer_0 W distribution"),
    ]
    render_components_to_file(comps, out, title="Training report")
    print(f"accuracy={ev.accuracy():.4f}  report -> {out}")
    assert ev.accuracy() > 0.9


if __name__ == "__main__":
    main()
