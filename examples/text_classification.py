"""Document classification with TF-IDF features (the reference's
bagofwords/vectorizer workflow): corpus -> TfidfVectorizer -> dense
classifier -> evaluate. Runs anywhere (TPU or CPU); ~5 s.

Run: python examples/text_classification.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_corpus(n_per_class=120, seed=0):
    """Synthetic two-topic corpus with shared filler words (so the model
    must weight the discriminative terms — exactly what tf-idf does)."""
    rng = np.random.default_rng(seed)
    topics = {
        "sports": ["match", "goal", "team", "coach", "league", "score",
                   "player", "season"],
        "cooking": ["recipe", "oven", "flour", "butter", "simmer", "dish",
                    "flavor", "sauce"],
    }
    filler = ["the", "a", "and", "today", "really", "very", "about",
              "with", "some", "new"]
    docs, labels = [], []
    for label, (name, words) in enumerate(sorted(topics.items())):
        for _ in range(n_per_class):
            n_topic = rng.integers(3, 6)
            n_fill = rng.integers(4, 8)
            toks = ([words[i] for i in rng.integers(0, len(words), n_topic)]
                    + [filler[i] for i in rng.integers(0, len(filler),
                                                       n_fill)])
            rng.shuffle(toks)
            docs.append(" ".join(toks))
            labels.append(label)
    order = rng.permutation(len(docs))
    return [docs[i] for i in order], np.asarray(labels)[order]


def main():
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.datasets import ArrayDataSetIterator
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nlp import TfidfVectorizer
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.updater import Adam

    docs, labels = make_corpus()
    split = int(0.8 * len(docs))
    vec = TfidfVectorizer(min_word_frequency=2)
    x_train = vec.fit_transform(docs[:split]).astype(np.float32)
    x_test = vec.transform(docs[split:]).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[labels]
    print(f"vocab {len(vec.vocab)} terms; idf('the')="
          f"{vec.idf('the'):.3f} vs idf('goal')={vec.idf('goal'):.3f}")

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(5e-3))
            .list()
            .layer(Dense(n_in=x_train.shape[1], n_out=32,
                         activation="relu"))
            .layer(Output(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ArrayDataSetIterator(x_train, y[:split], batch_size=32,
                                 drop_last=True), epochs=10)
    ev = net.evaluate(DataSet(x_test, y[split:]))
    print(f"test accuracy: {ev.accuracy():.3f}")
    print(ev.stats())
    assert ev.accuracy() > 0.95


if __name__ == "__main__":
    main()
