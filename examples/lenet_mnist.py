"""LeNet on MNIST — the canonical first example (reference:
dl4j-examples LenetMnistExample).

Uses real MNIST IDX files when cached (see datasets/fetchers.py for the
cache dirs), the flagged synthetic fallback otherwise, so the script runs
anywhere. ~3 epochs reach >97% on real MNIST.

Run: python examples/lenet_mnist.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu import zoo
from deeplearning4j_tpu.datasets.fetchers import MnistDataSetIterator
from deeplearning4j_tpu.optimize import (PerformanceListener,
                                         ScoreIterationListener)


def main():
    train = MnistDataSetIterator(batch_size=128, train=True)
    test = MnistDataSetIterator(batch_size=512, train=False)
    print("dataset:", train.descriptor)

    net = zoo.lenet()  # bf16 compute / f32 master params
    net.set_listeners(ScoreIterationListener(50), PerformanceListener(50))
    net.fit(train, epochs=3)

    ev = net.evaluate(test)
    print(ev.stats())


if __name__ == "__main__":
    main()
