"""Tour of the five parallelism axes on one virtual 8-device mesh.

Runs anywhere (forces a virtual 8-device CPU mesh; identical semantics
on a real TPU slice):
  dp — data parallelism: batch sharded, params replicated, XLA all-reduce
  tp — tensor parallelism: weights column-sharded over a 'model' axis
  pp — pipeline parallelism: GPipe microbatch wavefront over 'pipe'
  ep — expert parallelism: routed MoE, experts sharded over 'expert'
  sp — sequence parallelism: LSTM time axis sharded, carry on the ring

Each section prints the placement and a training/equality signal. On a
real TPU slice the same code runs with collectives over ICI.

Run: python examples/parallelism_tour.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# APPEND the virtual-device flag to any pre-existing XLA_FLAGS instead
# of setdefault: a user running e.g. XLA_FLAGS=--xla_dump_to=/tmp/d
# would otherwise silently lose the 8-device mesh (1 device -> every
# Mesh below fails) because setdefault keeps their value verbatim
_FLAG = "--xla_force_host_platform_device_count=8"
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " " + _FLAG).strip()

import jax

# force the virtual CPU mesh BEFORE any backend init (calling
# jax.devices() first would lock in the default platform): the tour is
# about placement semantics, which are identical on real chips
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def build_mlp(n_out=32):
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.updater import Adam
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
            .list()
            .layer(Dense(n_in=16, n_out=n_out, activation="relu"))
            .layer(Output(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def main():
    if len(jax.devices()) < 8:
        sys.exit(
            f"parallelism_tour needs 8 devices, found {len(jax.devices())}. "
            "The XLA backend initialized before the virtual-device flag "
            "took effect — run with XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 (or unset any "
            "conflicting --xla_force_host_platform_device_count value).")
    devices = np.array(jax.devices()[:8])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 16)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]

    from deeplearning4j_tpu.datasets import DataSet

    # ---- dp ----
    net = build_mlp().use_mesh(Mesh(devices, ("data",)))
    print("dp: batch sharded over 8 devices, score =",
          float(net.fit_batch(DataSet(x, y))))

    # ---- dp x tp ----
    mesh2d = Mesh(devices.reshape(2, 4), ("data", "model"))
    tp_net = build_mlp().use_mesh(mesh2d, model_axis="model")
    print("tp: layer_0 W spec =",
          tuple(tp_net.params["layer_0"]["W"].sharding.spec),
          "score =", float(tp_net.fit_batch(DataSet(x, y))))

    # ---- pp ----
    from deeplearning4j_tpu.parallel.pipeline import (pipeline_train_step,
                                                      shard_stages,
                                                      split_microbatches,
                                                      stack_stage_params)
    pipe_mesh = Mesh(devices, ("pipe",))
    stages = [{"W": jnp.asarray(rng.normal(0, 0.3, (16, 16)), jnp.float32),
               "b": jnp.zeros((16,), jnp.float32)} for _ in range(8)]
    stacked = shard_stages(pipe_mesh, "pipe", stack_stage_params(stages))
    target = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    step = jax.jit(pipeline_train_step(
        pipe_mesh, "pipe", lambda p, h: jnp.tanh(h @ p["W"] + p["b"]),
        lambda out, l: jnp.mean((out - l) ** 2), lr=0.2))
    params, first = stacked, None
    for i in range(10):
        params, loss = step(params, split_microbatches(jnp.asarray(x[:, :16]), 16),
                            split_microbatches(target, 16))
        first = first if first is not None else float(loss)
    print(f"pp: 8-stage GPipe, loss {first:.4f} -> {float(loss):.4f}")

    # ---- ep ----
    from deeplearning4j_tpu.parallel.experts import (init_moe_params,
                                                     moe_ffn, shard_experts)
    ep_mesh = Mesh(devices, ("expert",))
    moe = shard_experts(ep_mesh, "expert",
                        init_moe_params(jax.random.PRNGKey(0), 8, 16, 32))
    out, aux = jax.jit(lambda p, t: moe_ffn(p, t, capacity=32))(
        moe, jnp.asarray(x))
    print("ep: 8 experts, W1 spec =", tuple(moe["W1"].sharding.spec),
          f"aux load-balance loss = {float(aux):.3f}")

    # ---- sp ----
    import deeplearning4j_tpu.ops.lstm  # registers the lstm_sequence op
    from deeplearning4j_tpu.parallel.sequence import (
        sequence_parallel_lstm, shard_sequence)
    seq_mesh = Mesh(devices, ("seq",))
    T, b, f, h = 32, 2, 4, 6
    params = {"Wx": jnp.asarray(rng.normal(0, .3, (f, 4 * h)), jnp.float32),
              "Wh": jnp.asarray(rng.normal(0, .3, (h, 4 * h)), jnp.float32),
              "b": jnp.zeros((4 * h,), jnp.float32),
              "p": jnp.zeros((3, h), jnp.float32)}
    xs = jnp.asarray(rng.normal(size=(b, T, f)), jnp.float32)
    ys, hT, cT = sequence_parallel_lstm(
        seq_mesh, "seq", params, shard_sequence(seq_mesh, "seq", xs),
        jnp.zeros((b, h)), jnp.zeros((b, h)))
    print("sp: LSTM over time-sharded seq, y shape", ys.shape,
          "final h norm %.4f" % float(jnp.linalg.norm(hT)))


if __name__ == "__main__":
    main()
