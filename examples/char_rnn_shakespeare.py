"""Character-level RNN (GravesLSTM) — the reference's
GravesLSTMCharModellingExample, on any text file.

Trains the zoo char-RNN (Pallas fused LSTM kernel on TPU) with truncated
BPTT and samples text with the streaming `rnn_time_step` decoder.

Run: python examples/char_rnn_shakespeare.py [path/to/corpus.txt]
(no corpus -> a small built-in pangram corpus so the script always runs)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

FALLBACK = ("the quick brown fox jumps over the lazy dog. "
            "pack my box with five dozen liquor jugs. ") * 200


def main():
    text = FALLBACK
    if len(sys.argv) > 1:
        with open(sys.argv[1]) as f:
            text = f.read()
    chars = sorted(set(text))
    idx = {c: i for i, c in enumerate(chars)}
    v = len(chars)
    print(f"corpus: {len(text)} chars, vocab {v}")

    from deeplearning4j_tpu import zoo
    from deeplearning4j_tpu.datasets.dataset import DataSet

    seq, batch = 64, 32
    net = zoo.char_rnn(vocab_size=v, hidden=256, n_layers=2)

    rng = np.random.default_rng(0)
    ids = np.asarray([idx[c] for c in text], np.int32)
    eye = np.eye(v, dtype=np.float32)

    def sample_batch():
        starts = rng.integers(0, len(ids) - seq - 1, batch)
        x = np.stack([eye[ids[s:s + seq]] for s in starts])
        y = np.stack([eye[ids[s + 1:s + seq + 1]] for s in starts])
        return DataSet(x, y)

    for step in range(201):
        score = net.fit_batch(sample_batch())
        if step % 50 == 0:
            print(f"step {step}: loss {float(score):.4f}")

    # streaming generation
    net.rnn_clear_previous_state()
    out = [text[0]]
    x = eye[[idx[text[0]]]][:, None, :]          # [1, 1, v]
    for _ in range(200):
        probs = np.asarray(net.rnn_time_step(x[:, 0, :]), np.float64)[0]
        probs = np.clip(probs, 1e-9, None)
        c = rng.choice(v, p=probs / probs.sum())
        out.append(chars[c])
        x = eye[[c]][:, None, :]
    print("sample:", "".join(out))


if __name__ == "__main__":
    main()
