"""Fault-tolerant training runtime: a supervisor around ``fit``.

SURVEY.md §5.3 calls preemption-resume the TPU stack's fault-tolerance
answer, and utils/checkpoint.py provides the raw primitive — but nothing
in the seed *supervised* a long fit() run: a crash, a NaN blow-up or a
TPU preemption simply lost the run. The TrainingSupervisor closes that
gap (the TensorFlow checkpoint/recovery loop of Abadi et al. §4.4,
rendered onto this framework's fused-step training):

- **Periodic checkpointing** to fresh ``step_<n>`` directories (the
  crash-atomic discipline utils/checkpoint.py documents), plus an
  atomically-renamed ``LATEST`` pointer file and retention GC that keeps
  the newest ``keep_checkpoints`` valid steps.
- **Auto-resume**: a relaunched supervisor discovers the newest *valid*
  checkpoint (``find_latest_checkpoint`` skips partial saves missing
  ``meta.json``) and continues to the same absolute target step.
- **Transient-step retry**: exceptions of the configured types are
  retried with exponential backoff before giving up.
- **NaN/Inf sentinel**: a non-finite loss rolls the net back to the last
  good checkpoint and backs off the learning rate
  (``net.set_lr_scale``); poisoned parameters are never checkpointed.
- **Preemption (SIGTERM)**: the in-flight step finishes, a final
  checkpoint is written, and ``run`` returns with status ``preempted``.
- **Cross-process coordination**: under ``jax.process_count() > 1``
  every recovery decision above is routed through the consensus layer
  in parallel/distributed.py (``agree_decision`` over tiny recovery
  codes with a ``DL4J_TPU_COLLECTIVE_TIMEOUT_S`` deadline): any-NaN →
  every process rolls back in lockstep, any-transient → every process
  retries on the same backoff schedule, SIGTERM anywhere → fleet-wide
  preemption with one final barriered checkpoint. A consensus round
  that times out names a dead peer: the supervisor flushes a
  ``peer_lost`` flight record, writes NO partial checkpoint, and
  returns status ``peer_lost`` so a launcher (resilience/launcher.py)
  can relaunch — possibly SHRUNK, whereupon the elastic reshard
  restore re-lays the run onto the smaller fleet.

Every recovery action is emitted as a :class:`RecoveryEvent` through the
net's listeners (``TrainingListener.on_recovery``), counted in
:class:`ResilienceStats` (a ``/metrics``-style ``snapshot()``), and the
checkpoint saves are timed as ``checkpoint_barrier`` phases when a
``parallel.stats.TrainingStatsCollector`` is supplied.

Deterministic fault injection for all of these paths lives in
resilience/faultinject.py; scripts/chaos_train.py drives them end to end
and asserts bit-identical final parameters vs an uninterrupted run.
"""

from __future__ import annotations

import logging
import math
import os
import shutil
import signal
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from deeplearning4j_tpu.observability import goodput as _goodput
from deeplearning4j_tpu.observability import metrics as _obs_metrics
from deeplearning4j_tpu.observability.trace import get_tracer as _get_tracer

logger = logging.getLogger("deeplearning4j_tpu")

_LATEST_POINTER = "LATEST"


class TrainingDivergedError(RuntimeError):
    """The NaN sentinel exhausted ``max_nan_rollbacks`` — training keeps
    producing non-finite losses even after rollback + LR backoff."""


@dataclass(frozen=True)
class RecoveryEvent:
    """One supervisor action: kind is ``resume`` | ``checkpoint`` |
    ``retry`` | ``rollback`` | ``preempt`` | ``gc`` | ``reshard`` |
    ``peer_lost``."""
    kind: str
    step: int
    detail: str = ""

    def __str__(self):
        return f"[{self.kind} @ step {self.step}] {self.detail}"


class ResilienceStats:
    """Thread-safe recovery counters — the observability surface the
    serving tier's ServingStats provides for inference, for training:
    restarts, rollbacks and retry counts are numbers a dashboard can
    poll, not log lines."""

    def __init__(self):
        self._lock = threading.Lock()
        self.resumes = 0
        self.checkpoints = 0
        self.retries = 0
        self.rollbacks = 0
        self.preemptions = 0
        self.gc_removed = 0
        self.nan_check_lag = 0
        self.reshards = 0
        self.peer_losses = 0

    def bump(self, counter: str, n: int = 1):
        with self._lock:
            setattr(self, counter, getattr(self, counter) + n)

    def note_nan_check_lag(self, lag: int):
        """Record how many steps behind the lazy NaN sentinel was when it
        materialized a score (max over the run; 0 = checked at the step
        boundary like the eager PR2 sentinel)."""
        with self._lock:
            self.nan_check_lag = max(self.nan_check_lag, int(lag))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "resumes_total": self.resumes,
                "checkpoints_total": self.checkpoints,
                "retries_total": self.retries,
                "rollbacks_total": self.rollbacks,
                "preemptions_total": self.preemptions,
                "checkpoints_gc_total": self.gc_removed,
                "nan_check_lag_max": self.nan_check_lag,
                "reshards_total": self.reshards,
                "peer_losses_total": self.peer_losses,
            }

    # ------------------------------------------- unified-registry bridge
    # Mirrors ServingStats.attach_to_registry: the counters stay the
    # source of truth, the registry renders them at scrape time.

    _HELP = {
        "resumes_total": "Runs resumed from a checkpoint",
        "checkpoints_total": "Checkpoints committed",
        "retries_total": "Transient step failures retried",
        "rollbacks_total": "NaN/Inf rollbacks to the last good checkpoint",
        "preemptions_total": "Clean preemption exits",
        "checkpoints_gc_total": "Old/partial checkpoints removed by GC",
        "nan_check_lag_max": "Max steps the lazy NaN sentinel lagged",
        "reshards_total": "Resumes that re-laid the run onto a "
                          "different fleet size",
        "peer_losses_total": "Consensus timeouts naming a dead peer "
                             "(the run exited with status peer_lost)",
    }

    def metric_families(self, labels=None):
        from deeplearning4j_tpu.observability.metrics import MetricFamily

        L = dict(labels or {})
        out = []
        for key, value in self.snapshot().items():
            kind = "gauge" if key == "nan_check_lag_max" else "counter"
            out.append(MetricFamily(f"dl4j_resilience_{key}", kind,
                                    self._HELP[key]).add(value, L))
        return out

    def attach_to_registry(self, registry=None, *, labels=None):
        from deeplearning4j_tpu.observability.metrics import get_registry

        self.detach_from_registry()
        reg = registry if registry is not None else get_registry()

        def _collect():
            return self.metric_families(labels)

        reg.register_collector(_collect)
        self._registry, self._collector = reg, _collect
        return reg

    def detach_from_registry(self):
        reg = getattr(self, "_registry", None)
        if reg is not None:
            reg.unregister_collector(self._collector)
            self._registry = self._collector = None


def _default_retry_on():
    from deeplearning4j_tpu.resilience.faultinject import TransientStepError
    return (TransientStepError,)


@dataclass
class SupervisorConfig:
    """Knobs for one supervised run (RESILIENCE.md has the failure
    matrix these map onto)."""

    checkpoint_dir: str
    checkpoint_every_steps: int = 100
    keep_checkpoints: int = 3
    resume: bool = True
    #: exception types treated as transient and retried with backoff;
    #: anything else propagates immediately
    retry_on: tuple = field(default_factory=_default_retry_on)
    max_step_retries: int = 3
    backoff_initial_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 5.0
    #: multiply the learning rate by this after each NaN rollback
    nan_lr_backoff: float = 0.5
    max_nan_rollbacks: int = 3
    #: check the loss for NaN/Inf every n steps. Scores are kept as lazy
    #: device arrays and only materialized (device sync) at the check
    #: boundary, before every checkpoint snapshot (so poison is still
    #: never checkpointed — the rollback window is unchanged), and at
    #: exit; 1 = the eager per-step sentinel, larger values trade
    #: detection lag (reported as ``nan_check_lag_max``) for a sync-free
    #: step path. 0 disables the sentinel.
    nan_check_every: int = 1
    #: hand the orbax write + meta/LATEST renames to a background writer
    #: thread; the step path only pays a donation-safe device-side
    #: snapshot. Barriers (join + error propagation) happen at the next
    #: save, NaN rollback, preemption and exit, preserving the crash
    #: contract: a crash during the background write still leaves the
    #: previous valid checkpoint restorable.
    async_checkpoints: bool = True
    handle_sigterm: bool = True
    #: keep a crash flight recorder (observability.flightrec) installed
    #: for the run: recent spans + recovery events, flushed atomically
    #: to flight_<instance>.json in checkpoint_dir on SIGTERM, NaN
    #: rollback, preemption and crash
    flight_recorder: bool = True
    #: persistent XLA compilation cache dir for this run (None = the
    #: DL4J_TPU_COMPILE_CACHE env var, if set) — a restarted replacement
    #: process pointed at the same dir recompiles ~nothing
    compile_cache_dir: Optional[str] = None
    #: route recovery decisions through the cross-process consensus
    #: layer: "auto" (default) turns it on exactly when
    #: jax.process_count() > 1 and a coordination-service client exists;
    #: True/False force it (False runs a multi-process fleet with
    #: process-LOCAL recovery — only safe when no fault ever fires)
    coordinate: object = "auto"
    #: per-run override for the consensus/barrier deadline (None = the
    #: DL4J_TPU_COLLECTIVE_TIMEOUT_S env var / its default). A consensus
    #: round exceeding it names a lost peer and ends the run
    collective_timeout_s: Optional[float] = None
    #: injectable for tests (real runs sleep through backoff)
    sleep_fn: Callable[[float], None] = time.sleep


@dataclass
class SupervisorResult:
    status: str                    # "completed" | "preempted" | "peer_lost"
    final_step: int
    resumed_from: Optional[str]
    events: List[RecoveryEvent]
    stats: dict
    #: goodput.RunReport for the whole supervised run (None when the
    #: goodput engine is disabled); also saved as run_report.json
    #: (rank-suffixed ``run_report.r<k>.json`` off rank 0) in the
    #: checkpoint dir
    report: Optional[object] = None
    #: status == "peer_lost" detail: {"lost_ranks": [...],
    #: "detection_s": float, "round": str} from the timed-out consensus
    peer_loss: Optional[dict] = None


class TrainingSupervisor:
    """Wraps ``MultiLayerNetwork.fit`` / ``ComputationGraph.fit`` in the
    checkpoint/recovery loop. The core entry point is :meth:`run` (a
    deterministic ``batch_fn(step) -> DataSet`` plus an absolute target
    step — exactly resumable because the data for step *i* never depends
    on how many times the process died); :meth:`fit` adapts the familiar
    (data, labels, epochs, batch_size) surface onto it."""

    def __init__(self, net, config: SupervisorConfig, *, injector=None,
                 stats_collector=None):
        self.net = net
        self.config = config
        self.injector = injector
        self.stats_collector = stats_collector  # TrainingStatsCollector
        self.stats = ResilienceStats()
        self.events: List[RecoveryEvent] = []
        self._preempt_requested = False
        self._last_good: Optional[str] = None
        #: cross-process consensus routing (set per run by
        #: _setup_coordination; False for single-process runs)
        self._coordinated = False
        #: filled when a consensus round named a dead peer
        self.peer_loss: Optional[dict] = None
        #: datapipe.Pipeline being supervised (fit_pipeline): its
        #: state_dict rides in every checkpoint's meta.json and is
        #: restored alongside the net on resume/rollback
        self._pipeline = None
        #: goodput ledger for the active run (reshard annotations land
        #: on the RunReport through it)
        self._ledger = None
        self._lr_scale0 = getattr(net, "_lr_scale", 1.0)
        #: async checkpoint writer state: at most ONE write in flight
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_pending: Optional[dict] = None
        #: (step, lazy device score) pairs not yet NaN-checked
        self._pending_scores: List[tuple] = []
        os.makedirs(config.checkpoint_dir, exist_ok=True)
        #: crash flight recorder (black box): best-effort — its absence
        #: must never break training
        self.flight = None
        if config.flight_recorder:
            try:
                from deeplearning4j_tpu.observability.flightrec import \
                    install_flight_recorder
                self.flight = install_flight_recorder(
                    dir=config.checkpoint_dir)
            except Exception:
                self.flight = None

    def _flight_flush(self, reason: str, exc=None) -> Optional[str]:
        """Flush the black box (best-effort; returns the artifact path)."""
        if self.flight is None:
            return None
        try:
            return self.flight.flush(reason, exc=exc)
        except Exception:
            return None

    # --------------------------------------------------- cross-process glue
    def _setup_coordination(self):
        """Decide (per run) whether recovery decisions go through the
        consensus layer. Coordinated runs force synchronous checkpoints:
        the save barriers are cross-process collectives and must run on
        the thread making the consensus calls, in the same order on
        every rank."""
        from deeplearning4j_tpu.parallel import distributed as _dist
        cfg = self.config
        if isinstance(cfg.coordinate, bool):
            self._coordinated = cfg.coordinate
        else:
            self._coordinated = _dist.consensus_available()
        if self._coordinated and cfg.async_checkpoints:
            logger.info(
                "multi-process run: checkpoints forced synchronous — the "
                "save barrier is a cross-process collective and must stay "
                "on the consensus thread")
        return self._coordinated

    def _agree(self, code: int, name: str) -> List[int]:
        from deeplearning4j_tpu.parallel import distributed as _dist
        return _dist.agree_decision(
            code, name=name, timeout_s=self.config.collective_timeout_s)

    def _any_process(self, flag: bool, name: str) -> bool:
        return any(self._agree(1 if flag else 0, name))

    def _on_peer_lost(self, exc) -> None:
        """A consensus round named a dead peer: record it, flush the
        black box, and write NOTHING further — the last barriered
        checkpoint (meta.json committed on every rank) is the newest
        restorable state, and any save attempt now would just hang on
        the corpse."""
        self.peer_loss = {
            "lost_ranks": list(getattr(exc, "lost_ranks", [])),
            "detection_s": getattr(exc, "elapsed_s", None),
            "round": getattr(exc, "round_name", ""),
        }
        self._emit("peer_lost", self.net.iteration,
                   f"{exc}", counter="peer_losses")
        self._flight_flush("peer_lost", exc=exc)

    # --------------------------------------------------------------- events
    def _emit(self, kind: str, step: int, detail: str = "",
              counter: Optional[str] = None):
        ev = RecoveryEvent(kind, step, detail)
        self.events.append(ev)
        if counter:
            self.stats.bump(counter)
        if self.flight is not None:
            try:  # the black box sees every recovery event
                self.flight.record_event(kind, step, detail)
            except Exception:
                pass
        logger.info("resilience %s", ev)
        for l in getattr(self.net, "listeners", ()):
            on_recovery = getattr(l, "on_recovery", None)
            if on_recovery is not None:
                on_recovery(self.net, ev)
        return ev

    # ----------------------------------------------------------- checkpoint
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.config.checkpoint_dir, f"step_{step}")

    def _write_latest_pointer(self, path: str):
        # atomic latest-pointer: observers (and a quick resume fast path)
        # read one small file; the rename is the commit point, so the
        # pointer never names a half-written checkpoint. Multi-process:
        # rank 0 only — N processes share the checkpoint dir, and
        # concurrent writers to one .tmp path would interleave
        import jax
        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        tmp = os.path.join(self.config.checkpoint_dir,
                           "." + _LATEST_POINTER + ".tmp")
        with open(tmp, "w") as f:
            f.write(os.path.basename(path))
        os.replace(tmp, os.path.join(self.config.checkpoint_dir,
                                     _LATEST_POINTER))

    def _checkpoint(self, step: int, reason: str, wait: bool = False) -> str:
        """Checkpoint the net's current state. With ``async_checkpoints``
        the step path pays only a donation-safe device-side snapshot
        (``snapshot_for_checkpoint``); the orbax write, meta.json rename
        and LATEST pointer happen on a background writer thread. The
        previous in-flight write is always drained first (one writer at a
        time), and ``wait=True`` (preemption/final saves) drains this one
        too. Writer errors — including injected crashes from the
        faultinject seam, which fires inside the writer — surface at the
        next drain point exactly as a synchronous save's would."""
        from deeplearning4j_tpu.utils.checkpoint import (
            save_checkpoint, snapshot_for_checkpoint)
        cfg = self.config
        tracer = _get_tracer()
        self._drain_checkpoint()
        path = self._step_dir(step)
        # pipeline state is captured HERE on the main thread — in the
        # async path the background writer gets plain data, consistent
        # with the device snapshot taken at the same step boundary
        extra = None
        if self._pipeline is not None:
            extra = {"datapipe": self._pipeline.state_dict()}
        if not cfg.async_checkpoints or self._coordinated:
            with tracer.span("checkpoint_write", step=step, reason=reason):
                save_checkpoint(self.net, path, stats=self.stats_collector,
                                extra_meta=extra)
                self._write_latest_pointer(path)
            self._commit_checkpoint(step, reason, path)
            return path
        with tracer.span("checkpoint_snapshot", step=step):
            snap = snapshot_for_checkpoint(self.net)
        pending = {"step": step, "reason": reason, "path": path,
                   "error": None}

        def write():
            # runs on dl4j-ckpt-writer: the span lands in that thread's
            # trace lane, overlapping the main loop's device_step spans
            try:
                with tracer.span("checkpoint_write", step=step,
                                 reason=reason):
                    save_checkpoint(snap, path, stats=self.stats_collector,
                                    extra_meta=extra)
                    self._write_latest_pointer(path)
            except BaseException as e:  # kept for the drain barrier
                pending["error"] = e

        t = threading.Thread(target=write, name="dl4j-ckpt-writer",
                             daemon=True)
        self._ckpt_pending = pending
        self._ckpt_thread = t
        t.start()
        if wait:
            self._drain_checkpoint()
        return path

    def _commit_checkpoint(self, step: int, reason: str, path: str):
        """Post-write bookkeeping (main thread only): rollback target,
        event/counter, retention GC."""
        self._last_good = path
        self._emit("checkpoint", step, f"{reason} -> {path}",
                   counter="checkpoints")
        self._gc(step)

    def _drain_checkpoint(self, raise_errors: bool = True):
        """Barrier on the in-flight background write (no-op when idle).
        On success the checkpoint becomes the rollback target; on failure
        the stored exception (e.g. an InjectedCrash that fired between
        the tree commit and the meta rename) is re-raised here — the
        async analogue of a synchronous save crashing in place."""
        t, pending = self._ckpt_thread, self._ckpt_pending
        if t is None:
            return
        timeout_s = float(os.environ.get(
            "DL4J_TPU_CKPT_JOIN_TIMEOUT_S", "600"))
        with _get_tracer().span("checkpoint_barrier"):
            t.join(timeout=timeout_s)
        if t.is_alive():
            # the writer wedged (dead filesystem, hung flush): a barrier
            # that never returns would freeze training; fail the drain
            # instead and leave the daemon thread to the interpreter
            err = TimeoutError(
                f"checkpoint writer did not finish within {timeout_s:g}s "
                "(DL4J_TPU_CKPT_JOIN_TIMEOUT_S)")
            self._ckpt_thread = None
            self._ckpt_pending = None
            if raise_errors:
                raise err
            logger.error("async checkpoint write for %s failed: %r",
                         pending["path"], err)
            return
        self._ckpt_thread = None
        self._ckpt_pending = None
        err = pending["error"]
        if err is not None:
            if raise_errors:
                raise err
            logger.error("async checkpoint write for %s failed: %r",
                         pending["path"], err)
            return
        self._commit_checkpoint(pending["step"], pending["reason"],
                                pending["path"])

    def _gc(self, current_step: int):
        """Retention: keep the newest ``keep_checkpoints`` valid steps;
        also sweep partial saves older than the latest valid one (they
        can never be resumed from and would otherwise accumulate one per
        crash). Multi-process: rank 0 only — checkpoints sit in a shared
        directory, and the post-save barrier guarantees no peer is still
        reading a directory rank 0 sweeps."""
        import jax
        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        from deeplearning4j_tpu.utils.checkpoint import (_STEP_DIR,
                                                         is_valid_checkpoint)
        root = self.config.checkpoint_dir
        entries = []
        for name in os.listdir(root):
            m = _STEP_DIR.match(name)
            if m:
                entries.append((int(m.group(1)), os.path.join(root, name)))
        entries.sort()
        valid = [(s, p) for s, p in entries if is_valid_checkpoint(p)]
        keep = {p for _, p in valid[-max(1, self.config.keep_checkpoints):]}
        newest_valid = valid[-1][0] if valid else -1
        removed = 0
        for step, path in entries:
            partial = not is_valid_checkpoint(path)
            if path in keep or (partial and step >= newest_valid):
                continue
            shutil.rmtree(path, ignore_errors=True)
            removed += 1
        if removed:
            self.stats.bump("gc_removed", removed)
            self._emit("gc", current_step,
                       f"removed {removed} old/partial checkpoint(s)")

    def _mesh_kwargs(self) -> dict:
        """Restore kwargs matching the live net's placement, so a meshed
        net's checkpoint leaves land DIRECTLY in their target
        NamedShardings (utils/checkpoint.py schema v2) instead of a host
        round-trip."""
        meshed = getattr(self.net, "_mesh", None)
        if meshed is None:
            return {}
        detail = getattr(self.net, "_mesh_detail", None) or {}
        return dict(mesh=meshed[0], data_axis=meshed[1],
                    model_axis=detail.get("model_axis"),
                    tp_rules=detail.get("tp_rules"))

    def _current_mesh_json(self):
        meshed = getattr(self.net, "_mesh", None)
        if meshed is None:
            return None
        mesh, data_axis = meshed
        detail = getattr(self.net, "_mesh_detail", None) or {}
        return {"axis_names": [str(a) for a in mesh.axis_names],
                "shape": [int(mesh.shape[a]) for a in mesh.axis_names],
                "device_count": int(mesh.size),
                "data_axis": data_axis,
                "model_axis": detail.get("model_axis")}

    def _load_into(self, path: str):
        """Restore ``path`` INTO the existing net object (params, state,
        optimizer state, step/epoch counters) so user references stay
        valid; the compiled step is shape-compatible and is reused.

        Elastic: the checkpoint may have been saved under a DIFFERENT
        mesh/fleet size (schema-v2 layout manifest records the old
        world). Params re-lay onto the live net's mesh automatically;
        a datapipe shard cursor baked for the old fleet is remapped via
        the coverage rule in datapipe/reshard.py, and the transition is
        emitted as a ``reshard`` RecoveryEvent + stamped onto the
        RunReport."""
        from deeplearning4j_tpu.utils.checkpoint import (
            _net_kind, read_checkpoint_layout, read_checkpoint_meta,
            restore_computation_graph, restore_multi_layer_network)
        kw = self._mesh_kwargs()
        if _net_kind(self.net) == "graph":
            restored = restore_computation_graph(path, **kw)
        else:
            restored = restore_multi_layer_network(path, **kw)
        net = self.net
        net.params = restored.params
        net.state = restored.state
        net.opt_state = restored.opt_state
        net.iteration = restored.iteration
        net.epoch = restored.epoch
        self._last_good = path

        layout = read_checkpoint_layout(path)
        old_mesh = (layout or {}).get("mesh")
        new_mesh = self._current_mesh_json()
        old_n = (old_mesh or {}).get("device_count", 1)
        new_n = (new_mesh or {}).get("device_count", 1)
        reshard_detail = None
        if layout is not None and old_n != new_n:
            reshard_detail = {"from_mesh": old_mesh, "to_mesh": new_mesh,
                              "from_process_count":
                                  layout.get("process_count")}

        if self._pipeline is not None:
            meta = read_checkpoint_meta(path)
            if "datapipe" in meta:
                from deeplearning4j_tpu.datapipe.reshard import (
                    remap_for, shard_position)
                dp_state = meta["datapipe"]
                old_pos = shard_position(dp_state)
                try:
                    self._pipeline.load_state_dict(dp_state)
                except ValueError:
                    # shard cursor baked for another fleet size: re-cut
                    # the stream at the coverage rule's low-water mark
                    remapped = remap_for(self._pipeline, dp_state)
                    self._pipeline.load_state_dict(remapped)
                    new_pos = shard_position(remapped)
                    reshard_detail = dict(reshard_detail or {})
                    reshard_detail["datapipe"] = {
                        "from": old_pos and dict(zip("nik", old_pos)),
                        "to": new_pos and dict(zip("nik", new_pos))}
            else:
                logger.warning(
                    "checkpoint %s carries no datapipe state; the pipeline "
                    "keeps its current position", path)

        if reshard_detail is not None:
            self._emit("reshard", net.iteration,
                       f"re-laid onto {new_n} device(s) from a "
                       f"{old_n}-device checkpoint at {path}",
                       counter="reshards")
            if self._ledger is not None:
                self._ledger.annotate(reshard=reshard_detail)

        if self._coordinated:
            # restore barrier: no process races ahead of the orbax
            # commit (into training — or worse, into a rank-0 GC sweep)
            # while a peer is still reading this checkpoint
            from deeplearning4j_tpu.parallel import distributed as _dist
            _dist.barrier("dl4j_restore_done",
                          timeout_s=self.config.collective_timeout_s)

    # ------------------------------------------------------------- stepping
    def request_preemption(self):
        """Ask for a clean stop at the next step boundary (what the
        SIGTERM handler calls; tests and the fault injector call it
        directly)."""
        self._preempt_requested = True

    def _sigterm(self, signum, frame):
        logger.warning("SIGTERM received — will checkpoint and exit at "
                       "the next step boundary")
        # flush the black box NOW: if the sender escalates to SIGKILL
        # before the clean boundary, the post-mortem already exists
        self._flight_flush("sigterm")
        self.request_preemption()

    def _attempt_step(self, ds, step: int):
        """One fit_batch with transient-failure retry + exponential
        backoff. The injector's before_step hook runs inside the retried
        region so injected transient faults exercise this exact path.

        Coordinated runs add a pre-step consensus round per attempt:
        nobody enters the compiled step (whose gradient psum is a
        collective) unless EVERY process is ready, and a transient on
        any rank backs the whole fleet off on the same schedule — the
        single-process retry semantics, made deadlock-free. A failure
        that surfaces INSIDE the collective step cannot be retried in
        lockstep (peers are already mid-psum) and propagates."""
        cfg = self.config
        delay = cfg.backoff_initial_s
        attempt = 0
        while True:
            err = None
            try:
                if self.injector is not None:
                    self.injector.before_step(self, self.net, step)
            except cfg.retry_on as e:
                err = e
            if self._coordinated:
                failed = any(self._agree(0 if err is None else 1, "step"))
            else:
                failed = err is not None
            if not failed:
                try:
                    return self.net.fit_batch(ds)
                except cfg.retry_on as e:
                    if self._coordinated:
                        raise
                    err = e
            attempt += 1
            if attempt > cfg.max_step_retries:
                if err is not None:
                    raise err
                from deeplearning4j_tpu.resilience.faultinject import \
                    TransientStepError
                raise TransientStepError(
                    f"a peer process kept failing step {step} past "
                    f"{cfg.max_step_retries} coordinated retries")
            if err is not None:
                cause = f"{type(err).__name__}: {err}"
            else:
                cause = "peer transient failure"
            self._emit(
                "retry", step,
                f"attempt {attempt}/{cfg.max_step_retries} after "
                f"{cause}; backoff {delay:.3f}s",
                counter="retries")
            cfg.sleep_fn(delay)
            delay = min(delay * cfg.backoff_factor, cfg.backoff_max_s)

    def _flush_nan_checks(self):
        """Materialize every pending lazy score (device sync happens HERE,
        not on the step path) and return the first non-finite
        ``(step, value)``, or None. Detection lag — how many steps ran
        past a score before it was checked — is recorded in
        ``ResilienceStats.nan_check_lag``."""
        pending, self._pending_scores = self._pending_scores, []
        bad = None
        now = self.net.iteration
        for step, score in pending:
            self.stats.note_nan_check_lag(now - (step + 1))
            if bad is None and not math.isfinite(float(score)):
                bad = (step, float(score))
        return bad

    def _agreed_bad(self):
        """The fleet-wide NaN decision. Single-process: just the local
        flush. Coordinated: every process publishes its local verdict
        (0 = clean, step+1 = first bad step) and the agreed outcome is
        the MINIMUM bad step any rank saw — so one poisoned rank rolls
        every rank back to the same checkpoint, in lockstep, even the
        ranks whose local losses were finite. Call sites are
        schedule-aligned (same steps, same due boundaries), so the
        consensus rounds line up by construction."""
        bad = self._flush_nan_checks()
        if not self._coordinated:
            return bad
        code = (bad[0] + 1) if bad is not None else 0
        codes = self._agree(code, "nan")
        hits = [c - 1 for c in codes if c]
        if not hits:
            return None
        step = min(hits)
        score = bad[1] if bad is not None and bad[0] == step else \
            float("nan")
        return (step, score)

    def _rollback(self, step: int, score: float, rollbacks: int):
        cfg = self.config
        # the poisoned trajectory's un-checked scores are moot after the
        # restore, and the writer must be idle before _last_good is read
        self._pending_scores.clear()
        self._drain_checkpoint()
        if rollbacks > cfg.max_nan_rollbacks:
            raise TrainingDivergedError(
                f"loss is non-finite ({score}) at step {step} even after "
                f"{cfg.max_nan_rollbacks} rollback(s) with LR backoff "
                f"x{cfg.nan_lr_backoff} each — giving up rather than "
                "checkpointing poisoned parameters")
        if self._last_good is None:
            raise TrainingDivergedError(
                f"loss is non-finite ({score}) at step {step} and no good "
                "checkpoint exists to roll back to")
        new_scale = getattr(self.net, "_lr_scale", 1.0) * cfg.nan_lr_backoff
        with _get_tracer().span("rollback", step=step):
            self._load_into(self._last_good)
        if hasattr(self.net, "set_lr_scale"):
            self.net.set_lr_scale(new_scale)
        self._emit("rollback", self.net.iteration,
                   f"non-finite loss ({score}) at step {step}; restored "
                   f"{self._last_good}, lr scale now {new_scale:g}",
                   counter="rollbacks")
        self._flight_flush("nan_rollback")

    # ------------------------------------------------------------ main loop
    def run(self, batch_fn: Callable[[int], object],
            target_step: int) -> SupervisorResult:
        """Train until ``net.iteration == target_step`` feeding
        ``batch_fn(step)`` at each step. Resumable: relaunching with the
        same arguments continues from the newest valid checkpoint to the
        same final step."""
        from deeplearning4j_tpu.parallel import distributed as _dist
        from deeplearning4j_tpu.utils.checkpoint import (
            find_latest_checkpoint)
        cfg = self.config
        net = self.net
        resumed_from = None

        _obs_metrics.install_runtime_metrics()
        from deeplearning4j_tpu.compilecache import configure as _cc_configure
        _cc_configure(cfg.compile_cache_dir)  # falls back to env var
        self._setup_coordination()
        # attach (and stay attached after run(): a post-run scrape still
        # reports this job's recovery counters alongside serving/compile
        # series from the same process)
        self.stats.attach_to_registry(
            labels={"job": os.path.basename(
                os.path.normpath(cfg.checkpoint_dir))})
        ledger = _goodput.start_run("resilient_fit", net=net)
        self._ledger = ledger

        if cfg.resume:
            latest = find_latest_checkpoint(cfg.checkpoint_dir)
            if latest is not None:
                with _get_tracer().span("restore"):
                    self._load_into(latest)
                self._emit("resume", net.iteration, f"restored {latest}",
                           counter="resumes")
                resumed_from = latest

        old_handler = None
        use_signal = (cfg.handle_sigterm
                      and threading.current_thread()
                      is threading.main_thread())
        if use_signal:
            old_handler = signal.signal(signal.SIGTERM, self._sigterm)
        rollbacks = 0
        status = "completed"
        try:
            try:
                if (self._last_good is None
                        and net.iteration < target_step):
                    # baseline save: the NaN sentinel needs a rollback
                    # target from the very first step, and a crash before
                    # the first periodic save must not lose the (possibly
                    # expensive) initialization
                    self._checkpoint(net.iteration, "baseline")

                while True:
                    if self._coordinated:
                        # one consensus round per loop pass: SIGTERM (or
                        # an injected preempt) on ANY rank stops every
                        # rank at this same step boundary
                        if self._any_process(self._preempt_requested,
                                             "preempt"):
                            self._preempt_requested = True
                    if self._preempt_requested:
                        status = "preempted"
                        break
                    if net.iteration >= target_step:
                        # tail flush: the last chunk of lazy scores may
                        # hold poison — a rollback rewinds iteration and
                        # re-enters
                        bad = self._agreed_bad()
                        if bad is not None:
                            rollbacks += 1
                            self._rollback(bad[0], bad[1], rollbacks)
                            continue
                        break
                    step = net.iteration
                    score = self._attempt_step(batch_fn(step), step)
                    if cfg.nan_check_every > 0:
                        self._pending_scores.append((step, score))
                    due_check = (cfg.nan_check_every > 0
                                 and net.iteration % cfg.nan_check_every
                                 == 0)
                    due_ckpt = (net.iteration % cfg.checkpoint_every_steps
                                == 0 and net.iteration < target_step)
                    if (due_check or due_ckpt) and self._pending_scores:
                        # every score up to here is verified finite
                        # BEFORE a snapshot is taken: poison is never
                        # checkpointed, even with a lagging
                        # (nan_check_every > 1) sentinel
                        bad = self._agreed_bad()
                        if bad is not None:
                            rollbacks += 1
                            self._rollback(bad[0], bad[1], rollbacks)
                            continue
                    if due_ckpt:
                        self._checkpoint(net.iteration, "periodic")

                if status == "preempted":
                    bad = self._agreed_bad()
                    if bad is not None:
                        # never checkpoint poison, even on the way out
                        rollbacks += 1
                        self._rollback(bad[0], bad[1], rollbacks)
                    self._checkpoint(net.iteration, "preemption",
                                     wait=True)
                    self._emit("preempt", net.iteration,
                               f"clean exit at step {net.iteration} of "
                               f"{target_step}", counter="preemptions")
                    self._flight_flush("preemption")
                else:
                    self._drain_checkpoint()  # settle _last_good first
                    if self._last_good != self._step_dir(net.iteration):
                        self._checkpoint(net.iteration, "final", wait=True)
            except _dist.PeerLostError as e:
                # a peer died mid-run: flush the post-mortem, write NO
                # partial checkpoint (any save barrier would hang on the
                # corpse; the meta.json invariant keeps half-saves
                # non-restorable), exit with a distinct status for the
                # launcher
                status = "peer_lost"
                self._on_peer_lost(e)
        finally:
            if use_signal:
                signal.signal(signal.SIGTERM, old_handler)
            # exit barrier: when an exception is already propagating the
            # writer's own error must not mask it — join + log only. On
            # clean paths the writer was drained above (wait=True saves),
            # so this is a no-op.
            self._drain_checkpoint(raise_errors=False)
            if sys.exc_info()[0] is not None:
                # exception path: still close the ledger (end_run is
                # idempotent, so the clean-path call below stays a no-op)
                # and flush the black box — THE post-mortem artifact
                self._flight_flush("exception", exc=sys.exc_info()[1])
                _goodput.end_run(ledger, status="failed")

        report = _goodput.end_run(
            ledger, status=status, save_to=self._report_path())
        return SupervisorResult(
            status=status, final_step=net.iteration,
            resumed_from=resumed_from, events=list(self.events),
            stats=self.stats.snapshot(), report=report,
            peer_loss=self.peer_loss)

    def _report_path(self) -> str:
        """``run_report.json`` — rank-suffixed (``run_report.r<k>.json``)
        off rank 0, so N processes sharing one checkpoint dir stop
        clobbering each other's reports."""
        from deeplearning4j_tpu.observability.distributed import rank_suffix
        return os.path.join(self.config.checkpoint_dir,
                            f"run_report{rank_suffix()}.json")

    # ------------------------------------------------------- pipeline loop
    def fit_pipeline(self, pipeline, *, epochs: int = 1) -> SupervisorResult:
        """Supervise training over a ``datapipe.Pipeline`` — the
        streaming-source twin of :meth:`run`. The pipeline's
        ``state_dict()`` rides in every checkpoint's ``meta.json``
        (captured at the same step boundary as the device snapshot), so
        resume and NaN rollback restore DATA position — epoch, source
        cursor, shuffle RNG + window, partial batch buffers, prefetched
        batches — alongside the parameters: a killed-and-relaunched run
        consumes the exact record sequence an uninterrupted one would,
        and final params are bit-identical even from a shuffled or
        streaming source. Completion is data-driven (the stream runs out
        of epochs) rather than an absolute target step."""
        cfg = self.config
        net = self.net
        self._pipeline = pipeline
        resumed_from = None

        from deeplearning4j_tpu.parallel import distributed as _dist
        from deeplearning4j_tpu.utils.checkpoint import (
            find_latest_checkpoint)
        _obs_metrics.install_runtime_metrics()
        from deeplearning4j_tpu.compilecache import configure as _cc_configure
        _cc_configure(cfg.compile_cache_dir)  # falls back to env var
        self._setup_coordination()
        self.stats.attach_to_registry(
            labels={"job": os.path.basename(
                os.path.normpath(cfg.checkpoint_dir))})
        ledger = _goodput.start_run("resilient_fit", net=net)
        self._ledger = ledger

        if cfg.resume:
            latest = find_latest_checkpoint(cfg.checkpoint_dir)
            if latest is not None:
                with _get_tracer().span("restore"):
                    self._load_into(latest)
                self._emit("resume", net.iteration,
                           f"restored {latest} (datapipe epoch "
                           f"{pipeline.epoch})", counter="resumes")
                resumed_from = latest

        old_handler = None
        use_signal = (cfg.handle_sigterm
                      and threading.current_thread()
                      is threading.main_thread())
        if use_signal:
            old_handler = signal.signal(signal.SIGTERM, self._sigterm)
        stream = None

        def invalidate_stream():
            # close the live generator chain FIRST (stops prefetch
            # workers mid-pull) so a restore never races a worker still
            # mutating upstream stage state
            nonlocal stream
            if stream is not None:
                stream.close()
                stream = None

        rollbacks = 0
        status = "completed"
        try:
            try:
                if self._last_good is None:
                    # baseline save: rollback target from the very first
                    # step, now including the pipeline's start-of-run
                    # state
                    self._checkpoint(net.iteration, "baseline")

                while True:
                    if self._coordinated:
                        # preemption consensus BEFORE pulling a batch:
                        # the final checkpoint's data cursor must not
                        # have consumed a record nobody trained on
                        if self._any_process(self._preempt_requested,
                                             "preempt"):
                            self._preempt_requested = True
                    if self._preempt_requested:
                        status = "preempted"
                        break
                    if stream is None:
                        stream = pipeline.stream(epochs)
                    ds = next(stream, None)
                    if self._coordinated:
                        # epoch-end is a fleet decision: the first shard
                        # to run dry ends the epoch everywhere (peers
                        # drop their surplus — mirroring LocalSGD's
                        # windowed agreement), because a lone finisher
                        # heading for the exit barrier while others keep
                        # training is a deadlock
                        exhausted = self._any_process(ds is None, "data")
                    else:
                        exhausted = ds is None
                    if exhausted:
                        # stream exhausted — but the lazy-score tail may
                        # hold poison; a rollback rewinds data position
                        # too and re-enters the loop with a rebuilt
                        # stream
                        bad = self._agreed_bad()
                        if bad is not None:
                            rollbacks += 1
                            invalidate_stream()
                            self._rollback(bad[0], bad[1], rollbacks)
                            continue
                        break
                    step = net.iteration
                    score = self._attempt_step(ds, step)
                    if cfg.nan_check_every > 0:
                        self._pending_scores.append((step, score))
                    due_check = (cfg.nan_check_every > 0
                                 and net.iteration % cfg.nan_check_every
                                 == 0)
                    due_ckpt = (net.iteration % cfg.checkpoint_every_steps
                                == 0)
                    if (due_check or due_ckpt) and self._pending_scores:
                        bad = self._agreed_bad()
                        if bad is not None:
                            rollbacks += 1
                            invalidate_stream()
                            self._rollback(bad[0], bad[1], rollbacks)
                            continue
                    if due_ckpt:
                        self._checkpoint(net.iteration, "periodic")

                if status == "preempted":
                    bad = self._agreed_bad()
                    if bad is not None:
                        rollbacks += 1
                        invalidate_stream()
                        self._rollback(bad[0], bad[1], rollbacks)
                    # park the prefetch workers so the saved pipeline
                    # state is the final word on data position
                    invalidate_stream()
                    self._checkpoint(net.iteration, "preemption",
                                     wait=True)
                    self._emit("preempt", net.iteration,
                               f"clean exit at step {net.iteration} "
                               f"(datapipe epoch {pipeline.epoch} of "
                               f"{epochs})", counter="preemptions")
                    self._flight_flush("preemption")
                else:
                    self._drain_checkpoint()  # settle _last_good first
                    if self._last_good != self._step_dir(net.iteration):
                        self._checkpoint(net.iteration, "final", wait=True)
            except _dist.PeerLostError as e:
                status = "peer_lost"
                self._on_peer_lost(e)
        finally:
            if use_signal:
                signal.signal(signal.SIGTERM, old_handler)
            invalidate_stream()
            # the pipeline reports only while consumed: detach its
            # collector so back-to-back runs over fresh pipeline objects
            # don't accumulate stale families in the global registry
            pipeline.stats.detach_from_registry()
            self._drain_checkpoint(raise_errors=False)
            if sys.exc_info()[0] is not None:
                self._flight_flush("exception", exc=sys.exc_info()[1])
                _goodput.end_run(ledger, status="failed")

        report = _goodput.end_run(
            ledger, status=status, save_to=self._report_path())
        return SupervisorResult(
            status=status, final_step=net.iteration,
            resumed_from=resumed_from, events=list(self.events),
            stats=self.stats.snapshot(), report=report,
            peer_loss=self.peer_loss)

    # ----------------------------------------------------------- fit facade
    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 32) -> SupervisorResult:
        """The ``fit``-shaped entry: materializes the batch sequence and
        supervises to the absolute step ``epochs * len(batches)`` —
        absolute so a killed-and-relaunched run lands on the SAME final
        step count as an uninterrupted one. A ``datapipe.Pipeline``
        dispatches to :meth:`fit_pipeline` instead (streaming, never
        materialized; data position checkpointed)."""
        from deeplearning4j_tpu.datapipe.core import Pipeline
        if isinstance(data, Pipeline):
            return self.fit_pipeline(data, epochs=epochs)
        batches = _materialize_batches(data, labels, batch_size)
        if not batches:
            raise ValueError("no training batches")
        target = epochs * len(batches)
        return self.run(lambda step: batches[step % len(batches)], target)


def _materialize_batches(data, labels, batch_size):
    """(data, labels) | DataSet | MultiDataSet | iterator -> list of
    batches. Materialized so batch_fn(step) is deterministic across
    restarts (resumability beats streaming here; for out-of-core data
    pass a deterministic batch_fn to run() directly)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
    from deeplearning4j_tpu.datasets.iterator import (ArrayDataSetIterator,
                                                      DataSetIterator)
    if isinstance(data, (DataSet, MultiDataSet)):
        return [data]
    if isinstance(data, DataSetIterator):
        batches = list(data)
        data.reset()
        return batches
    return list(ArrayDataSetIterator(data, labels, batch_size=batch_size))


def resilient_fit(net, data, labels=None, *, checkpoint_dir: str,
                  epochs: int = 1, batch_size: int = 32, injector=None,
                  stats_collector=None, **config_kw) -> SupervisorResult:
    """One-call supervised training: ``resilient_fit(net, x, y,
    checkpoint_dir=...)`` trains with checkpoint/resume, retry, NaN
    rollback and preemption handling. ``config_kw`` feeds
    SupervisorConfig (checkpoint_every_steps, keep_checkpoints, ...)."""
    cfg = SupervisorConfig(checkpoint_dir=checkpoint_dir, **config_kw)
    sup = TrainingSupervisor(net, cfg, injector=injector,
                             stats_collector=stats_collector)
    return sup.fit(data, labels, epochs=epochs, batch_size=batch_size)
