"""Elastic fleet launcher: spawn, watch, and relaunch a coordinated
multi-process training fleet — shrinking it when workers die.

The single-process supervisor (``supervisor.py``) survives faults
*inside* one process. This module owns the layer above: a parent that
spawns ``N`` coordinator-addressed worker processes, watches their
exits, and — when the fleet fails — relaunches it at a (possibly
smaller) size so training resumes from the last fleet-wide checkpoint
via the elastic reshard path (``utils/checkpoint.restore_*`` +
``datapipe/reshard.remap_for``).

Division of labour on a worker death:

- the **dead** worker leaves nothing behind (no partial checkpoint —
  the barriered meta commit in ``utils/checkpoint.py`` guarantees the
  last *complete* checkpoint is the newest restorable one);
- each **surviving** worker detects the loss as a consensus timeout
  (``parallel.distributed.PeerLostError``), flushes a ``peer_lost``
  flight record, and exits with :data:`PEER_LOST_EXIT` — it does NOT
  attempt a solo checkpoint, which would fork history;
- the **launcher** (this module) observes the non-zero exits, gives
  stragglers a short grace window to notice the loss themselves, kills
  any that don't, then relaunches the fleet at
  ``max(min_size, size // 2)`` with a fresh coordinator port and a
  bumped ``DL4J_TPU_INCARNATION`` (so consensus keys from the dead
  incarnation can never collide with the new one).

Per-worker environment (set on top of the parent's):

- ``DL4J_TPU_RUN_ID`` — one id for the whole fleet across relaunches,
  so observability artifacts correlate;
- ``DL4J_TPU_INSTANCE=worker-<rank>`` — per-member identity;
- ``DL4J_TPU_INCARNATION=<launch index>`` — consensus key namespace;
- ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` — informational mirrors of
  the argv coordinates (workers still call ``initialize()`` explicitly);
- ``XLA_FLAGS=--xla_force_host_platform_device_count=K`` when
  ``total_devices`` is set — the launcher keeps the *global* device
  count constant across shrinks (``K = total_devices // size``) so a
  resumed smaller fleet sees the same mesh axis size and restores the
  old layout via the elastic resharding path bit-identically;
- ``DL4J_TPU_COMPILE_CACHE`` when ``compile_cache_dir`` is set — the
  fleet shares one persistent XLA compile cache (the shared-dir
  backend, compilecache/cache.py), so only the first worker ever pays
  a fresh compile and relaunched workers boot warm.

The launcher itself never imports jax: worker argv construction is
delegated to a ``build_argv(size, rank, coordinator)`` callable, so the
monitoring/relaunch logic is unit-testable with plain ``python -c``
workers (see ``tests/test_crossproc.py``). The end-to-end drill with
real jax workers is ``scripts/chaos_multihost.py``.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

__all__ = [
    "PEER_LOST_EXIT",
    "WorkerRecord",
    "LaunchRecord",
    "FleetResult",
    "FleetLauncher",
    "free_port",
]

logger = logging.getLogger(__name__)

#: exit status a worker uses when it detected a LOST PEER (consensus
#: timeout) and shut down cleanly without checkpointing. Distinct from
#: a generic failure so the launcher (and operators reading logs) can
#: tell "I died" from "somebody else died and I noticed".
PEER_LOST_EXIT = 43


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (the usual bind-then-close race is
    fine here: each launch gets a fresh port, collisions just fail the
    launch and the next relaunch picks another)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


@dataclass
class WorkerRecord:
    """One worker process within one launch."""
    rank: int
    pid: int
    returncode: Optional[int] = None
    duration_s: Optional[float] = None
    #: True when the launcher had to SIGKILL it (straggler past grace)
    killed: bool = False

    @property
    def peer_lost(self) -> bool:
        return self.returncode == PEER_LOST_EXIT


@dataclass
class LaunchRecord:
    """One spawn-to-exit cycle of the whole fleet."""
    index: int                  # launch number == DL4J_TPU_INCARNATION
    size: int
    coordinator: str
    workers: List[WorkerRecord] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return bool(self.workers) and all(
            w.returncode == 0 for w in self.workers)

    @property
    def failed_ranks(self) -> List[int]:
        return [w.rank for w in self.workers if w.returncode != 0]

    @property
    def peer_lost_ranks(self) -> List[int]:
        return [w.rank for w in self.workers if w.peer_lost]


@dataclass
class FleetResult:
    """Outcome of :meth:`FleetLauncher.run`."""
    status: str                 # "completed" | "failed"
    final_size: int
    launches: List[LaunchRecord]

    @property
    def relaunches(self) -> int:
        return max(0, len(self.launches) - 1)


class FleetLauncher:
    """Spawn ``size`` coordinated workers, monitor them, and relaunch
    (shrunk) on failure.

    ``build_argv(size, rank, coordinator)`` returns the argv for one
    worker; everything else — ports, env, monitoring, shrink policy —
    is the launcher's job.
    """

    def __init__(self, build_argv: Callable[[int, int, str], List[str]],
                 *,
                 min_size: int = 1,
                 max_launches: int = 8,
                 shrink_on_failure: bool = True,
                 straggler_grace_s: float = 30.0,
                 launch_timeout_s: float = 600.0,
                 poll_interval_s: float = 0.05,
                 total_devices: Optional[int] = None,
                 host: str = "127.0.0.1",
                 run_id: Optional[str] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None,
                 log_dir: Optional[str] = None,
                 compile_cache_dir: Optional[str] = None):
        self.build_argv = build_argv
        self.min_size = max(1, int(min_size))
        self.max_launches = int(max_launches)
        self.shrink_on_failure = bool(shrink_on_failure)
        self.straggler_grace_s = float(straggler_grace_s)
        self.launch_timeout_s = float(launch_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.total_devices = total_devices
        self.host = host
        self.run_id = run_id or f"fleet-{os.getpid()}-{int(time.time())}"
        self.extra_env = dict(extra_env or {})
        self.cwd = cwd
        self.log_dir = log_dir
        self.compile_cache_dir = compile_cache_dir

    # ------------------------------------------------------------- env
    def _worker_env(self, size: int, rank: int, launch_index: int) -> dict:
        env = dict(os.environ)
        env.update(self.extra_env)
        env["DL4J_TPU_RUN_ID"] = self.run_id
        env["DL4J_TPU_INSTANCE"] = f"worker-{rank}"
        env["DL4J_TPU_INCARNATION"] = str(launch_index)
        env["JAX_NUM_PROCESSES"] = str(size)
        env["JAX_PROCESS_ID"] = str(rank)
        if self.compile_cache_dir:
            # the whole fleet shares ONE persistent compile cache
            # (compilecache/cache.py shared-dir backend): worker 0's
            # compiles are every later worker's — and every RELAUNCHED
            # worker's — cache hits
            env["DL4J_TPU_COMPILE_CACHE"] = self.compile_cache_dir
        if self.total_devices:
            if self.total_devices % size:
                raise ValueError(
                    f"total_devices={self.total_devices} not divisible "
                    f"by fleet size {size}")
            env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                                f"{self.total_devices // size}")
        return env

    # ----------------------------------------------------------- launch
    def launch_once(self, size: int, launch_index: int = 0) -> LaunchRecord:
        """One spawn-to-exit cycle: start ``size`` workers against a
        fresh coordinator port, wait for all of them, killing stragglers
        once the grace window after the first failure expires."""
        size = int(size)
        coord = f"{self.host}:{free_port(self.host)}"
        rec = LaunchRecord(index=launch_index, size=size, coordinator=coord)
        logger.info("fleet launch %d: %d worker(s), coordinator %s",
                    launch_index, size, coord)

        procs: List[subprocess.Popen] = []
        logs = []
        start = time.monotonic()
        try:
            for rank in range(size):
                argv = self.build_argv(size, rank, coord)
                out = None
                if self.log_dir:
                    os.makedirs(self.log_dir, exist_ok=True)
                    out = open(os.path.join(
                        self.log_dir,
                        f"worker-l{launch_index}-r{rank}.log"), "wb")
                    logs.append(out)
                procs.append(subprocess.Popen(
                    argv, env=self._worker_env(size, rank, launch_index),
                    cwd=self.cwd, stdout=out,
                    stderr=subprocess.STDOUT if out else None))
                rec.workers.append(WorkerRecord(rank=rank,
                                                pid=procs[-1].pid))

            self._monitor(procs, rec, start)
        finally:
            for fh in logs:
                fh.close()
        dur = time.monotonic() - start
        logger.info("fleet launch %d finished in %.1fs: codes %s%s",
                    launch_index, dur,
                    [w.returncode for w in rec.workers],
                    (f" (peer_lost on ranks {rec.peer_lost_ranks})"
                     if rec.peer_lost_ranks else ""))
        return rec

    def _monitor(self, procs, rec: LaunchRecord, start: float) -> None:
        grace_deadline = None
        hard_deadline = start + self.launch_timeout_s
        while True:
            now = time.monotonic()
            alive = False
            for proc, w in zip(procs, rec.workers):
                if w.returncode is not None:
                    continue
                code = proc.poll()
                if code is None:
                    alive = True
                    continue
                w.returncode = code
                w.duration_s = now - start
                if code != 0 and grace_deadline is None:
                    # first casualty: peers get a grace window to detect
                    # the loss via consensus timeout and exit themselves
                    # (with PEER_LOST_EXIT) before we resort to SIGKILL
                    grace_deadline = now + self.straggler_grace_s
                    logger.warning(
                        "worker rank %d exited %d; giving peers %.1fs "
                        "to detect the loss", w.rank, code,
                        self.straggler_grace_s)
            if not alive:
                return
            past_grace = grace_deadline is not None and now > grace_deadline
            if past_grace or now > hard_deadline:
                for proc, w in zip(procs, rec.workers):
                    if w.returncode is None and proc.poll() is None:
                        logger.error(
                            "killing straggler rank %d (pid %d)",
                            w.rank, proc.pid)
                        proc.kill()
                        proc.wait()
                        w.returncode = proc.returncode
                        w.duration_s = time.monotonic() - start
                        w.killed = True
                return
            time.sleep(self.poll_interval_s)

    # -------------------------------------------------------------- run
    def next_size(self, size: int) -> int:
        """The fleet size after a failed launch at ``size``."""
        if not self.shrink_on_failure:
            return size
        return max(self.min_size, size // 2)

    def run(self, initial_size: int) -> FleetResult:
        """Launch the fleet and keep relaunching (shrunk on failure)
        until a launch completes cleanly or ``max_launches`` is spent.
        Workers are expected to resume from the shared checkpoint dir
        themselves (``SupervisorConfig.resume=True`` + elastic reshard
        restore), so each relaunch continues rather than restarts."""
        size = max(self.min_size, int(initial_size))
        launches: List[LaunchRecord] = []
        for index in range(self.max_launches):
            rec = self.launch_once(size, launch_index=index)
            launches.append(rec)
            if rec.ok:
                return FleetResult(status="completed", final_size=size,
                                   launches=launches)
            new_size = self.next_size(size)
            logger.warning(
                "fleet launch %d failed (ranks %s); relaunching at "
                "size %d", index, rec.failed_ranks, new_size)
            size = new_size
        return FleetResult(status="failed", final_size=size,
                           launches=launches)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m deeplearning4j_tpu.resilience.launcher -n 2 -- CMD``
    — run ``CMD`` as each worker, with ``{size}``, ``{rank}`` and
    ``{coordinator}`` placeholders substituted in its arguments."""
    import argparse
    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("-n", "--size", type=int, default=2)
    ap.add_argument("--min-size", type=int, default=1)
    ap.add_argument("--max-launches", type=int, default=8)
    ap.add_argument("--total-devices", type=int, default=None)
    ap.add_argument("--compile-cache-dir", default=None,
                    help="shared persistent XLA compile cache dir "
                         "exported to every worker as "
                         "DL4J_TPU_COMPILE_CACHE")
    ap.add_argument("--grace", type=float, default=30.0)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command (after --)")
    args = ap.parse_args(argv)
    cmd = [c for c in args.cmd if c != "--"]
    if not cmd:
        ap.error("no worker command given (put it after --)")

    def build_argv(size, rank, coordinator):
        subs = {"size": size, "rank": rank, "coordinator": coordinator}
        return [c.format(**subs) for c in cmd]

    logging.basicConfig(level=logging.INFO,
                        format="%(levelname)s %(name)s: %(message)s")
    result = FleetLauncher(
        build_argv, min_size=args.min_size,
        max_launches=args.max_launches, total_devices=args.total_devices,
        compile_cache_dir=args.compile_cache_dir,
        straggler_grace_s=args.grace).run(args.size)
    print(f"[launcher] {result.status} after {len(result.launches)} "
          f"launch(es), final size {result.final_size}")
    return 0 if result.status == "completed" else 1


if __name__ == "__main__":
    sys.exit(main())
