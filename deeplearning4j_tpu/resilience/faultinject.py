"""Deterministic fault injection for the resilience runtime.

Every recovery path the TrainingSupervisor implements is exercised by
tests through this harness rather than hoped for:

- :meth:`FaultInjector.crash_during_save` — raise :class:`InjectedCrash`
  between the orbax tree commit and the ``meta.json`` rename (the
  ``_POST_COMMIT_HOOK`` seam in utils/checkpoint.py), leaving exactly
  the partial-save footprint a real preemption leaves.
- :meth:`FaultInjector.fail_step` — raise :class:`TransientStepError`
  the first *times* attempts of a given step (exercises
  retry-with-backoff).
- :meth:`FaultInjector.poison_step` — overwrite one parameter leaf with
  NaN before a given step, so the fused step produces a non-finite loss
  (exercises the sentinel rollback + LR backoff).
- :meth:`FaultInjector.preempt_at_step` — request a clean preemption at
  a step boundary (exercises the SIGTERM path without relying on signal
  delivery timing); :meth:`sigterm_at_step` delivers a real SIGTERM to
  the process instead.
- :meth:`FaultInjector.kill_at_step` / :meth:`hang_at_step` — REAL
  process death (SIGKILL: no handlers, no cleanup) and a stall longer
  than the collective timeout; with ``rank=`` these target one fleet
  member, which is how scripts/chaos_multihost.py murders a single
  worker mid-epoch and asserts the survivors detect the loss.

Every planner accepts ``rank=`` (default None = every process): the
fault fires only on the process whose ``jax.process_index()`` matches,
so one shared fault plan — constructed identically on every worker —
expresses "kill rank 1 at step 5" without per-process branching.

Faults are keyed by absolute step / save index, so a plan replays
identically across process restarts — scripts/chaos_train.py relies on
that to assert a chaos run converges to the uninterrupted run's exact
parameters.
"""

from __future__ import annotations

import signal as _signal
from contextlib import contextmanager

import numpy as np


def _on_this_rank(rank) -> bool:
    """True when a fault planned for ``rank`` should fire here (None =
    everywhere). Outside a jax runtime, rank 0 is assumed."""
    if rank is None:
        return True
    try:
        import jax
        return jax.process_index() == int(rank)
    except Exception:
        return int(rank) == 0


class InjectedCrash(BaseException):
    """Simulated process death. Deliberately a BaseException: nothing in
    the supervisor (or any library ``except Exception``) may swallow it,
    exactly like a real SIGKILL."""


class TransientStepError(RuntimeError):
    """A step failure worth retrying (the injected stand-in for flaky
    device/runtime errors)."""


class FaultInjector:
    """A deterministic fault plan. Plan with the ``*_at``/``*_step``
    methods, pass the injector to the TrainingSupervisor, and wrap the
    run in :meth:`installed` when the plan includes save crashes (that
    arms the checkpoint post-commit hook)."""

    def __init__(self):
        self._step_failures = {}      # step -> [remaining raises, rank]
        self._poison_steps = {}       # step -> [remaining poisons, rank]
        self._preempt_steps = {}      # step -> rank (clean preemption)
        self._sigterm_steps = {}      # step -> rank (real SIGTERM)
        self._kill_steps = {}         # step -> (rank, signal)
        self._hang_steps = {}         # step -> (seconds, rank)
        self._crash_saves = set()     # save index -> crash post-commit
        self._save_index = 0
        self.log: list[tuple] = []    # (fault, step/index) actually fired

    # ------------------------------------------------------------- planning
    def fail_step(self, step: int, times: int = 1, rank=None):
        """Raise TransientStepError on the first ``times`` attempts of
        ``step`` (attempt times+1 then succeeds — retry fodder). With
        ``rank=k`` only process k raises (its peers must still back off
        with it — the coordinated-retry path)."""
        self._step_failures[int(step)] = [int(times), rank]
        return self

    def poison_step(self, step: int, times: int = 1, rank=None):
        """Before ``step`` (its first ``times`` attempts), set one
        parameter leaf to NaN — the fused step then yields a non-finite
        loss, like a gradient blow-up or corrupted device buffer. With
        ``rank=k`` only process k is poisoned (its peers must still roll
        back with it in lockstep)."""
        self._poison_steps[int(step)] = [int(times), rank]
        return self

    def preempt_at_step(self, step: int, rank=None):
        """Request a clean preemption once ``step`` is reached (the
        supervisor finishes the in-flight step, checkpoints, exits).
        With ``rank=k`` the request lands on one process; consensus
        broadcasts it fleet-wide."""
        self._preempt_steps[int(step)] = rank
        return self

    def sigterm_at_step(self, step: int, rank=None):
        """Deliver a real SIGTERM to this process at ``step`` — the
        supervisor's installed handler must turn it into a clean
        checkpoint-and-exit."""
        self._sigterm_steps[int(step)] = rank
        return self

    def kill_at_step(self, step: int, rank=None, sig=_signal.SIGKILL):
        """REAL process death at ``step``: SIGKILL (default) gives no
        handler a chance — exactly the footprint of an OOM-killed or
        hard-preempted fleet member. Fires at the step boundary (before
        the step's collective), so surviving peers detect the loss as a
        consensus timeout, not a wedged psum."""
        self._kill_steps[int(step)] = (rank, sig)
        return self

    def hang_at_step(self, step: int, seconds: float, rank=None):
        """Stall this process ``seconds`` at ``step`` — longer than the
        collective timeout, a hang is indistinguishable from death to
        the peers (and the hung process finds them gone when it wakes)."""
        self._hang_steps[int(step)] = (float(seconds), rank)
        return self

    def crash_during_save(self, save_index: int):
        """Crash the ``save_index``-th checkpoint save (0-based, counted
        while :meth:`installed` is active) between the tree commit and
        the meta.json rename — the window that yields a partial save."""
        self._crash_saves.add(int(save_index))
        return self

    # ------------------------------------------------------ checkpoint seam
    @contextmanager
    def installed(self):
        """Arm the utils/checkpoint.py post-commit hook for the duration
        of the block (save-crash faults only fire while armed)."""
        from deeplearning4j_tpu.utils import checkpoint
        prev = checkpoint._POST_COMMIT_HOOK
        checkpoint._POST_COMMIT_HOOK = self._post_commit
        try:
            yield self
        finally:
            checkpoint._POST_COMMIT_HOOK = prev

    def _post_commit(self, path: str):
        idx = self._save_index
        self._save_index += 1
        if idx in self._crash_saves:
            self._crash_saves.discard(idx)
            self.log.append(("crash_save", idx))
            raise InjectedCrash(
                f"injected crash between tree commit and meta rename "
                f"(save #{idx}, {path})")

    # -------------------------------------------------------- step-time hook
    def before_step(self, supervisor, net, step: int):
        """Called by the supervisor inside the retried region, once per
        attempt of ``step``. Rank-targeted faults fire only on their
        process; the plan itself is identical everywhere."""
        if step in self._hang_steps:
            seconds, rank = self._hang_steps.pop(step)
            if _on_this_rank(rank):
                self.log.append(("hang", step))
                import time
                time.sleep(seconds)
        if step in self._kill_steps:
            rank, sig = self._kill_steps.pop(step)
            if _on_this_rank(rank):
                self.log.append(("kill", step))
                import os
                os.kill(os.getpid(), sig)
        if step in self._sigterm_steps:
            rank = self._sigterm_steps.pop(step)
            if _on_this_rank(rank):
                self.log.append(("sigterm", step))
                import os
                os.kill(os.getpid(), _signal.SIGTERM)
        if step in self._preempt_steps:
            rank = self._preempt_steps.pop(step)
            if _on_this_rank(rank):
                self.log.append(("preempt", step))
                supervisor.request_preemption()
        poison = self._poison_steps.get(step)
        if poison is not None and poison[0] > 0:
            poison[0] -= 1
            if _on_this_rank(poison[1]):
                self.log.append(("poison", step))
                _poison_params(net)
        fail = self._step_failures.get(step)
        if fail is not None and fail[0] > 0:
            fail[0] -= 1
            if _on_this_rank(fail[1]):
                self.log.append(("transient", step))
                raise TransientStepError(f"injected transient failure at "
                                         f"step {step}")


def _poison_params(net):
    """NaN one parameter leaf in place (first layer, first tensor)."""
    import jax.numpy as jnp
    params = dict(net.params)
    name = next(iter(params))
    sub = dict(params[name])
    key = next(iter(sub))
    sub[key] = jnp.full_like(sub[key], jnp.nan)
    params[name] = sub
    net.params = params
