"""Deterministic fault injection for the resilience runtime.

Every recovery path the TrainingSupervisor implements is exercised by
tests through this harness rather than hoped for:

- :meth:`FaultInjector.crash_during_save` — raise :class:`InjectedCrash`
  between the orbax tree commit and the ``meta.json`` rename (the
  ``_POST_COMMIT_HOOK`` seam in utils/checkpoint.py), leaving exactly
  the partial-save footprint a real preemption leaves.
- :meth:`FaultInjector.fail_step` — raise :class:`TransientStepError`
  the first *times* attempts of a given step (exercises
  retry-with-backoff).
- :meth:`FaultInjector.poison_step` — overwrite one parameter leaf with
  NaN before a given step, so the fused step produces a non-finite loss
  (exercises the sentinel rollback + LR backoff).
- :meth:`FaultInjector.preempt_at_step` — request a clean preemption at
  a step boundary (exercises the SIGTERM path without relying on signal
  delivery timing); :meth:`sigterm_at_step` delivers a real SIGTERM to
  the process instead.

Faults are keyed by absolute step / save index, so a plan replays
identically across process restarts — scripts/chaos_train.py relies on
that to assert a chaos run converges to the uninterrupted run's exact
parameters.
"""

from __future__ import annotations

from contextlib import contextmanager

import numpy as np


class InjectedCrash(BaseException):
    """Simulated process death. Deliberately a BaseException: nothing in
    the supervisor (or any library ``except Exception``) may swallow it,
    exactly like a real SIGKILL."""


class TransientStepError(RuntimeError):
    """A step failure worth retrying (the injected stand-in for flaky
    device/runtime errors)."""


class FaultInjector:
    """A deterministic fault plan. Plan with the ``*_at``/``*_step``
    methods, pass the injector to the TrainingSupervisor, and wrap the
    run in :meth:`installed` when the plan includes save crashes (that
    arms the checkpoint post-commit hook)."""

    def __init__(self):
        self._step_failures = {}      # step -> remaining raise count
        self._poison_steps = {}       # step -> remaining poison count
        self._preempt_steps = set()   # clean preemption request
        self._sigterm_steps = set()   # real SIGTERM delivery
        self._crash_saves = set()     # save index -> crash post-commit
        self._save_index = 0
        self.log: list[tuple] = []    # (fault, step/index) actually fired

    # ------------------------------------------------------------- planning
    def fail_step(self, step: int, times: int = 1,):
        """Raise TransientStepError on the first ``times`` attempts of
        ``step`` (attempt times+1 then succeeds — retry fodder)."""
        self._step_failures[int(step)] = int(times)
        return self

    def poison_step(self, step: int, times: int = 1):
        """Before ``step`` (its first ``times`` attempts), set one
        parameter leaf to NaN — the fused step then yields a non-finite
        loss, like a gradient blow-up or corrupted device buffer."""
        self._poison_steps[int(step)] = int(times)
        return self

    def preempt_at_step(self, step: int):
        """Request a clean preemption once ``step`` is reached (the
        supervisor finishes the in-flight step, checkpoints, exits)."""
        self._preempt_steps.add(int(step))
        return self

    def sigterm_at_step(self, step: int):
        """Deliver a real SIGTERM to this process at ``step`` — the
        supervisor's installed handler must turn it into a clean
        checkpoint-and-exit."""
        self._sigterm_steps.add(int(step))
        return self

    def crash_during_save(self, save_index: int):
        """Crash the ``save_index``-th checkpoint save (0-based, counted
        while :meth:`installed` is active) between the tree commit and
        the meta.json rename — the window that yields a partial save."""
        self._crash_saves.add(int(save_index))
        return self

    # ------------------------------------------------------ checkpoint seam
    @contextmanager
    def installed(self):
        """Arm the utils/checkpoint.py post-commit hook for the duration
        of the block (save-crash faults only fire while armed)."""
        from deeplearning4j_tpu.utils import checkpoint
        prev = checkpoint._POST_COMMIT_HOOK
        checkpoint._POST_COMMIT_HOOK = self._post_commit
        try:
            yield self
        finally:
            checkpoint._POST_COMMIT_HOOK = prev

    def _post_commit(self, path: str):
        idx = self._save_index
        self._save_index += 1
        if idx in self._crash_saves:
            self._crash_saves.discard(idx)
            self.log.append(("crash_save", idx))
            raise InjectedCrash(
                f"injected crash between tree commit and meta rename "
                f"(save #{idx}, {path})")

    # -------------------------------------------------------- step-time hook
    def before_step(self, supervisor, net, step: int):
        """Called by the supervisor inside the retried region, once per
        attempt of ``step``."""
        if step in self._sigterm_steps:
            self._sigterm_steps.discard(step)
            self.log.append(("sigterm", step))
            import os
            import signal
            os.kill(os.getpid(), signal.SIGTERM)
        if step in self._preempt_steps:
            self._preempt_steps.discard(step)
            self.log.append(("preempt", step))
            supervisor.request_preemption()
        if self._poison_steps.get(step, 0) > 0:
            self._poison_steps[step] -= 1
            self.log.append(("poison", step))
            _poison_params(net)
        if self._step_failures.get(step, 0) > 0:
            self._step_failures[step] -= 1
            self.log.append(("transient", step))
            raise TransientStepError(f"injected transient failure at "
                                     f"step {step}")


def _poison_params(net):
    """NaN one parameter leaf in place (first layer, first tensor)."""
    import jax.numpy as jnp
    params = dict(net.params)
    name = next(iter(params))
    sub = dict(params[name])
    key = next(iter(sub))
    sub[key] = jnp.full_like(sub[key], jnp.nan)
    params[name] = sub
    net.params = params
