"""Fault-tolerant training runtime (RESILIENCE.md).

``TrainingSupervisor`` / ``resilient_fit`` wrap ``fit`` with periodic
checkpointing + atomic latest-pointer + retention GC, auto-resume from
the newest valid checkpoint, transient-step retry with exponential
backoff, a NaN/Inf rollback sentinel with learning-rate backoff, and
clean SIGTERM preemption. ``faultinject`` provides the deterministic
fault harness that keeps every one of those paths under test.
``launcher`` sits one layer up: it spawns and watches a coordinated
multi-process fleet and relaunches it (shrunk) when workers die, with
survivors detecting lost peers via consensus timeouts and exiting
``PEER_LOST_EXIT`` instead of checkpointing a forked history."""

from deeplearning4j_tpu.resilience.faultinject import (
    FaultInjector,
    InjectedCrash,
    TransientStepError,
)
from deeplearning4j_tpu.resilience.launcher import (
    PEER_LOST_EXIT,
    FleetLauncher,
    FleetResult,
    LaunchRecord,
    WorkerRecord,
)
from deeplearning4j_tpu.resilience.supervisor import (
    RecoveryEvent,
    ResilienceStats,
    SupervisorConfig,
    SupervisorResult,
    TrainingDivergedError,
    TrainingSupervisor,
    resilient_fit,
)

__all__ = [
    "FaultInjector",
    "FleetLauncher",
    "FleetResult",
    "InjectedCrash",
    "LaunchRecord",
    "PEER_LOST_EXIT",
    "RecoveryEvent",
    "ResilienceStats",
    "SupervisorConfig",
    "SupervisorResult",
    "TrainingDivergedError",
    "TrainingSupervisor",
    "TransientStepError",
    "WorkerRecord",
    "resilient_fit",
]
