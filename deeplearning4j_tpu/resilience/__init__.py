"""Fault-tolerant training runtime (RESILIENCE.md).

``TrainingSupervisor`` / ``resilient_fit`` wrap ``fit`` with periodic
checkpointing + atomic latest-pointer + retention GC, auto-resume from
the newest valid checkpoint, transient-step retry with exponential
backoff, a NaN/Inf rollback sentinel with learning-rate backoff, and
clean SIGTERM preemption. ``faultinject`` provides the deterministic
fault harness that keeps every one of those paths under test."""

from deeplearning4j_tpu.resilience.faultinject import (
    FaultInjector,
    InjectedCrash,
    TransientStepError,
)
from deeplearning4j_tpu.resilience.supervisor import (
    RecoveryEvent,
    ResilienceStats,
    SupervisorConfig,
    SupervisorResult,
    TrainingDivergedError,
    TrainingSupervisor,
    resilient_fit,
)

__all__ = [
    "FaultInjector",
    "InjectedCrash",
    "RecoveryEvent",
    "ResilienceStats",
    "SupervisorConfig",
    "SupervisorResult",
    "TrainingDivergedError",
    "TrainingSupervisor",
    "TransientStepError",
    "resilient_fit",
]
