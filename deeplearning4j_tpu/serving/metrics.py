"""Serving-side observability: counters, latency percentiles, and the
executed-batch-size histogram behind the ``/metrics`` endpoint.

One ``ServingStats`` instance is shared by the HTTP handlers (request
counting, per-request latency), the micro-batch dispatcher (executed
batches, coalesce accounting, queue depth) and the device runner
(compile count = number of distinct padded bucket shapes, the invariant
the bucket ladder exists to bound).

Everything is O(1) per event under one lock: latencies go into a
fixed-size ring (last ``window`` requests — serving dashboards want
recent percentiles, not since-boot averages), batch sizes into a dict
histogram keyed by the executed bucket.
"""

from __future__ import annotations

import threading
import time
from collections import deque

#: how far back the drain-rate estimate looks. Old enough to smooth
#: bucket-to-bucket jitter, young enough that a stall (device thread
#: wedged) pushes Retry-After to its ceiling within one horizon.
DRAIN_HORIZON_S = 30.0


class ServingStats:
    """Thread-safe serving counters + a recent-latency ring.

    The coalesce ratio — mean real rows per device forward — is the
    number that tells you whether cross-request batching is actually
    happening: 1.0 means every request paid its own forward (the seed
    lock-serialized behavior), ``max_batch`` means the dispatcher is
    saturating the bucket ladder.
    """

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self._window = int(window)
        self._lat = [0.0] * self._window   # seconds, ring buffer
        self._lat_n = 0                     # total ever recorded
        self.requests = 0                   # accepted /predict requests
        self.rows = 0                       # real (unpadded) rows served
        self.batches = 0                    # device forwards executed
        self.batch_rows = 0                 # real rows over those forwards
        self.batch_requests = 0             # tickets over those forwards
        self.rejected = 0                   # 503 admission rejections
        self.errors = 0                     # 400 request failures
        self.timeouts = 0                   # 504 per-request deadline expiries
        self.nan_rows = 0                   # replies with non-finite values
        self.batch_hist: dict[int, int] = {}  # executed bucket -> count
        # executed bucket -> cumulative device-forward seconds: the
        # measured per-bucket service times the trace autotuner fits
        # its service model to (compilecache.autotune)
        self.bucket_device_s: dict[int, float] = {}
        self.padded_rows = 0                # filler rows across forwards
        # unix time of the first successful reply — the cold-start
        # clock's far edge (cold_start_s = this minus process start)
        self.first_reply_unix: float | None = None
        self.queue_depth_fn = lambda: 0     # wired by the dispatcher
        # recent executed batches as (t, rows, tickets) — the observed
        # drain rate behind the derived Retry-After. _clock is
        # injectable so the retry math is pinnable in tests.
        self._clock = time.monotonic
        self._drain: deque = deque(maxlen=256)

    # ------------------------------------------------------------- recording
    def record_request(self, rows: int, latency_s: float):
        with self._lock:
            if self.first_reply_unix is None:
                self.first_reply_unix = time.time()
            self.requests += 1
            self.rows += int(rows)
            self._lat[self._lat_n % self._window] = float(latency_s)
            self._lat_n += 1

    def record_batch(self, bucket: int, rows: int, n_tickets: int,
                     device_s: float | None = None):
        with self._lock:
            self.batches += 1
            self.batch_rows += int(rows)
            self.batch_requests += int(n_tickets)
            self.padded_rows += max(0, int(bucket) - int(rows))
            self.batch_hist[int(bucket)] = self.batch_hist.get(int(bucket),
                                                               0) + 1
            if device_s is not None:
                self.bucket_device_s[int(bucket)] = (
                    self.bucket_device_s.get(int(bucket), 0.0)
                    + float(device_s))
            self._drain.append((self._clock(), int(rows), int(n_tickets)))

    # ------------------------------------------------------------ drain rate
    def _rates_locked(self):
        """(rows/s, tickets/s) over the recent horizon; (0, 0) until two
        distinct-time samples exist. Called with the lock held."""
        now = self._clock()
        pts = [p for p in self._drain if now - p[0] <= DRAIN_HORIZON_S]
        if not pts:
            return 0.0, 0.0
        span = now - pts[0][0]
        if span <= 0:
            return 0.0, 0.0
        return (sum(p[1] for p in pts) / span,
                sum(p[2] for p in pts) / span)

    def drain_rate(self) -> float:
        """Observed serving throughput, real rows/s over the recent
        horizon (0.0 until the window holds data)."""
        with self._lock:
            return self._rates_locked()[0]

    def retry_after_s(self, queue_tickets=None, lo: float = 0.05,
                      hi: float = 5.0) -> float:
        """Derived ``Retry-After`` for a 503: current backlog divided by
        the observed ticket drain rate, clamped to [lo, hi]. An idle
        queue answers ``lo`` (come right back); no observed drainage —
        cold start or a wedged device — answers ``hi`` (the honest
        worst case, since nothing is provably moving)."""
        if queue_tickets is None:
            queue_tickets = self.queue_depth_fn()
        if queue_tickets <= 0:
            return lo
        with self._lock:
            ticket_rate = self._rates_locked()[1]
        if ticket_rate <= 0:
            return hi
        return round(min(hi, max(lo, queue_tickets / ticket_rate)), 3)

    def record_rejected(self):
        with self._lock:
            self.rejected += 1

    def record_error(self):
        with self._lock:
            self.errors += 1

    def record_timeout(self):
        with self._lock:
            self.timeouts += 1

    def record_nan_rows(self, n: int = 1):
        """Rows whose reply carried a non-finite value — the serving
        twin of the supervisor's NaN sentinel, and a canary promotion
        gate (a freshly published version that starts emitting NaNs is
        rolled back before it leaves its traffic fraction)."""
        with self._lock:
            self.nan_rows += int(n)

    # ------------------------------------------------------------- reporting
    def _percentiles(self, lats, qs):
        if not lats:
            return {f"p{int(q * 100)}": None for q in qs}
        s = sorted(lats)
        out = {}
        for q in qs:
            # nearest-rank on the recent window — no interpolation noise
            i = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
            out[f"p{int(q * 100)}"] = round(s[i] * 1000.0, 3)
        return out

    def snapshot(self, shapes_seen=()) -> dict:
        """One JSON-ready dict — the ``/metrics`` payload."""
        with self._lock:
            n = min(self._lat_n, self._window)
            lats = self._lat[:n]
            batches = self.batches
            out = {
                "requests_total": self.requests,
                "rows_total": self.rows,
                "batches_total": batches,
                "rejected_total": self.rejected,
                "errors_total": self.errors,
                "timeouts_total": self.timeouts,
                "nan_rows_total": self.nan_rows,
                "queue_depth": int(self.queue_depth_fn()),
                "latency_ms": self._percentiles(lats, (0.50, 0.95, 0.99)),
                "latency_window": n,
                "batch_size_hist": {str(k): v for k, v in
                                    sorted(self.batch_hist.items())},
                # mean device-forward ms per executed bucket — the
                # measured service times the trace autotuner fits
                "device_ms_by_bucket": {
                    str(k): round(1000.0 * s / self.batch_hist[k], 3)
                    for k, s in sorted(self.bucket_device_s.items())
                    if self.batch_hist.get(k)},
                # real rows (and tickets) per device forward — the
                # cross-request coalescing signal
                "coalesce_rows_per_batch": (
                    round(self.batch_rows / batches, 3) if batches else None),
                "coalesce_requests_per_batch": (
                    round(self.batch_requests / batches, 3) if batches
                    else None),
                # filler rows the bucket ladder padded in, and their
                # share of every row that rode a device forward
                "padded_rows_total": self.padded_rows,
                "padding_waste_fraction": (
                    round(self.padded_rows
                          / (self.batch_rows + self.padded_rows), 4)
                    if self.batch_rows + self.padded_rows else None),
                "compile_count": len(shapes_seen),
                "shapes_seen": sorted(int(s) for s in shapes_seen),
                "drain_rate_rows_per_s": round(self._rates_locked()[0], 3),
            }
        # derived Retry-After the 503 path would answer right now
        out["retry_after_s"] = self.retry_after_s(out["queue_depth"])
        return out

    # ------------------------------------------- unified-registry bridge
    # The lock-guarded counters above stay the single source of truth
    # (snapshot() and its tests are untouched); the registry sees them
    # through a render-time collector, so Prometheus scrapes and the
    # JSON endpoint can never disagree.

    def metric_families(self, shapes_seen=(), labels=None):
        from deeplearning4j_tpu.observability.metrics import MetricFamily

        snap = self.snapshot(shapes_seen)
        L = dict(labels or {})
        fams = []

        def fam(name, kind, help, value, extra=None):
            fams.append(MetricFamily(name, kind, help)
                        .add(value, {**L, **(extra or {})}))

        fam("dl4j_serving_requests_total", "counter",
            "Accepted /predict requests", snap["requests_total"])
        fam("dl4j_serving_rows_total", "counter",
            "Real (unpadded) rows served", snap["rows_total"])
        fam("dl4j_serving_batches_total", "counter",
            "Device forwards executed", snap["batches_total"])
        fam("dl4j_serving_rejected_total", "counter",
            "503 admission rejections", snap["rejected_total"])
        fam("dl4j_serving_errors_total", "counter",
            "Request failures", snap["errors_total"])
        fam("dl4j_serving_timeouts_total", "counter",
            "504 per-request deadline expiries", snap["timeouts_total"])
        fam("dl4j_serving_nan_rows_total", "counter",
            "Reply rows carrying non-finite values (the serving NaN "
            "sentinel — a canary promotion gate)", snap["nan_rows_total"])
        fam("dl4j_serving_queue_depth", "gauge",
            "Tickets pending in the micro-batch queue",
            snap["queue_depth"])
        lat = MetricFamily(
            "dl4j_serving_latency_ms", "gauge",
            "Recent-window request latency percentiles (ms)")
        for q, v in snap["latency_ms"].items():
            if v is not None:
                lat.add(v, {**L, "quantile": q})
        if lat.samples:
            fams.append(lat)
        hist = MetricFamily(
            "dl4j_serving_batch_executions_total", "counter",
            "Device forwards by executed bucket size")
        for bucket, count in snap["batch_size_hist"].items():
            hist.add(count, {**L, "bucket": bucket})
        if hist.samples:
            fams.append(hist)
        if snap["coalesce_rows_per_batch"] is not None:
            fam("dl4j_serving_coalesce_rows_per_batch", "gauge",
                "Mean real rows per device forward (cross-request "
                "coalescing signal)", snap["coalesce_rows_per_batch"])
            fam("dl4j_serving_coalesce_requests_per_batch", "gauge",
                "Mean tickets per device forward",
                snap["coalesce_requests_per_batch"])
        fam("dl4j_serving_padded_rows_total", "counter",
            "Filler rows added by bucket-ladder padding",
            snap["padded_rows_total"])
        if snap["padding_waste_fraction"] is not None:
            fam("dl4j_serving_padding_waste_fraction", "gauge",
                "Padded rows over total rows through device forwards",
                snap["padding_waste_fraction"])
        fam("dl4j_serving_compiled_buckets", "gauge",
            "Distinct padded bucket shapes executed (XLA compile-cache "
            "footprint of the bucket ladder)", snap["compile_count"])
        fam("dl4j_serving_drain_rate_rows_per_s", "gauge",
            "Observed serving throughput over the recent horizon",
            snap["drain_rate_rows_per_s"])
        fam("dl4j_serving_retry_after_seconds", "gauge",
            "Derived Retry-After a 503 would answer now (backlog over "
            "observed drain rate, clamped)", snap["retry_after_s"])
        return fams

    def attach_to_registry(self, registry=None, *, labels=None,
                           shapes_fn=None):
        """Register a collector view of these stats on *registry*
        (default: the process-global one). ``shapes_fn`` supplies the
        server's live ``shapes_seen`` set at render time."""
        from deeplearning4j_tpu.observability.metrics import get_registry

        self.detach_from_registry()
        reg = registry if registry is not None else get_registry()

        def _collect():
            shapes = shapes_fn() if shapes_fn is not None else ()
            return self.metric_families(shapes, labels)

        reg.register_collector(_collect)
        self._registry, self._collector = reg, _collect
        return reg

    def detach_from_registry(self):
        reg = getattr(self, "_registry", None)
        if reg is not None:
            reg.unregister_collector(self._collector)
            self._registry = self._collector = None


# ------------------------------------------------- decode-tier families
def decode_metric_families(describe: dict, labels=None):
    """Render a ``DecodeEngine.describe()`` dict into MetricFamily rows
    for the unified registry — the decode/KV-pool view of ``/metrics``
    (Prometheus text + JSON) and, because ``export_snapshot`` reads the
    same registry, the federation wire form. Registered as a render-time
    collector by ``ModelServer`` when a decode engine is attached."""
    from deeplearning4j_tpu.observability.metrics import MetricFamily

    L = dict(labels or {})
    fams = []

    def fam(name, kind, help, value):
        if value is None:
            return
        fams.append(MetricFamily(name, kind, help).add(value, L))

    fam("dl4j_kv_pool_pages_used", "gauge",
        "Physical KV pages held (each shared page counted once)",
        describe.get("pages_used"))
    fam("dl4j_kv_pool_shared_pages", "gauge",
        "KV pages currently referenced by two or more sessions",
        describe.get("shared_pages"))
    fam("dl4j_kv_pool_dedup_ratio", "gauge",
        "Logical page charge over physical pages held (1.0 = nothing "
        "shared)", describe.get("dedup_ratio"))
    fam("dl4j_kv_pool_evictions_total", "counter",
        "Sessions LRU-released to free pages",
        describe.get("evictions"))
    fam("dl4j_decode_prefill_chunks_total", "counter",
        "Prompt segments submitted through the chunked-prefill path",
        describe.get("prefill_chunks"))
    fam("dl4j_decode_interleaved_prefills_total", "counter",
        "Chunked prefills during which decode steps dispatched between "
        "chunks", describe.get("interleaved_prefills"))
    fam("dl4j_decode_prefix_hits_total", "counter",
        "Prefills that adopted a shared prompt-prefix page chain",
        describe.get("prefix_hits"))
    fam("dl4j_decode_shared_tokens_total", "counter",
        "Prefill tokens skipped by adopting shared pages",
        describe.get("shared_tokens"))
    fam("dl4j_decode_reprefills_total", "counter",
        "Evicted sessions re-admitted bit-identically from history",
        describe.get("reprefills"))
    itok = describe.get("inter_token_hist")
    if itok and itok.get("count"):
        from deeplearning4j_tpu.observability.metrics import _fmt_value
        hist = MetricFamily(
            "dl4j_decode_inter_token_seconds", "histogram",
            "Wall time between consecutive emitted tokens per decode "
            "session (live p50/p99 source — the tail the chunked-"
            "prefill/speculative levers move)")
        cum = 0
        for le, n in sorted(itok["buckets"].items(),
                            key=lambda kv: float(kv[0])):
            cum += int(n)
            hist.add(cum, {**L, "le": _fmt_value(float(le))},
                     suffix="_bucket")
        hist.add(round(float(itok["sum"]), 6), L, suffix="_sum")
        hist.add(int(itok["count"]), L, suffix="_count")
        fams.append(hist)
    if describe.get("speculative_k"):
        fam("dl4j_decode_spec_rounds_total", "counter",
            "Speculative draft-propose/target-verify rounds run",
            describe.get("spec_rounds"))
        fam("dl4j_decode_spec_proposed_total", "counter",
            "Draft tokens proposed for target verification",
            describe.get("spec_proposed"))
        fam("dl4j_decode_spec_accepted_total", "counter",
            "Draft proposals accepted by exact target-argmax match",
            describe.get("spec_accepted"))
        fam("dl4j_decode_spec_rejected_total", "counter",
            "Draft proposals truncated at the first argmax mismatch",
            describe.get("spec_rejected"))
        fam("dl4j_decode_spec_accept_tokens_per_step", "gauge",
            "Tokens emitted per target decode launch (1.0 = plain "
            "decode; the speculative speedup lever)",
            describe.get("spec_accept_tokens_per_step"))
    return fams
