"""Continuous micro-batching dispatcher: the cross-request coalescing
core of the serving runtime.

The seed server ran ONE forward per HTTP request under a global lock —
256 concurrent single-row requests became 256 serialized bucket-1
forwards and the accelerator idled between dispatches. Here the HTTP
handler threads only *enqueue*: each request becomes a ticket
``(features, rows, future)`` in a bounded queue, and a single device
thread drains whatever is pending, concatenates compatible tickets into
ONE padded power-of-two bucket forward, then scatters the result rows
back to each ticket's future. Request-level batching is the classic
serving lever for accelerator utilization (TF-Serving's batching story);
the bucket ladder keeps the XLA compile cache bounded exactly as before.

Mechanics:
- Compatibility: tickets coalesce only when every per-input row shape
  (everything but the batch dim) matches — multi-input ComputationGraph
  requests group by their input-arity/shape signature, and a malformed
  request (wrong feature width) forms its own group so its failure
  never poisons co-batched well-formed requests.
- Linger: when the queue is shallow the device thread waits up to
  ``batch_window_ms`` for more compatible tickets before launching; a
  full bucket launches immediately. At high concurrency the window
  never matters (the queue is never empty); at concurrency 1 it is the
  entire added latency, so keep it small.
- Backpressure: ``submit`` raises ``QueueFullError`` once ``max_queue``
  tickets are pending — the HTTP layer turns that into 503 +
  ``Retry-After`` instead of unbounded memory growth.
- Drain: ``stop()`` flushes every pending ticket through the device
  before the thread exits — no request accepted before shutdown is
  dropped.

Precision contracts (PRECISION.md): under the default f32 serving path
every coalesced row is BIT-IDENTICAL to the same row served alone
(min_batch=2 floor + padded buckets guarantee it). When the server is
built with ``compute_dtype="bfloat16"``, matmul compute runs half-width
through a shadow policy view of the same f32 params — rows then carry a
numeric-TOLERANCE contract (~1e-2 relative vs the f32 forward; heads
still activate in f32), not bit-identity. The batcher itself is
dtype-agnostic: both contracts are properties of the forward_fn it is
given.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from deeplearning4j_tpu.analysis.guards import guarded_by
from deeplearning4j_tpu.observability import goodput as _goodput
from deeplearning4j_tpu.observability.trace import get_tracer as _get_tracer


class QueueFullError(RuntimeError):
    """Admission control: the pending-ticket queue is at ``max_queue``."""


class BatcherDeadError(RuntimeError):
    """The device thread died on an unexpected (non-request) error. The
    server maps this to 503 + an unhealthy ``/healthz`` — a dead batcher
    must look down to the load balancer, not hang every request until
    its deadline."""


def bucket_ladder(min_batch: int, max_batch: int) -> list[int]:
    """The full power-of-two bucket ladder (min_batch, 2*min_batch, ...,
    capped at max_batch) — the compile footprint warm-up walks and the
    AOT precompiler (compilecache.precompile) persists."""
    ladder = []
    b = max(1, int(min_batch))
    while True:
        ladder.append(min(b, int(max_batch)))
        if b >= max_batch:
            break
        b *= 2
    return ladder


def next_bucket(n: int, max_batch: int, min_batch: int = 1) -> int:
    """Power-of-two bucket, capped at ``max_batch``. Requests larger than
    ``max_batch`` are CHUNKED by the caller (never compiled at raw size —
    one oversized POST must not grow the XLA compile cache). The
    ``min_batch`` floor (the dispatcher uses 2) keeps every forward on
    the same gemm code path: a size-1 bucket lowers to a gemv whose row
    results can differ in the last ulp from the batched kernel, which
    would make a reply depend on what traffic it happened to coalesce
    with."""
    b = max(1, int(min_batch))
    while b < n:
        b *= 2
    return min(b, max_batch)


class _Ticket:
    __slots__ = ("feats", "rows", "key", "future", "t_submit", "trace_id",
                 "priority")

    def __init__(self, feats, rows, key, trace_id=None, priority=0):
        self.feats = feats
        self.rows = rows
        self.key = key
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.trace_id = trace_id
        # strict-priority tier (scheduling/core.py PRIORITY: 0 =
        # interactive, higher = sheds/waits first); the device thread
        # seeds each bucket from the oldest highest-tier ticket
        self.priority = priority


def _trace_ids(batch) -> list:
    """The distinct client trace ids riding a coalesced batch (ordered,
    deduped) — the correlation key a merged fleet timeline joins on."""
    out = []
    for t in batch:
        if t.trace_id and t.trace_id not in out:
            out.append(t.trace_id)
    return out


@guarded_by("_cond", "_pending", "_stopping", "_crashed", "_thread")
class MicroBatcher:
    """Bounded ticket queue + device thread.

    ``forward(feats)`` is the model adapter: it receives the padded
    bucket-shaped input list and returns the model output (one array or
    a list/tuple of arrays, each with ``bucket`` rows). It only ever
    runs on the device thread (and during ``warm()``), so it needs no
    locking of its own.
    """

    def __init__(self, forward, *, max_batch: int = 1024,
                 batch_window_ms: float = 2.0, max_queue: int = 1024,
                 min_batch: int = 2, stats=None, shapes_seen=None):
        self._forward = forward
        self.max_batch = int(max_batch)
        self.min_batch = min(int(min_batch), self.max_batch)
        self.batch_window_ms = float(batch_window_ms)
        self.max_queue = int(max_queue)
        self.stats = stats
        # injectable so fleet replicas sharing one forward share ONE
        # compile-footprint set (the bucket ladder compiles per forward,
        # not per replica)
        self.shapes_seen: set[int] = (shapes_seen if shapes_seen is not None
                                      else set())
        self._pending: deque[_Ticket] = deque()
        self._cond = threading.Condition()
        self._thread = None
        self._stopping = False
        self._crashed = False
        if stats is not None:
            stats.queue_depth_fn = lambda: len(self._pending)

    @property
    def depth(self) -> int:
        """Tickets currently pending — the observed-load signal the
        fleet's queue-depth router weighs replicas by."""
        return len(self._pending)

    @property
    def healthy(self) -> bool:
        """False once the device thread has died (crashed on a
        non-request error, or exited while not stopping) — the liveness
        signal ``/healthz`` reports."""
        if self._crashed:
            return False
        if (self._thread is not None and not self._thread.is_alive()
                and not self._stopping):
            return False
        return True

    # ---------------------------------------------------------------- warmup
    def warm(self, row_shapes, skip=None) -> list[int]:
        """Precompile the bucket ladder (min_batch, ..., max_batch) with
        zero-filled inputs of the given per-input row shapes, so no live
        request ever pays an XLA compile stall. Runs synchronously (call
        before serving traffic).

        Buckets already in ``shapes_seen`` are SKIPPED — they were
        compiled by an earlier warm or by live traffic on this shared
        forward (e.g. a fleet ``restart(i)`` re-warm), and re-running
        them would only burn device time re-executing cached programs.
        ``skip`` overrides the skip set (ReplicaSet.warm passes its
        pre-warm snapshot so a fleet of DISTINCT forwards still warms
        each one fully despite the shared ``shapes_seen``). Returns only
        the buckets this call actually ran."""
        skip = self.shapes_seen if skip is None else skip
        compiled = []
        for bucket in bucket_ladder(self.min_batch, self.max_batch):
            if bucket in skip:
                continue
            feats = [np.zeros((bucket,) + tuple(s), np.float32)
                     for s in row_shapes]
            self._forward(feats)
            self.shapes_seen.add(bucket)
            compiled.append(bucket)
        return compiled

    # ------------------------------------------------------------- lifecycle
    def start(self):
        # thread-safe: concurrent lazy starts (every predict() calls
        # start) must neither double-spawn the device thread nor let
        # ``healthy`` observe a created-but-not-yet-started Thread
        # (is_alive() False would read as a dead batcher and get the
        # replica evicted at birth) — publish only after start()
        if self._thread is not None:
            # lock-free fast path: _thread is only ever set under the
            # lock and only after the thread is running
            return self
        with self._cond:
            if self._thread is None:
                self._stopping = False
                t = threading.Thread(target=self._loop, daemon=True,
                                     name="microbatcher-device")
                t.start()
                self._thread = t
        return self

    def stop(self):
        """Graceful drain: every already-accepted ticket is executed
        before the device thread exits; new submits are rejected."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=60)
            with self._cond:
                self._thread = None

    # --------------------------------------------------------------- enqueue
    def submit(self, feats: list, trace_id: str = None,
               priority: int = 0) -> Future:
        """Enqueue one request (``feats``: list of arrays, one per model
        input, equal leading row counts <= max_batch). Returns a Future
        resolving to the model output sliced back to this ticket's rows.
        ``trace_id`` (the client's ``X-DL4J-Trace-Id``) rides the ticket
        onto the queue_wait/batch_assembly/device_compute span attrs so
        server spans correlate with client-side spans. ``priority`` is
        the strict-priority tier (scheduling/core.py: 0 = interactive);
        a lower number is dequeued first, FIFO within a tier."""
        rows = int(feats[0].shape[0])
        if rows > self.max_batch:
            raise ValueError(f"ticket of {rows} rows > max_batch "
                             f"{self.max_batch} — chunk before submit")
        key = tuple(tuple(f.shape[1:]) for f in feats)
        t = _Ticket(feats, rows, key, trace_id, priority=int(priority))
        with self._cond:
            if not self.healthy:
                raise BatcherDeadError("device thread is dead")
            if self._stopping:
                raise RuntimeError("batcher is stopped")
            if len(self._pending) >= self.max_queue:
                if self.stats is not None:
                    self.stats.record_rejected()
                raise QueueFullError(
                    f"{len(self._pending)} tickets pending "
                    f"(max_queue={self.max_queue})")
            self._pending.append(t)
            self._cond.notify_all()
        return t.future

    # ----------------------------------------------------------- device side
    def _seed_locked(self) -> _Ticket:
        """The next ticket to anchor a device forward: the OLDEST
        ticket of the HIGHEST priority tier present (strict priority,
        FIFO within a tier) — an interactive request never waits behind
        a batch backlog that arrived first. The scan is oldest-first
        and exits at the first tier-0 ticket, so the default regime
        (everything tier 0) stays the O(1) popleft it always was."""
        best = None
        for t in self._pending:
            if best is None or t.priority < best.priority:
                best = t
                if best.priority <= 0:
                    break
        if best is self._pending[0]:
            return self._pending.popleft()
        self._pending.remove(best)
        return best

    def _gather_locked(self):
        """Pop the seed ticket (oldest, highest tier) plus every
        compatible ticket that fits in the bucket; linger up to
        batch_window_ms for stragglers when the bucket is not full.
        Called with the lock held."""
        batch = [self._seed_locked()]
        rows = batch[0].rows
        key = batch[0].key

        def sweep():
            nonlocal rows
            kept = deque()
            while self._pending:
                t = self._pending.popleft()
                if t.key == key and rows + t.rows <= self.max_batch:
                    batch.append(t)
                    rows += t.rows
                else:
                    kept.append(t)
            self._pending.extendleft(reversed(kept))

        sweep()
        # linger: wait (releasing the lock) for more compatible tickets
        # until the bucket fills or the window closes
        if self.batch_window_ms > 0:
            deadline = time.monotonic() + self.batch_window_ms / 1000.0
            while rows < self.max_batch and not self._stopping:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
                sweep()
        return batch, rows

    def _loop(self):
        batch = None
        try:
            while True:
                with self._cond:
                    while not self._pending and not self._stopping:
                        self._cond.wait()
                    if not self._pending:
                        return  # stopping and fully drained
                    batch, rows = self._gather_locked()
                # one queue_wait span per device forward, timed from the
                # oldest ticket's submit (the worst wait in the batch)
                attrs = {"tickets": len(batch)}
                tids = _trace_ids(batch)
                if tids:
                    attrs["trace_ids"] = tids
                _get_tracer().record("queue_wait", batch[0].t_submit,
                                     time.perf_counter(), attrs)
                self._execute(batch, rows)
                batch = None
        except BaseException as e:  # noqa: BLE001 — device thread death
            # _execute already absorbs per-request Exceptions; anything
            # that reaches here (SystemExit, MemoryError, a bug) kills
            # the device thread. Mark unhealthy and fail every waiting
            # ticket NOW — futures must never hang until their deadline
            # on a thread that will never run again.
            self._die(batch, e)

    def _die(self, batch, exc):
        with self._cond:
            self._crashed = True
            stranded = list(self._pending)
            self._pending.clear()
        err = BatcherDeadError(
            f"device thread died: {type(exc).__name__}: {exc}")
        for t in list(batch or ()) + stranded:
            if not t.future.done():
                if self.stats is not None:
                    self.stats.record_error()
                t.future.set_exception(err)

    def _execute(self, batch, rows):
        n_inputs = len(batch[0].feats)
        tracer = _get_tracer()
        tids = _trace_ids(batch)
        tid_attrs = {"trace_ids": tids} if tids else {}
        try:
            with tracer.span("batch_assembly", tickets=len(batch),
                             **tid_attrs):
                feats = [np.concatenate([t.feats[i] for t in batch])
                         if len(batch) > 1 else batch[0].feats[i]
                         for i in range(n_inputs)]
                bucket = next_bucket(rows, self.max_batch, self.min_batch)
                if bucket != rows:
                    feats = [np.pad(f, [(0, bucket - rows)] + [(0, 0)]
                                    * (f.ndim - 1)) for f in feats]
                self.shapes_seen.add(bucket)
            t_fwd = time.perf_counter()
            with tracer.span("device_compute", bucket=bucket, rows=rows,
                             **tid_attrs):
                out = self._forward(feats)
            device_s = time.perf_counter() - t_fwd
        except Exception as e:
            for t in batch:
                if self.stats is not None:
                    self.stats.record_error()
                t.future.set_exception(e)
            return
        if self.stats is not None:
            # per-bucket device seconds feed the autotuner's measured
            # service model (ServingStats.bucket_device_s)
            self.stats.record_batch(bucket, rows, len(batch),
                                    device_s=device_s)
        # padding-waste accounting: bucket - rows filler rows rode this
        # device forward (goodput ledger + dl4j_padding_waste_fraction)
        _goodput.record_padding("serving_bucket", rows, bucket - rows)
        many = isinstance(out, (list, tuple))
        outs = [np.asarray(o) for o in out] if many else [np.asarray(out)]
        off = 0
        for t in batch:
            sliced = [o[off:off + t.rows] for o in outs]
            off += t.rows
            t.future.set_result(sliced if many else sliced[0])
