"""Replica tier: N micro-batcher device workers behind ONE admission
queue.

A single ``MicroBatcher`` caps rows/sec at whatever its one device
thread can launch, no matter how many devices exist. The fleet keeps
the batcher exactly as it is — one bounded queue, one device thread,
one coalesced bucket forward — and scales it horizontally:

- **Admission** is global: ``submit`` rejects with ``QueueFullError``
  once the SUM of LIVE replicas' queue depths reaches ``max_queue``
  (a dead-but-unswept replica's stranded tickets are not capacity),
  so backpressure (503 + Retry-After) reflects fleet capacity, not
  whichever replica a request happened to hash to. With a
  ``scheduler`` (scheduling/core.py) attached, admission runs the
  unified class/quota/deadline discipline instead, and the admitted
  class rides each ticket as its strict-priority tier.
- **Routing** is by observed load: each ticket goes to the live replica
  with the shallowest queue (ties rotate round-robin) — the same
  measured-not-modeled scheduling stance as TVM's cost-model-free
  tuning (PAPERS.md), using the queue-depth signal the metrics registry
  already exports.
- **Eviction** generalizes the ``BatcherDeadError`` seam: when a
  replica's device thread dies, its in-flight and queued tickets fail
  fast with ``BatcherDeadError`` (batcher.py `_die`) — the fleet
  catches that *per ticket* and resubmits onto a surviving replica, so
  the client's future still resolves with rows. A ticket failed by
  ``_die`` never reached ``set_result``, so the requeue cannot
  double-deliver; the forward itself is pure inference, so a re-run is
  idempotent. ``BatcherDeadError`` escapes to the caller only when NO
  live replica remains.
- **Draining** removes a replica from routing while its accepted queue
  finishes; ``restart`` re-admits a slot with a fresh batcher. Replicas
  share the forward callable (and thus the jit cache), so a restarted
  replica serves warm — no second bucket-ladder compile.
- **Warm-up is hoisted**: ``warm`` runs the bucket ladder once per
  DISTINCT forward object, not once per replica. N replicas over one
  model/mesh pay one ladder (asserted via the compile-count metric —
  ``dl4j_xla_compile_total`` is flat in N).
- **Session affinity** (decode serving, serving/decode.py): a ticket
  submitted with ``session=sid`` sticks to the replica that served the
  session last — decode steps hit a warm jit cache and stable queue
  instead of ping-ponging. Affinity is a ROUTING HINT layered on the
  least-depth picker, never a correctness dependency: the session's
  cache state rides the ticket itself, so when the pinned replica dies
  or drains the map rebinds to the least-depth survivor (an
  ``affinity_miss``) and the requeue machinery above applies unchanged.

All replicas share one ``ServingStats`` (counters are lock-guarded) and
one ``shapes_seen`` set (the compile-cache footprint is a property of
the shared forward, not of any replica). The shared stats'
``queue_depth_fn`` is rebound to the fleet-wide total.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import List, Optional

from deeplearning4j_tpu.analysis.guards import guarded_by
from deeplearning4j_tpu.serving.batcher import (BatcherDeadError,
                                                MicroBatcher, QueueFullError)

LIVE = "live"
DRAINING = "draining"
DEAD = "dead"


class Replica:
    """One micro-batcher worker slot in the fleet."""

    __slots__ = ("index", "batcher", "status", "evicted_at")

    def __init__(self, index: int, batcher: MicroBatcher):
        self.index = index
        self.batcher = batcher
        self.status = LIVE
        self.evicted_at: Optional[float] = None

    @property
    def depth(self) -> int:
        return self.batcher.depth

    def describe(self) -> dict:
        """The per-replica health row (``/healthz``, ``/metrics``,
        ``/api/fleet`` scoreboard)."""
        return {"replica": self.index, "status": self.status,
                "queue_depth": self.depth}


@guarded_by("_lock", "_rr", "requeued", "_affinity", "affinity_hits",
            "affinity_misses", "replicas")
class ReplicaSet:
    """N replicas of one forward behind global admission + least-depth
    routing. With ``n=1`` this degenerates to exactly the single-batcher
    behavior (one queue, same backpressure, same drain)."""

    def __init__(self, forward, n: int = 1, *, max_batch: int = 1024,
                 batch_window_ms: float = 2.0, max_queue: int = 1024,
                 min_batch: int = 2, stats=None, forwards=None,
                 scheduler=None):
        if forwards is None:
            forwards = [forward] * int(n)
        self.max_queue = int(max_queue)
        self.stats = stats
        #: scheduling.core.SchedulingCore — when set, admission runs
        #: class watermarks / tenant quotas / deadline sheds through it
        #: (None keeps the legacy single-threshold reject exactly)
        self.scheduler = scheduler
        self.shapes_seen: set[int] = set()
        self._batcher_cfg = dict(max_batch=max_batch,
                                 batch_window_ms=batch_window_ms,
                                 max_queue=max_queue, min_batch=min_batch)
        self._lock = threading.Lock()
        self._rr = 0          # round-robin tiebreak cursor
        self.requeued = 0     # tickets resubmitted after an eviction
        self._affinity = {}   # session id -> pinned Replica
        self.affinity_hits = 0
        self.affinity_misses = 0
        #: the width this tier was PROVISIONED at: a fleet restarted on
        #: fewer devices keeps serving but reports degraded until a
        #: later restart restores the original width
        self.target_n = len(forwards)
        self.replicas: List[Replica] = [
            Replica(i, self._make_batcher(fwd))
            for i, fwd in enumerate(forwards)]
        if stats is not None:
            # each batcher's __init__ bound queue_depth_fn to its own
            # queue; the shared stats must report the fleet-wide total
            stats.queue_depth_fn = self.total_depth

    def _make_batcher(self, forward) -> MicroBatcher:
        return MicroBatcher(forward, stats=self.stats,
                            shapes_seen=self.shapes_seen,
                            **self._batcher_cfg)

    # ------------------------------------------------------------- lifecycle
    def start(self):
        for r in self.replicas:
            if r.status == LIVE:
                r.batcher.start()
        return self

    def stop(self):
        """Graceful fleet drain: every replica finishes its accepted
        queue before its device thread exits."""
        for r in self.replicas:
            r.batcher.stop()

    def warm(self, row_shapes, skip=None):
        """Hoisted warm-up: run the bucket ladder once per DISTINCT
        forward object. Replicas sharing one model/mesh share the jit
        cache, so the ladder compiles once no matter how many replicas
        ride it; ``shapes_seen`` is shared, so the compile-count metric
        stays flat in N. Buckets already in ``shapes_seen`` before this
        call (an earlier warm, a restart re-warm, live traffic) are
        skipped per batcher.warm — but only against the PRE-call
        snapshot, so when replicas carry distinct forwards each still
        warms its own full ladder. Returns the buckets actually
        compiled by this call (sorted, deduped across forwards).

        ``shapes_seen`` holds bare batch-bucket ints with no notion of
        WHICH row-shape ladder they came from, so a caller warming
        several ladders in sequence (decode, then each prompt rung, as
        ``DecodeEngine.warm`` does) must pass an explicit ``skip`` set —
        otherwise the snapshot taken after the first ladder silently
        suppresses every later one and those rungs compile during the
        timed run."""
        seen0 = set(self.shapes_seen) if skip is None else set(skip)
        warmed = set()
        compiled: set[int] = set()
        for r in self.replicas:
            fid = id(r.batcher._forward)
            if fid in warmed:
                continue
            warmed.add(fid)
            compiled.update(r.batcher.warm(row_shapes, skip=seen0))
        return sorted(compiled)

    # ----------------------------------------------------------------- state
    @property
    def healthy(self) -> bool:
        """At least one replica can take traffic."""
        return any(r.status == LIVE and r.batcher.healthy
                   for r in self.replicas)

    def total_depth(self) -> int:
        return sum(r.depth for r in self.replicas)

    def live_depth(self) -> int:
        """Backlog that can still DRAIN: queue depths of replicas whose
        device thread is alive (live or draining). A dead-but-unswept
        replica's stranded tickets are about to be failed by ``_die`` /
        requeued — counting them against ``max_queue`` inflated rejects
        right after an eviction, bouncing traffic the survivors had
        room for. This is the admission-control depth; ``total_depth``
        stays the observable-truth gauge."""
        return sum(r.depth for r in self.replicas
                   if r.status != DEAD and r.batcher.healthy)

    @property
    def degraded(self) -> bool:
        """Serving on fewer replicas than the tier was provisioned with
        (a shrunken-fleet restart) — visible on every scoreboard row."""
        return len(self.replicas) < self.target_n

    def describe(self) -> list[dict]:
        with self._lock:
            self._sweep_dead_locked()
            degraded = self.degraded
            rows = []
            for r in self.replicas:
                row = r.describe()
                if degraded:
                    row["degraded"] = True
                    row["target_replicas"] = self.target_n
                rows.append(row)
            return rows

    def _sweep_dead_locked(self):
        # lazy eviction: a device thread that died between submissions
        # shows up here (batcher.healthy), not only via a failed ticket
        for r in self.replicas:
            if r.status != DEAD and not r.batcher.healthy:
                r.status = DEAD
                r.evicted_at = time.time()

    def _mark_dead(self, replica: Replica):
        with self._lock:
            if replica.status != DEAD:
                replica.status = DEAD
                replica.evicted_at = time.time()

    # --------------------------------------------------------------- control
    def drain(self, index: int):
        """Remove a replica from routing; its already-accepted tickets
        still execute. Re-admit with ``restart``."""
        with self._lock:
            self.replicas[index].status = DRAINING

    def restart(self, index: int):
        """Re-admit a drained/evicted slot with a FRESH batcher over the
        same forward. The forward's jit cache survives the old device
        thread, so the restarted replica serves warm — no second
        bucket-ladder compile (``shapes_seen`` is shared and unchanged).

        Guarded: restarting a replica that is still LIVE and healthy is
        an explicit error (``drain(index)`` first, or use
        :meth:`swap_forward` for a zero-blackout in-place swap) — the
        old behavior silently stacked a second batcher over a running
        one, leaking its device thread and queue."""
        r = self.replicas[index]
        old = r.batcher
        with self._lock:
            if r.status == LIVE and old.healthy:
                raise RuntimeError(
                    f"replica {index} is live and healthy — drain({index}) "
                    "before restart, or swap_forward() for an in-place "
                    "hot swap")
        if old.healthy:
            old.stop()
        fresh = self._make_batcher(old._forward).start()
        with self._lock:
            # publish batcher + status together: a concurrent _pick must
            # never route to a LIVE replica still holding the dead batcher
            r.batcher = fresh
            r.status = LIVE
            r.evicted_at = None
        if self.stats is not None:
            # _make_batcher rebound the shared stats' depth fn to the
            # new batcher's queue; restore the fleet-wide total
            self.stats.queue_depth_fn = self.total_depth
        return r

    def swap_forward(self, index: int, forward):
        """Zero-blackout hot swap: replace one replica's forward with a
        FRESH batcher over *forward*, publish-then-drain. The new
        batcher is built and started first, then published under the
        lock (a concurrent ``_pick`` sees either the old live batcher
        or the new live batcher — never a gap), and only THEN does the
        old batcher drain gracefully: its accepted queue and in-flight
        batch finish on the OLD forward (old weights) while new
        admissions already run the new one. The drain blocks the swap
        *caller*, never traffic.

        When both forwards close over the same jitted programs (the
        ``ModelServer.hot_swap`` version-bound closures share the
        serving net's jit cache), the swap compiles nothing fresh —
        ``shapes_seen`` is shared and unchanged."""
        r = self.replicas[index]
        fresh = self._make_batcher(forward).start()
        with self._lock:
            old = r.batcher
            r.batcher = fresh
            r.status = LIVE
            r.evicted_at = None
        if self.stats is not None:
            # _make_batcher rebound the shared stats' depth fn to the
            # new batcher's queue; restore the fleet-wide total
            self.stats.queue_depth_fn = self.total_depth
        if old.healthy:
            old.stop()   # graceful: queued tickets finish on old weights
        return r

    def restart_fleet(self, forwards=None, *, n: Optional[int] = None,
                      forward=None):
        """Rebuild the whole replica tier — possibly NARROWER than it
        was provisioned (a fleet relaunched after losing devices).
        Existing batchers drain gracefully; the new replicas share the
        surviving jit cache (same forward object ⇒ warm restart, no
        second bucket ladder). The tier keeps serving with whatever it
        gets — ``degraded`` turns true when the new width is below the
        original ``target_n`` and every scoreboard row says so, until a
        later ``restart_fleet`` back at full width clears it.

        Pass explicit ``forwards`` (one per replica), or ``n`` (+
        optionally a shared ``forward``; defaults to replica 0's)."""
        if forwards is None:
            if n is None or int(n) < 1:
                raise ValueError("restart_fleet needs forwards or n >= 1")
            fwd = forward if forward is not None \
                else self.replicas[0].batcher._forward
            forwards = [fwd] * int(n)
        if not forwards:
            raise ValueError("restart_fleet needs at least one replica")
        for r in self.replicas:
            if r.batcher.healthy:
                r.batcher.stop()
        with self._lock:
            self.replicas = [Replica(i, self._make_batcher(f))
                             for i, f in enumerate(forwards)]
            self._rr = 0
            self._affinity.clear()   # old Replica objects are gone
        if self.stats is not None:
            self.stats.queue_depth_fn = self.total_depth
        return self

    # --------------------------------------------------------------- routing
    def _pick(self, session=None) -> Optional[Replica]:
        with self._lock:
            self._sweep_dead_locked()
            live = [r for r in self.replicas if r.status == LIVE]
            if not live:
                return None
            if session is not None:
                pinned = self._affinity.get(session)
                if pinned is not None and pinned.status == LIVE:
                    self.affinity_hits += 1
                    return pinned
                # first sighting, or the pinned replica died/drained —
                # rebind below to the least-depth pick
                self.affinity_misses += 1
            depths = [r.depth for r in live]
            lo = min(depths)
            tied = [r for r, d in zip(live, depths) if d == lo]
            pick = tied[self._rr % len(tied)]
            self._rr += 1
            if session is not None:
                self._affinity[session] = pick
            return pick

    def forget_session(self, session):
        """Drop a closed session's routing pin (decode.close_session)."""
        with self._lock:
            self._affinity.pop(session, None)

    def submit(self, feats: list, trace_id: str = None,
               session=None, klass=None, tenant=None,
               deadline_ms=None) -> Future:
        """Admit one ticket fleet-wide and route it to the shallowest
        live queue — or, with ``session=``, to the session's pinned
        replica while it stays live. Admission counts only LIVE
        replicas' depths (a dead-but-unswept replica's stranded queue
        is not capacity the survivors owe anyone). With a
        ``scheduler`` attached, admission runs the unified discipline
        (scheduling/core.py): per-tenant quotas, class watermarks
        (batch sheds at 50% of ``max_queue``, interactive only at
        100% — the legacy threshold), and deadline sheds against the
        derived wait estimate; the admitted class rides the batcher
        ticket as its strict-priority tier. Raises ``QueueFullError``
        (or its ``ShedError`` subclass) on reject, and
        ``BatcherDeadError`` only when no live replica remains."""
        self.start()
        depth = self.live_depth()
        priority = 0
        if self.scheduler is not None:
            # the wait estimate feeds ONLY the deadline shed — skip the
            # drain-rate scan (O(window) under the stats lock) for the
            # deadline-less fast path
            wait = self.stats.retry_after_s(depth) \
                if deadline_ms is not None and self.stats is not None \
                else None
            try:
                k = self.scheduler.admit(
                    tenant=tenant, klass=klass, deadline_ms=deadline_ms,
                    rows=int(feats[0].shape[0]), depth=depth,
                    capacity=self.max_queue, wait_estimate_s=wait)
            except QueueFullError:
                if self.stats is not None:
                    self.stats.record_rejected()
                raise
            priority = self.scheduler.PRIORITY[k]
        elif depth >= self.max_queue:
            if self.stats is not None:
                self.stats.record_rejected()
            raise QueueFullError(
                f"{depth} tickets pending across "
                f"{len(self.replicas)} replicas (max_queue="
                f"{self.max_queue})")
        outer = Future()
        self._dispatch(feats, trace_id, outer, first=True, session=session,
                       priority=priority)
        return outer

    def _dispatch(self, feats, trace_id, outer: Future, first: bool,
                  session=None, priority: int = 0):
        while True:
            r = self._pick(session)
            if r is None:
                err = BatcherDeadError("all replicas dead")
                if first:
                    raise err
                outer.set_exception(err)
                return
            b = r.batcher
            try:
                inner = b.submit(feats, trace_id, priority=priority)
            except BatcherDeadError:
                # lost the race with a dying device thread — evict and
                # try the next live replica
                self._mark_dead(r)
                continue
            except QueueFullError:
                if first:
                    raise
                outer.set_exception(
                    QueueFullError("no capacity on surviving replicas"))
                return
            except RuntimeError:
                if r.batcher is not b:
                    # lost the race with a hot swap: the stopped batcher
                    # we captured was already replaced — the replica is
                    # live again under its fresh batcher, re-pick
                    continue
                if first:
                    raise
                # requeue path hit a full/stopped survivor: the client
                # sees the failure (and retries) rather than the ticket
                # silently blocking a device callback thread
                outer.set_exception(
                    QueueFullError("no capacity on surviving replicas"))
                return
            inner.add_done_callback(
                lambda f, rep=r: self._on_done(f, rep, feats, trace_id,
                                               outer, session, priority))
            return

    def _on_done(self, inner: Future, replica: Replica, feats, trace_id,
                 outer: Future, session=None, priority: int = 0):
        exc = inner.exception()
        if exc is None:
            outer.set_result(inner.result())  # analysis: ok(C003) — done-callback: future already resolved
        elif isinstance(exc, BatcherDeadError):
            # the replica died with this ticket in flight; its future
            # was failed by _die BEFORE any result delivery, so a
            # resubmit cannot double-deliver — requeue onto survivors
            # (a pinned session rebinds in _pick: the pin is dead)
            self._mark_dead(replica)
            with self._lock:
                self.requeued += 1
            self._dispatch(feats, trace_id, outer, first=False,
                           session=session, priority=priority)
        else:
            outer.set_exception(exc)
