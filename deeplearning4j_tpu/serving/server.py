"""Continuous-batching HTTP inference server.

Parity surface: DL4jServeRouteBuilder.java:27,64 (deserialize record ->
``Model.output()`` -> publish), grown into a production serving
runtime. The seed design serialized every request under a global lock —
one forward per request, accelerator idle between dispatches. This
version decouples the HTTP threads from the device entirely:

- HTTP handlers *enqueue* tickets into a bounded queue; a single device
  thread (serving/batcher.py) coalesces whatever is pending — across
  requests — into ONE padded power-of-two bucket forward, then scatters
  result rows back to each request's future.
- ``start()`` warm-up precompiles the whole bucket ladder (when the
  model's input row shape is inferable or given via ``input_shapes``),
  so no live request pays the first-compile stall.
- Admission control: a full queue answers 503 + ``Retry-After`` instead
  of growing without bound; ``stop()`` drains accepted work first.
- ``/metrics`` (serving/metrics.py): request/row counters, p50/p95/p99
  latency, executed-batch-size histogram, queue depth, coalesce ratio,
  compile count (= ``len(shapes_seen)``).

Works for MultiLayerNetwork (single ``features`` array) and
ComputationGraph (list under ``inputs``; multi-output replies are
lists). Multi-input requests coalesce only within the same input
arity/row-shape group.

Endpoints:
- ``POST /predict``  {"features": [[...]]} or {"inputs": [[[...]], ...]}
  -> {"predictions": ...}
- ``POST /decode``   (when built with ``decode_engine=``) the sessionful
  cross-host decode protocol: {"op": "prefill"|"step"|"close", "sid":
  ..., "ids": [history], "token": t} -> {"logits": [...]} — a ``step``
  for an unknown sid re-prefills from the carried history, the seam a
  FrontDoorRouter fails sessions over on (serving/router.py)
- ``GET /healthz``   liveness + model summary sizes
- ``GET /metrics``   ServingStats snapshot (JSON); with
  ``Accept: text/plain`` (or ``?format=prometheus``) the unified
  registry in Prometheus text exposition instead
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from deeplearning4j_tpu.observability import goodput as _goodput
from deeplearning4j_tpu.observability import metrics as _obs_metrics
from deeplearning4j_tpu.observability import trace as _obs_trace
from deeplearning4j_tpu.serving.batcher import (BatcherDeadError,
                                                MicroBatcher, QueueFullError,
                                                next_bucket)
from deeplearning4j_tpu.serving.fleet import ReplicaSet
from deeplearning4j_tpu.serving.metrics import ServingStats

_ = MicroBatcher  # re-exported (seed name); replicas are built by ReplicaSet

_next_bucket = next_bucket  # back-compat alias (seed name)


class DeadlineExceededError(RuntimeError):
    """The per-request deadline (``request_timeout_s``) expired before
    the device produced a result — mapped to HTTP 504."""


class UnknownSessionError(KeyError):
    """A decode op referenced a session this host does not hold and the
    request carried no token history to recover it from — mapped to
    HTTP 404 (the router retries elsewhere or surfaces it; a plain 400
    would read as a malformed request rather than a routing miss)."""

    def __str__(self):
        # KeyError.__str__ repr()s its arg; error payloads want prose
        return self.args[0] if self.args else ""


class _ServingHTTPServer(ThreadingHTTPServer):
    # default listen backlog is 5 — a 64-client closed-loop burst gets
    # connection resets before a single handler thread even spawns
    request_queue_size = 128


class ModelServer:
    def __init__(self, net, host: str = "127.0.0.1", port: int = 9500,
                 max_batch: int = 1024, batch_window_ms: float = 2.0,
                 max_queue: int = 1024, warmup: bool = True,
                 input_shapes=None, request_timeout_s: float = 300.0,
                 compute_dtype=None, replicas: int = 1, mesh=None,
                 model_axis: str = "model", data_axis=None, tp_rules=None,
                 compile_cache_dir=None, aot_manifest=None,
                 tuning_report=None, decode_engine=None,
                 push_url=None, push_interval_s: float = 2.0,
                 slos=None, scheduler=None):
        from deeplearning4j_tpu.compilecache import cache as _ccache
        # Cold-start engine (SERVING.md "Cold start & AOT"):
        # - compile_cache_dir (or $DL4J_TPU_COMPILE_CACHE) activates the
        #   persistent compilation cache, so a second boot of the same
        #   config deserializes executables instead of compiling;
        # - aot_manifest names (or True auto-locates, in the cache dir)
        #   the scripts/precompile.py receipt validated at start() —
        #   mismatch warns and falls back to lazy compile;
        # - tuning_report loads an autotuned (max_batch, batch_window_ms)
        #   from compilecache.autotune, overriding the defaults.
        self.compile_cache_dir = _ccache.configure(compile_cache_dir)
        self.aot_manifest = aot_manifest
        self.aot_manifest_ok = None  # set by start() when a manifest loads
        if tuning_report is not None:
            from deeplearning4j_tpu.compilecache import autotune as _at
            tuned = _at.load_tuned(tuning_report)
            max_batch = tuned["max_batch"]
            batch_window_ms = tuned["batch_window_ms"]
            self.tuned_config = tuned
        else:
            self.tuned_config = None
        self.net = net
        self.host = host
        self.port = port
        self.max_batch = max_batch
        self.warmup = warmup
        self.input_shapes = input_shapes
        self.request_timeout_s = float(request_timeout_s)
        self._httpd = None
        self._thread = None
        self._ledger = None
        self._fleet_collector = None
        self._decode_collector = None
        self.run_report = None  # goodput RunReport, set by stop()
        self.warmup_s = None    # warm-up ladder wall time, set by start()
        self._is_graph = hasattr(net, "conf") and hasattr(
            net.conf, "network_inputs")
        # Serving precision contract (PRECISION.md / SERVING.md):
        # compute_dtype=None serves with the net's own policy and keeps
        # the bit-identity contract (coalesced rows == row-at-a-time
        # rows, bit for bit). An explicit compute_dtype (e.g. "bfloat16")
        # serves through a shadow view of the SAME params under a
        # replaced policy — outputs then carry a numeric-tolerance
        # contract vs the f32 forward, not bit-identity.
        self.compute_dtype = compute_dtype
        self._serving_net = None
        if (compute_dtype is not None and compute_dtype
                != net.conf.global_conf.dtype.compute_dtype):
            self._serving_net = self._build_serving_net(compute_dtype)
        self.stats = ServingStats()
        # Mesh-parallel serving (SERVING.md "Fleet"): the coalesced
        # bucket forward runs tensor-parallel under shard_map with
        # arithmetic-free boundary collectives — params sharded ONCE
        # here, bit-identity preserved for f32 (parallel/inference.py).
        self.mesh = mesh
        min_batch = 2
        if mesh is not None:
            if self._is_graph:
                raise ValueError(
                    "mesh-parallel serving supports sequential layer "
                    "stacks; serve ComputationGraph models replicated")
            if compute_dtype is not None:
                raise ValueError(
                    "mesh serving is the f32 bit-identity path; combine "
                    "with compute_dtype via a bf16-policy net instead")
            from deeplearning4j_tpu.parallel.inference import (
                build_tp_output_fn)
            forward = build_tp_output_fn(net, mesh, model_axis,
                                         data_axis=data_axis,
                                         rules=tp_rules)
            if data_axis is not None:
                # data-sharded buckets must divide over the data axis;
                # power-of-two buckets >= the axis size always do
                min_batch = max(min_batch, int(mesh.shape[data_axis]))
        else:
            forward = self._device_forward
        # SLO-aware admission (SERVING.md §Traffic engine): on by
        # default with no quotas configured — class watermarks degrade
        # batch first under backpressure while default-class traffic
        # keeps the legacy reject threshold exactly; scheduler=False
        # disables (the bench.py sched_overhead off-arm), an explicit
        # SchedulingCore customizes quotas/watermarks.
        if scheduler is False:
            self.scheduler = None
        elif scheduler is None:
            from deeplearning4j_tpu.scheduling.core import SchedulingCore
            self.scheduler = SchedulingCore()
        else:
            self.scheduler = scheduler
        self._sched_collector = None
        # N batcher workers behind one admission queue (serving/fleet.py)
        # — replicas=1 degenerates to the single-batcher seed behavior
        self._fleet = ReplicaSet(
            forward, int(replicas), max_batch=max_batch,
            batch_window_ms=batch_window_ms, max_queue=max_queue,
            min_batch=min_batch, stats=self.stats,
            scheduler=self.scheduler)
        # every distinct padded batch shape handed to the device (warm-up
        # ladder included) — the compile count is bounded by
        # len(shapes_seen) (asserted by the serving concurrency test);
        # shared across replicas: the ladder compiles per forward
        self.shapes_seen = self._fleet.shapes_seen
        # Cross-host federation (SERVING.md "Cross-host federation"):
        # - decode_engine: a serving.decode.DecodeEngine this host serves
        #   sessionful /decode on. The wire protocol carries the full
        #   token history on every step, so an UNKNOWN sid is recovered
        #   by re-prefill — bit-identical, which is what lets a
        #   front-door router fail a session over onto this host after
        #   its pinned host died.
        # - push_url: a router/UIServer /api/metrics_push endpoint this
        #   host heartbeats its metrics snapshot to (HeartbeatPusher,
        #   retry attempts=3), carrying server_url so the router binds
        #   the pushed gauges to its proxy target.
        self.decode_engine = decode_engine
        self.push_url = push_url
        self.push_interval_s = float(push_interval_s)
        self._pusher = None
        # request-scoped span push (observability.distributed): a
        # bounded tracer sink drained into each heartbeat push, so the
        # aggregator's TraceStore can stitch this host's handler /
        # batcher / decode spans into per-request waterfalls. Built in
        # start() only when push_url is set; DL4J_TPU_TRACE=0 and
        # DL4J_TPU_TRACE_SAMPLE throttle it at the tracer.
        self._span_push = None
        # SLO engine (observability.slo): declared objectives evaluated
        # over this host's own ServingStats — gauge families on the
        # registry (scrape + federation push for free), and the
        # attainment summary stamped onto the drain RunReport by stop().
        from deeplearning4j_tpu.observability import slo as _slo
        if slos is None:
            slos = _slo.default_serving_slos(p99_bound_ms=float(
                os.environ.get("DL4J_TPU_SLO_P99_MS", "500")))
        self.slo_engine = _slo.SLOEngine(slos) if slos else None
        self._slo_collector = None
        # Live reload (SERVING.md §Live reload): the published weight
        # version currently serving (0 = boot weights, never hot-swapped)
        # and the swap counter — both pushed to the federation plane so
        # a router's canary gates can see WHICH version a host runs.
        self.model_version = 0
        self.swaps_total = 0
        #: strong ref to the hot-swapped (params, state) trees: the
        #: version-bound forward closures alias these on the device
        self._live_weights = None
        self._swap_lock = threading.Lock()

    @property
    def _batcher(self):
        """Replica 0's batcher — the seed single-batcher surface
        (tests patch ``server._batcher._forward``); routing and
        admission live on ``self._fleet``."""
        return self._fleet.replicas[0].batcher

    @property
    def fleet(self) -> ReplicaSet:
        return self._fleet

    # ------------------------------------------------------------ device side
    def _build_serving_net(self, compute_dtype):
        """A shadow net over the same configuration with only the
        policy's compute dtype replaced: structure-only init (no second
        parameter set is ever materialized — ``_device_forward`` aliases
        the primary net's live params/state each call, so a net that is
        still training serves its freshest weights)."""
        import dataclasses as _dc
        gc = self.net.conf.global_conf
        # dataclasses.replace re-runs DtypePolicy validation, so an
        # unknown dtype string fails here, at server build time
        gc2 = _dc.replace(gc, dtype=_dc.replace(
            gc.dtype, compute_dtype=compute_dtype))
        conf2 = _dc.replace(self.net.conf, global_conf=gc2)
        shadow = type(self.net)(conf2)
        shadow.init(structure_only=True)
        return shadow

    @property
    def serving_compute_dtype(self) -> str:
        """The dtype the serving forward actually computes in (the
        ``compute_dtype`` label on serving metrics)."""
        if self.compute_dtype is not None:
            return self.compute_dtype
        return self.net.conf.global_conf.dtype.compute_dtype

    def _device_forward(self, feats):
        """Model adapter run only on the batcher's device thread."""
        net = self.net
        if self._serving_net is not None:
            self._serving_net.params = self.net.params
            self._serving_net.state = self.net.state
            net = self._serving_net
        if self._is_graph:
            return net.output(*feats)
        return net.output(feats[0])

    # ----------------------------------------------------------- live reload
    def _versioned_forward(self, params, state):
        """A forward closure bound to PUBLISHED weights. The trick that
        makes a hot swap free: the serving net's jitted apply already
        takes ``(params, state)`` EXPLICITLY (multilayer._get_apply /
        graph.output), so a closure that calls the SAME jitted function
        with different trees reuses every compiled bucket executable —
        0 fresh compiles, and replicas mid-rolling-swap (some on the old
        version, some on the new) share one jit cache. Nothing on the
        live net is mutated, so there is no publication race with
        requests still finishing on the old weights."""
        import jax.numpy as jnp
        net = self._serving_net if self._serving_net is not None else self.net
        if self._is_graph:
            key = ("out", False, False)
            if key not in net._apply_fns:
                # build the graph's jitted output program exactly the
                # way net.output() would (it closes over structure, not
                # params) so swapped and unswapped paths share it
                import jax

                def fn(p, s, inputs, fmasks):
                    acts, _, _, _ = net._walk(p, s, inputs, train=False,
                                              rng=None, fmasks=fmasks)
                    return tuple(acts[o] for o in net.conf.network_outputs)
                net._apply_fns[key] = jax.jit(fn)

            def forward(feats):
                inputs, fmasks = net._prepare_inputs(
                    [jnp.asarray(f) for f in feats], None)
                outs = net._apply_fns[key](params, state, inputs, fmasks)
                return outs[0] if len(outs) == 1 else list(outs)
        else:
            def forward(feats):
                fn = net._get_apply(collect=False, train=False)
                return fn(params, state, jnp.asarray(feats[0]), None, None)
        return forward

    def hot_swap(self, publication=None, *, net=None, version=None):
        """Zero-downtime reload onto a published version: rolling
        ``swap_forward`` over every replica, each one publish-then-drain
        (fleet.py) — in-flight requests finish on the old weights while
        new admissions run the new ones, and at no instant is the
        replica out of routing. Decode sessions are not supported here
        (their KV caches are entangled with the old weights — drain the
        host and boot a new one off the shared compile cache instead;
        the router fails sessions over via bit-identical re-prefill),
        and mesh serving shards params at build time
        (parallel/inference.py), so it swaps by host replacement too.

        ``publication``: a serving.publish.Publication (its checkpoint
        is restored here unless a pre-restored ``net`` is passed). The
        publication's fingerprint must match the serving net's — same
        param pytree structure is what guarantees the jit-cache reuse.
        Returns a receipt dict: version, replicas swapped, wall time,
        and the XLA compile delta across the swap itself (0 on a warmed
        server — the budget-gated invariant)."""
        if self.mesh is not None:
            raise ValueError(
                "hot_swap is the single-host replica path; mesh serving "
                "shards params at build time — drain this host and boot "
                "a replacement off the shared compile cache instead")
        if self.decode_engine is not None:
            raise ValueError(
                "hot_swap cannot re-weight live decode sessions (KV "
                "caches hold old-weight state) — drain the host; the "
                "router re-prefills sessions onto survivors "
                "bit-identically")
        from deeplearning4j_tpu.compilecache.manifest import model_fingerprint
        from deeplearning4j_tpu.serving import publish as _publish
        with self._swap_lock:
            if publication is not None:
                if net is None:
                    net = _publish.load_net(publication.path)
                if version is None:
                    version = publication.version
                expect = publication.fingerprint
            else:
                if net is None:
                    raise ValueError("hot_swap needs a publication or a "
                                     "pre-restored net")
                expect = model_fingerprint(net)
            serving_fp = model_fingerprint(self.net)
            if expect is not None and expect != serving_fp:
                raise ValueError(
                    f"published fingerprint {expect} does not match the "
                    f"serving net's {serving_fp} — a hot swap can only "
                    "bind weights with the identical param structure "
                    "(different architecture ⇒ boot a new host)")
            # Checkpoint restore commits leaves to an explicit device
            # placement; the live net's params are uncommitted. jit keys
            # on that distinction, so feeding restored leaves straight in
            # retraces once per swap. Round-trip through host memory to
            # shed the committed placement and hit the existing cache.
            import jax
            import jax.numpy as jnp

            def _uncommit(tree):
                return jax.tree_util.tree_map(
                    lambda a: jnp.asarray(np.asarray(a)), tree)
            params = _uncommit(net.params)
            state = _uncommit(net.state) if net.state else {}
            forward = self._versioned_forward(params, state)
            compile0 = _obs_metrics.compile_snapshot()
            t0 = time.perf_counter()
            swapped = 0
            for r in list(self._fleet.replicas):
                if r.status == "dead":
                    continue  # an evicted slot keeps its slot semantics
                self._fleet.swap_forward(r.index, forward)
                swapped += 1
            self._live_weights = (params, state)
            self.model_version = int(version) if version is not None else \
                self.model_version + 1
            self.swaps_total += 1
            delta = _obs_metrics.compile_delta(compile0)
            return {"version": self.model_version,
                    "fingerprint": serving_fp,
                    "replicas_swapped": swapped,
                    "swap_s": round(time.perf_counter() - t0, 6),
                    "fresh_compiles": delta["count"]}

    def _infer_row_shapes(self):
        """Per-input row shapes (no batch dim) for warm-up, when they can
        be derived from the configuration; None disables warm-up."""
        if self.input_shapes is not None:
            return [tuple(s) for s in self.input_shapes]

        def from_itype(it):
            if it is None:
                return None
            if it.kind in ("feed_forward", "convolutional_flat"):
                return (it.size,)
            if it.kind == "convolutional":
                return (it.height, it.width, it.channels)
            if it.kind == "recurrent" and it.timesteps:
                return (it.timesteps, it.size)
            return None

        def from_conf(lc):
            from deeplearning4j_tpu.nn.conf.layers import (
                FeedForwardLayerConfig)
            from deeplearning4j_tpu.nn.conf.layers_recurrent import (
                BaseRecurrentConfig)
            if (isinstance(lc, FeedForwardLayerConfig)
                    and not isinstance(lc, BaseRecurrentConfig)
                    and getattr(lc, "n_in", None)):
                return (lc.n_in,)
            return None

        if self._is_graph:
            its = getattr(self.net.conf, "input_types", None)
            if its:
                shapes = [from_itype(it) for it in its]
                return None if any(s is None for s in shapes) else shapes
            shapes = []
            for name in self.net.conf.network_inputs:
                s = None
                for v, ins in self.net.conf.vertex_inputs.items():
                    if name in ins:
                        s = from_conf(self.net._resolved_confs.get(v))
                        if s is not None:
                            break
                if s is None:
                    return None
                shapes.append(s)
            return shapes
        s = from_itype(getattr(self.net.conf, "input_type", None))
        if s is None and getattr(self.net.conf, "layers", None):
            s = from_conf(self.net.conf.layers[0])
        return None if s is None else [s]

    # ------------------------------------------------------------ inference
    def predict(self, features, trace_id=None, klass=None, tenant=None,
                deadline_ms=None):
        """Enqueue the request into the micro-batcher and wait for the
        scattered result rows. Requests larger than ``max_batch`` are
        split into ``max_batch`` chunks so they reuse the already-compiled
        full-bucket program instead of compiling a fresh XLA executable of
        arbitrary shape. ``features``: one array (sequential net) or list
        of arrays (graph). ``trace_id`` propagates onto the batcher span
        attrs (the HTTP handler passes the client's ``X-DL4J-Trace-Id``);
        ``klass`` / ``tenant`` / ``deadline_ms`` are the scheduling
        headers (X-DL4J-Priority / -Tenant / -Deadline-Ms) threaded into
        fleet admission the same way. Raises QueueFullError (or its
        ShedError subclass naming the shed class) when admission control
        rejects (mapped to HTTP 503)."""
        t0 = time.perf_counter()
        many = isinstance(features, (list, tuple))
        if many and not self._is_graph and len(features) != 1:
            raise ValueError(
                "this model takes ONE features array — use the "
                '{"features": [...]} payload (the "inputs" list form is '
                "for multi-input graphs)")
        feats = [np.asarray(f, np.float32)
                 for f in (features if many else [features])]
        n = feats[0].shape[0]
        if any(f.shape[0] != n for f in feats):
            raise ValueError("all inputs must have the same number of rows")
        self._fleet.start()  # idempotent; lazy for direct predict() use
        futures = [self._fleet.submit(
                       [f[i:i + self.max_batch] for f in feats],
                       trace_id=trace_id, klass=klass, tenant=tenant,
                       deadline_ms=deadline_ms)
                   for i in range(0, max(n, 1), self.max_batch)]
        # one deadline for the whole request, not per chunk: the budget
        # left after chunk k is what chunk k+1 may spend
        deadline = t0 + self.request_timeout_s
        chunks = []
        for f in futures:
            try:
                chunks.append(f.result(
                    timeout=max(0.0, deadline - time.perf_counter())))
            except _FutureTimeout:
                self.stats.record_timeout()
                raise DeadlineExceededError(
                    f"request exceeded {self.request_timeout_s:g}s "
                    "deadline") from None
        if isinstance(chunks[0], list):
            out = [np.concatenate([c[k] for c in chunks])
                   if len(chunks) > 1 else chunks[0][k]
                   for k in range(len(chunks[0]))]
        else:
            out = (np.concatenate(chunks) if len(chunks) > 1 else chunks[0])
        # serving NaN sentinel: count reply rows carrying non-finite
        # values. The reply is still served (a canary's whole point is
        # measuring the bad version on real traffic) — the counter rides
        # the federation push, where the router's promotion gates kill
        # the version before it leaves its traffic fraction.
        nan_rows = 0
        for a in (out if isinstance(out, list) else [out]):
            a = np.asarray(a)
            flat = a.reshape(a.shape[0], -1) if a.ndim > 1 \
                else a.reshape(-1, 1)
            nan_rows += int((~np.isfinite(flat).all(axis=1)).sum())
        if nan_rows:
            self.stats.record_nan_rows(nan_rows)
        self.stats.record_request(n, time.perf_counter() - t0)
        return out

    # -------------------------------------------------------------- server
    def _validate_aot_manifest(self, row_shapes):
        """Check the precompile manifest (explicit path/dict, or
        auto-located in the cache dir) against THIS boot's serving
        config. A mismatch means the cached executables were built for
        a different program: warn — loudly, a boot that believes it is
        warm but compiles fresh is a silent perf regression — and fall
        back to lazy compile. Never raises; sets ``aot_manifest_ok``."""
        import warnings

        from deeplearning4j_tpu.compilecache import manifest as _man
        from deeplearning4j_tpu.serving.batcher import bucket_ladder
        src = self.aot_manifest
        if src is None and self.compile_cache_dir is not None:
            auto = os.path.join(self.compile_cache_dir, _man.MANIFEST_NAME)
            if os.path.exists(auto):
                src = auto
        if src is None:
            return
        try:
            man = src if isinstance(src, dict) else _man.load(src)
            mb = self._batcher
            mismatches = _man.validate_serving(
                man, self.net, row_shapes=row_shapes or (),
                ladder=bucket_ladder(mb.min_batch, mb.max_batch),
                max_batch=mb.max_batch, min_batch=mb.min_batch,
                compute_dtype=self.serving_compute_dtype, mesh=self.mesh)
        except Exception as e:
            mismatches = [f"unreadable manifest: {type(e).__name__}: {e}"]
        self.aot_manifest_ok = not mismatches
        if mismatches:
            warnings.warn(
                "AOT precompile manifest does not match this serving "
                "config — falling back to lazy compile (this boot pays "
                "fresh XLA compiles): " + "; ".join(mismatches),
                RuntimeWarning, stacklevel=3)

    def start(self):
        server = self

        # compile baseline taken BEFORE warm-up, so the serving RunReport
        # charges the warm-up ladder's compiles (and cache hits/misses)
        # to this run — that delta is exactly what a warm cache zeroes
        compile0 = _obs_metrics.compile_snapshot()
        if self.warmup:
            shapes = self._infer_row_shapes()
            self._validate_aot_manifest(shapes)
            if shapes is not None:
                t_warm = time.perf_counter()
                try:
                    # hoisted: one ladder per distinct forward, however
                    # many replicas share it (fleet.warm)
                    self._fleet.warm(shapes)
                    self.warmup_s = round(time.perf_counter() - t_warm, 6)
                except Exception:
                    # warm-up is an optimization: a shape-inference miss
                    # must never block serving (first requests compile
                    # lazily, exactly as the seed server did)
                    self.shapes_seen.clear()
        self._fleet.start()

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: closed-loop clients reuse their
            # connection instead of paying a TCP handshake per request
            # (every reply carries Content-Length, so this is safe).
            # Nagle off, or the two-segment request/reply pattern hits
            # the 40 ms delayed-ACK stall on every round trip.
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def log_message(self, *args):
                pass

            def _json(self, obj, code=200, headers=()):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _text(self, text, code=200,
                      content_type=_obs_metrics.PROMETHEUS_CONTENT_TYPE):
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path.startswith("/healthz"):
                    rows = server._fleet.describe()
                    if not server._fleet.healthy:
                        # every device thread dead means every /predict
                        # would hang or 503 — report down so the load
                        # balancer stops routing here
                        self._json({"status": "unhealthy",
                                    "reason": "batcher device thread dead",
                                    "replicas": rows}, 503)
                        return
                    n_live = sum(1 for r in rows if r["status"] == "live")
                    # some replicas down but traffic still flows:
                    # degraded, not down — the router keeps the node but
                    # the scoreboard shows the hole
                    self._json({"status": ("ok" if n_live == len(rows)
                                           else "degraded"),
                                "params": int(server.net.num_params()),
                                "graph": server._is_graph,
                                "model_version": server.model_version,
                                "replicas": rows})
                elif self.path.startswith("/metrics"):
                    if "format=snapshot" in self.path:
                        # federation wire form: full-fidelity families +
                        # identity + health, for an aggregator's scrape
                        from deeplearning4j_tpu.observability import \
                            distributed as _dist
                        self._json(_dist.export_snapshot(
                            health={"batcher_healthy":
                                    server._fleet.healthy,
                                    "replicas":
                                    server._fleet.describe()}))
                    elif _obs_metrics.wants_prometheus(
                            self.headers.get("Accept", ""), self.path):
                        # the full unified registry (serving + resilience
                        # + compile + device-memory series), not just the
                        # serving slice — one scrape sees the process
                        self._text(_obs_metrics.get_registry()
                                   .render_prometheus())
                    else:
                        self._json(server.metrics())
                else:
                    self._json({"error": "not found"}, 404)

            def _decode_op(self, payload, trace_id=None):
                """Host half of the cross-host decode protocol: the
                request always carries the session's full token history
                (``ids``), so a ``step`` for a sid this host has never
                seen — a router failover after another host died — is
                answered by re-prefilling from that history first. The
                re-prefill is bit-identical to the steps it replaces
                (serving/decode.py), so the reply is too. ``trace_id``
                threads through to the engine's prefill/step/verify
                spans and batcher tickets. An unknown sid with no
                history raises UnknownSessionError — HTTP 404, distinct
                from the 400 a malformed op earns."""
                eng = server.decode_engine
                op = payload.get("op")
                sid = payload["sid"]
                if op == "prefill":
                    logits = eng.prefill(sid, payload["ids"],
                                         trace_id=trace_id)
                    return {"logits": np.asarray(logits).tolist()}
                if op == "step":
                    recovered = False
                    if sid not in eng.sessions:
                        ids = payload.get("ids") or ()
                        if not ids:
                            raise UnknownSessionError(
                                f"unknown decode session '{sid}' and no "
                                "ids history to recover from")
                        eng.prefill(sid, ids, trace_id=trace_id)
                        recovered = True
                    logits = eng.step(sid, int(payload["token"]),
                                      trace_id=trace_id)
                    return {"logits": np.asarray(logits).tolist(),
                            "recovered": recovered}
                if op == "generate":
                    # multi-token op: the host runs the whole greedy
                    # loop (speculative rounds when the engine has a
                    # draft), so speculation's launch savings survive
                    # the wire — a per-step protocol would serialize
                    # every token through a round trip
                    ids = payload.get("ids") or ()
                    if not ids:
                        raise KeyError(
                            f"decode generate for '{sid}' needs ids")
                    toks = eng.generate(sid, [int(i) for i in ids],
                                        int(payload.get("n_tokens", 0)),
                                        trace_id=trace_id)
                    return {"tokens": [int(t) for t in toks],
                            "speculative": bool(eng.spec_k)}
                if op == "close":
                    return {"closed": eng.close_session(sid)}
                raise ValueError(f"unknown decode op {op!r}")

            def do_POST(self):  # noqa: N802
                is_decode = (self.path.startswith("/decode")
                             and server.decode_engine is not None)
                if not self.path.startswith("/predict") and not is_decode:
                    self._json({"error": "not found"}, 404)
                    return
                # trace-context propagation: accept the client's id (or
                # mint one) so batcher spans carry it, and echo it back
                # so the client can stitch both timelines together
                from deeplearning4j_tpu.observability import \
                    distributed as _dist
                from deeplearning4j_tpu.scheduling import core as _sched
                trace_id = (self.headers.get(_dist.TRACE_HEADER)
                            or _dist.new_trace_id())
                echo = ((_dist.TRACE_HEADER, trace_id),)
                # scheduling-context propagation, same contract: the
                # tenant/priority/deadline headers thread into fleet
                # admission and echo back normalized
                sched = _sched.parse_sched_headers(self.headers)
                echo += ((_sched.PRIORITY_HEADER, sched["klass"]),)
                if sched["tenant"]:
                    echo += ((_sched.TENANT_HEADER, sched["tenant"]),)
                if sched["deadline_ms"] is not None:
                    echo += ((_sched.DEADLINE_HEADER,
                              f"{sched['deadline_ms']:g}"),)
                # one handler span per request, trace-tagged and
                # carrying server_url — the span the aggregator's
                # TraceStore centers inside the router's send/recv hop
                # window to rebase this host's clock (error paths
                # included: a failed request still explains its time)
                with _obs_trace.get_tracer().span(
                        "decode_op" if is_decode else "predict_handler",
                        trace_id=trace_id, server_url=server.url):
                    self._handle_post(is_decode, trace_id, echo, sched)

            def _handle_post(self, is_decode, trace_id, echo, sched):
                from deeplearning4j_tpu.scheduling.core import (
                    SHED_CLASS_HEADER, ShedError)
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n).decode())
                    if is_decode:
                        self._json(self._decode_op(payload,
                                                   trace_id=trace_id),
                                   headers=echo)
                        return
                    if "inputs" in payload:
                        out = server.predict([np.asarray(a) for a in
                                              payload["inputs"]],
                                             trace_id=trace_id, **sched)
                    else:
                        out = server.predict(np.asarray(payload["features"]),
                                             trace_id=trace_id, **sched)
                    if isinstance(out, list):
                        preds = [np.asarray(o).tolist() for o in out]
                    else:
                        preds = np.asarray(out).tolist()
                    self._json({"predictions": preds}, headers=echo)
                except QueueFullError as e:
                    # backpressure: shed load instead of growing the
                    # queue. Retry-After is DERIVED: current backlog over
                    # the observed drain rate, clamped to [0.05s, 5s] —
                    # a fast-draining fleet calls clients back sooner.
                    # X-DL4J-Shed-Class names WHICH class was shed (the
                    # ShedError knows; a legacy full-queue reject sheds
                    # the request's own class) so load tests can verify
                    # batch sheds before interactive.
                    shed_k = e.klass if isinstance(e, ShedError) \
                        else sched["klass"]
                    if not isinstance(e, ShedError) \
                            and server.scheduler is not None:
                        server.scheduler.record_shed(shed_k)
                    self._json({"error": f"overloaded: {e}"}, 503,
                               headers=(("Retry-After",
                                         f"{server.stats.retry_after_s():g}"
                                         ),
                                        (SHED_CLASS_HEADER, shed_k)) + echo)
                except BatcherDeadError as e:
                    # dead device thread: same 503 the health check gives
                    self._json({"error": f"unhealthy: {e}"}, 503,
                               headers=echo)
                except DeadlineExceededError as e:
                    self._json({"error": str(e)}, 504, headers=echo)
                except UnknownSessionError as e:
                    # routing miss, not a malformed request: the router
                    # recovers by re-prefill elsewhere, so it is not
                    # counted against this host's error budget
                    self._json({"error": str(e)}, 404, headers=echo)
                except Exception as e:  # surface as a 400, keep serving
                    server.stats.record_error()
                    self._json({"error": f"{type(e).__name__}: {e}"}, 400,
                               headers=echo)

        self._httpd = _ServingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        _obs_metrics.install_runtime_metrics()
        self.stats.attach_to_registry(
            labels={"server": f"{self.host}:{self.port}",
                    "compute_dtype": self.serving_compute_dtype},
            shapes_fn=lambda: self.shapes_seen)
        self._attach_fleet_collector()
        self._attach_decode_collector()
        self._attach_slo_collector()
        self._attach_sched_collector()
        self._ledger = _goodput.start_run("serving", net=self.net)
        self._ledger.rebase_compile(compile0)
        if self.warmup_s is not None:
            self._ledger.annotate(warmup_s=self.warmup_s)
        from deeplearning4j_tpu.observability import distributed as _dist
        _dist.stamp_run_marker("serving")
        import threading
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        if self.push_url:
            # worker-fleet -> router federation heartbeat: retry is ON
            # (attempts=3, jittered backoff) so a router restart costs
            # one delayed push, not this host's scoreboard row.
            # Trace-tagged spans ride the same pushes (SpanPushBuffer
            # drains into the snapshot's "spans" key) so the router can
            # stitch per-request waterfalls without a second wire.
            self._span_push = _dist.SpanPushBuffer().install()
            self._pusher = _dist.HeartbeatPusher(
                self.push_url, self.push_interval_s,
                health_fn=self._push_health,
                spans_fn=self._span_push.payload).start()
        return self

    def _push_health(self) -> dict:
        """The health payload each federation push carries: readiness
        plus ``server_url`` — the key a FrontDoorRouter joins pushed
        gauges to its proxy target by."""
        snap = self.stats.snapshot(self.shapes_seen)
        health = {"batcher_healthy": self._fleet.healthy,
                  "server_url": self.url,
                  "model_version": self.model_version,
                  "replicas": self._fleet.describe(),
                  # the canary-gate slice: the few counters a router's
                  # promotion gates difference against their baseline
                  # (serving/router.py start_canary/evaluate_canary)
                  "serving": {
                      "requests_total": snap["requests_total"],
                      "errors_total": snap["errors_total"],
                      "timeouts_total": snap["timeouts_total"],
                      "nan_rows_total": snap["nan_rows_total"],
                      "latency_p99_ms": snap["latency_ms"]["p99"],
                  }}
        if self.decode_engine is not None:
            health["decode"] = self.decode_engine.describe()
        return health

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def metrics(self) -> dict:
        """ServingStats snapshot (same payload as ``GET /metrics``),
        plus the per-replica health rows and eviction-requeue count."""
        snap = self.stats.snapshot(self.shapes_seen)
        snap["replicas"] = self._fleet.describe()
        snap["requeued_total"] = self._fleet.requeued
        snap["model_version"] = self.model_version
        snap["weight_swaps_total"] = self.swaps_total
        if self.scheduler is not None:
            snap["sched"] = self.scheduler.snapshot()
        if self.decode_engine is not None:
            snap["decode"] = self.decode_engine.describe()
        return snap

    def _attach_fleet_collector(self):
        """Per-replica gauges on the unified registry. Each replica gets
        its own ``instance`` label, ``<identity.tag>/r<k>`` — the same
        key scheme the federation aggregator files instances under, so a
        merged fleet view distinguishes replicas without a new label
        vocabulary. Distinct family names (``dl4j_serving_replica_*``)
        keep the exposition free of duplicate-family clashes with the
        fleet-total serving series."""
        from deeplearning4j_tpu.observability import distributed as _dist
        from deeplearning4j_tpu.observability.metrics import MetricFamily
        score = {"live": 1.0, "draining": 0.5, "dead": 0.0}
        addr = f"{self.host}:{self.port}"

        def _collect():
            tag = _dist.get_identity().tag
            depth = MetricFamily(
                "dl4j_serving_replica_queue_depth", "gauge",
                "Tickets pending per fleet replica (the routing signal)")
            up = MetricFamily(
                "dl4j_serving_replica_up", "gauge",
                "Replica status: 1 live, 0.5 draining, 0 dead")
            for row in self._fleet.describe():
                labels = {"instance": f"{tag}/r{row['replica']}",
                          "server": addr}
                depth.add(row["queue_depth"], labels)
                up.add(score.get(row["status"], 0.0),
                       {**labels, "status": row["status"]})
            requeued = MetricFamily(
                "dl4j_serving_requeued_total", "counter",
                "Tickets resubmitted onto survivors after an eviction")
            requeued.add(self._fleet.requeued, {"server": addr})
            version = MetricFamily(
                "dl4j_serving_model_version", "gauge",
                "Published weight version currently serving (0 = boot "
                "weights, never hot-swapped)")
            version.add(self.model_version, {"server": addr})
            swaps = MetricFamily(
                "dl4j_serving_weight_swaps_total", "counter",
                "Completed zero-downtime weight hot swaps")
            swaps.add(self.swaps_total, {"server": addr})
            return [depth, up, requeued, version, swaps]

        reg = _obs_metrics.get_registry()
        reg.register_collector(_collect)
        self._fleet_collector = (reg, _collect)

    def _attach_decode_collector(self):
        """Decode/KV-pool gauges (shared pages, dedup ratio, chunked
        prefills) on the unified registry — present only when a decode
        engine rides this server. ``export_snapshot`` reads the same
        registry, so these series reach the federation wire form with
        no extra plumbing."""
        if self.decode_engine is None:
            return
        from deeplearning4j_tpu.serving.metrics import decode_metric_families
        addr = f"{self.host}:{self.port}"

        def _collect():
            return decode_metric_families(self.decode_engine.describe(),
                                          {"server": addr})

        reg = _obs_metrics.get_registry()
        reg.register_collector(_collect)
        self._decode_collector = (reg, _collect)

    def _attach_slo_collector(self):
        """SLO gauge families on the unified registry. The collector
        ingests a fresh stats snapshot per render, so every scrape (and
        every federation push, which reads the same registry) advances
        the sliding windows — scrape-driven evaluation, the standard
        Prometheus shape."""
        if self.slo_engine is None:
            return

        def _collect():
            self.slo_engine.ingest(self.stats.snapshot(self.shapes_seen))
            return self.slo_engine.families()

        reg = _obs_metrics.get_registry()
        reg.register_collector(_collect)
        self._slo_collector = (reg, _collect)

    def _attach_sched_collector(self):
        """``dl4j_sched_*`` families (per-class admitted/shed counters,
        per-tenant quota-token gauges) on the unified registry — the
        satellite contract that lets a load test watch batch shed while
        interactive is admitted. Federation pushes read the same
        registry, so the router sees these series for free."""
        if self.scheduler is None:
            return
        addr = f"{self.host}:{self.port}"

        def _collect():
            return self.scheduler.metric_families({"server": addr})

        reg = _obs_metrics.get_registry()
        reg.register_collector(_collect)
        self._sched_collector = (reg, _collect)

    def stop(self):
        """Stop accepting, then drain: every accepted ticket completes
        before the device thread exits. Closes the serving goodput
        ledger — ``self.run_report`` holds the RunReport afterwards."""
        if self._pusher is not None:
            self._pusher.stop()
            self._pusher = None
        if self._span_push is not None:
            self._span_push.remove()
            self._span_push = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self.decode_engine is not None:
            self.decode_engine.stop()
        self._fleet.stop()
        self.stats.detach_from_registry()
        if self._fleet_collector is not None:
            reg, collect = self._fleet_collector
            reg.unregister_collector(collect)
            self._fleet_collector = None
        if self._decode_collector is not None:
            reg, collect = self._decode_collector
            reg.unregister_collector(collect)
            self._decode_collector = None
        if self._slo_collector is not None:
            reg, collect = self._slo_collector
            reg.unregister_collector(collect)
            self._slo_collector = None
        if self._sched_collector is not None:
            reg, collect = self._sched_collector
            reg.unregister_collector(collect)
            self._sched_collector = None
        ledger = getattr(self, "_ledger", None)
        if ledger is not None and self.slo_engine is not None:
            # final ingest + stamp: the drain report carries the run's
            # SLO attainment next to its goodput numbers
            self.slo_engine.ingest(self.stats.snapshot(self.shapes_seen))
            ledger.annotate(slo=self.slo_engine.report())
        if ledger is not None and self.stats.first_reply_unix is not None:
            # time-to-first-reply from PROCESS start (kernel starttime):
            # imports + model build + compiles + warm-up, the whole cold
            # bill — not just the slice since this server object existed
            ledger.annotate(cold_start_s=round(
                self.stats.first_reply_unix
                - _obs_metrics.process_start_unix(), 6))
        report = _goodput.end_run(ledger)
        if report is not None:  # stop() is idempotent; keep the first
            self.run_report = report


def serve(net, host: str = "127.0.0.1", port: int = 9500,
          max_batch: int = 1024, batch_window_ms: float = 2.0,
          max_queue: int = 1024, warmup: bool = True,
          input_shapes=None, request_timeout_s: float = 300.0,
          compute_dtype=None, replicas: int = 1, mesh=None,
          model_axis: str = "model", data_axis=None,
          tp_rules=None, compile_cache_dir=None, aot_manifest=None,
          tuning_report=None, decode_engine=None, push_url=None,
          push_interval_s: float = 2.0, slos=None,
          scheduler=None) -> ModelServer:
    """One-call serving entry point: ``serve(net).url`` is live."""
    return ModelServer(net, host, port, max_batch,
                       batch_window_ms=batch_window_ms, max_queue=max_queue,
                       warmup=warmup, input_shapes=input_shapes,
                       request_timeout_s=request_timeout_s,
                       compute_dtype=compute_dtype, replicas=replicas,
                       mesh=mesh, model_axis=model_axis,
                       data_axis=data_axis, tp_rules=tp_rules,
                       compile_cache_dir=compile_cache_dir,
                       aot_manifest=aot_manifest,
                       tuning_report=tuning_report,
                       decode_engine=decode_engine, push_url=push_url,
                       push_interval_s=push_interval_s,
                       slos=slos, scheduler=scheduler).start()
