"""Batched HTTP inference server.

Parity surface: DL4jServeRouteBuilder.java:27,64 (deserialize record ->
``Model.output()`` -> publish). TPU-native design:

- ONE jitted forward per padded batch-bucket: request batches are padded
  up to the next power-of-two bucket (capped at ``max_batch``) so XLA
  compiles a handful of shapes once instead of one program per request
  size — then rows beyond the real batch are sliced off the reply.
- Works for MultiLayerNetwork (single ``features`` array) and
  ComputationGraph (list under ``inputs``; multi-output replies are
  lists).

Endpoints:
- ``POST /predict``  {"features": [[...]]} or {"inputs": [[[...]], ...]}
  -> {"predictions": ...}
- ``GET /healthz``   liveness + model summary sizes
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np


def _next_bucket(n: int, max_batch: int) -> int:
    """Power-of-two bucket, capped at ``max_batch``. Requests larger than
    ``max_batch`` are CHUNKED by the caller (never compiled at raw size —
    one oversized POST must not grow the XLA compile cache; the reference
    route consumes any-size payloads the same way,
    DL4jServeRouteBuilder.java:64)."""
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


class ModelServer:
    def __init__(self, net, host: str = "127.0.0.1", port: int = 9500,
                 max_batch: int = 1024):
        self.net = net
        self.host = host
        self.port = port
        self.max_batch = max_batch
        self._httpd = None
        self._thread = None
        self._lock = threading.Lock()
        # every distinct padded batch shape handed to the device — the
        # compile count is bounded by len(shapes_seen) (asserted by the
        # serving concurrency test)
        self.shapes_seen: set[int] = set()
        self._is_graph = hasattr(net, "conf") and hasattr(
            net.conf, "network_inputs")

    # ------------------------------------------------------------ inference
    def predict(self, features):
        """Pad to the bucket size, run the jitted forward, slice back.
        Requests larger than ``max_batch`` are split into ``max_batch``
        chunks so they reuse the already-compiled full-bucket program
        instead of compiling a fresh XLA executable of arbitrary shape.
        ``features``: one array (sequential net) or list of arrays (graph).
        Serialized under a lock — device execution is the shared
        resource; HTTP threads queue here."""
        many = isinstance(features, (list, tuple))
        if many and not self._is_graph and len(features) != 1:
            raise ValueError(
                "this model takes ONE features array — use the "
                '{"features": [...]} payload (the "inputs" list form is '
                "for multi-input graphs)")
        feats = [np.asarray(f, np.float32)
                 for f in (features if many else [features])]
        n = feats[0].shape[0]
        if n > self.max_batch:
            chunks = [self._predict_bucketed(
                          [f[i:i + self.max_batch] for f in feats])
                      for i in range(0, n, self.max_batch)]
            if isinstance(chunks[0], list):
                return [np.concatenate([c[k] for c in chunks])
                        for k in range(len(chunks[0]))]
            return np.concatenate(chunks)
        return self._predict_bucketed(feats)

    def _predict_bucketed(self, feats):
        n = feats[0].shape[0]
        bucket = _next_bucket(n, self.max_batch)
        if bucket != n:
            feats = [np.pad(f, [(0, bucket - n)] + [(0, 0)] * (f.ndim - 1))
                     for f in feats]
        self.shapes_seen.add(bucket)
        with self._lock:
            if self._is_graph:
                out = self.net.output(*feats)
            else:
                out = self.net.output(feats[0])
        if isinstance(out, (list, tuple)):
            return [np.asarray(o)[:n] for o in out]
        return np.asarray(out)[:n]

    # -------------------------------------------------------------- server
    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path.startswith("/healthz"):
                    self._json({"status": "ok",
                                "params": int(server.net.num_params()),
                                "graph": server._is_graph})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):  # noqa: N802
                if not self.path.startswith("/predict"):
                    self._json({"error": "not found"}, 404)
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n).decode())
                    if "inputs" in payload:
                        out = server.predict([np.asarray(a) for a in
                                              payload["inputs"]])
                    else:
                        out = server.predict(np.asarray(payload["features"]))
                    if isinstance(out, list):
                        preds = [o.tolist() for o in out]
                    else:
                        preds = out.tolist()
                    self._json({"predictions": preds})
                except Exception as e:  # surface as a 400, keep serving
                    self._json({"error": f"{type(e).__name__}: {e}"}, 400)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def serve(net, host: str = "127.0.0.1", port: int = 9500,
          max_batch: int = 1024) -> ModelServer:
    """One-call serving entry point: ``serve(net).url`` is live."""
    return ModelServer(net, host, port, max_batch).start()
