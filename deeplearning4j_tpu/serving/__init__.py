"""Model serving (dl4j-streaming parity tier).

The reference's serving story is a Camel route consuming Kafka and
calling ``Model.output()``
(dl4j-streaming/.../routes/DL4jServeRouteBuilder.java:27, route :64).
SURVEY.md §7 sanctions the TPU-idiomatic substitution: an HTTP
inference endpoint over the jitted ``output()`` — Kafka/Camel plumbing
is environment integration, not framework capability. On top of that
seam sits a continuous micro-batching runtime (serving/batcher.py):
cross-request coalescing into padded power-of-two bucket forwards,
bounded-queue backpressure, warm-up precompile, and ``/metrics``
observability (serving/metrics.py). See SERVING.md.
"""

from deeplearning4j_tpu.serving.batcher import (BatcherDeadError,
                                                MicroBatcher, QueueFullError)
from deeplearning4j_tpu.serving.decode import (DecodeEngine, DecodeSession,
                                               StreamingKVForward)
from deeplearning4j_tpu.serving.fleet import Replica, ReplicaSet
from deeplearning4j_tpu.serving.kvcache import CachePoolFullError, KVPagePool
from deeplearning4j_tpu.serving.metrics import ServingStats
from deeplearning4j_tpu.serving.publish import (Publication, WeightStore,
                                                load_net)
from deeplearning4j_tpu.serving.router import (FrontDoorRouter, HostHandle,
                                               NoHostsError)
from deeplearning4j_tpu.serving.server import (DeadlineExceededError,
                                               ModelServer, serve)

__all__ = ["ModelServer", "serve", "MicroBatcher", "QueueFullError",
           "BatcherDeadError", "DeadlineExceededError", "ServingStats",
           "Replica", "ReplicaSet", "DecodeEngine", "DecodeSession",
           "StreamingKVForward", "KVPagePool", "CachePoolFullError",
           "FrontDoorRouter", "HostHandle", "NoHostsError",
           "WeightStore", "Publication", "load_net"]
