"""Model serving (dl4j-streaming parity tier).

The reference's serving story is a Camel route consuming Kafka and
calling ``Model.output()``
(dl4j-streaming/.../routes/DL4jServeRouteBuilder.java:27, route :64).
SURVEY.md §7 sanctions the TPU-idiomatic substitution: a thin batched
HTTP inference endpoint over the jitted ``output()`` — Kafka/Camel
plumbing is environment integration, not framework capability.
"""

from deeplearning4j_tpu.serving.server import ModelServer, serve

__all__ = ["ModelServer", "serve"]
