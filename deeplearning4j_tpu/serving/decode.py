"""Autoregressive decode serving: prefill/decode phase split over the
micro-batcher, session state in a paged KV pool, session-affine routing.

The transformer streaming path (nn/layers/attention.py) is a pure
function of (params, cache state, next tokens) — so decode serving rides
the EXISTING batching runtime unchanged by making the per-session cache
part of the ticket:

- **Phase split for free.** ``MicroBatcher`` coalesces only tickets
  whose per-input row shapes match. Prefill tickets are
  ``[x [1, T, V], mask [1, T]]`` and decode tickets are ``[x [1, 1, V],
  *cache leaves]`` — different arity and shapes, so the batcher's own
  compatibility key IS the prefill/decode bucket split: decode steps
  from many sessions coalesce into one bucket-B single-token forward,
  prompts coalesce with same-length prompts, and neither phase ever
  pads against the other.
- **Prompt length ladder.** Prompts are right-padded (mask-marked) to a
  power-of-two rung so nearby lengths share one compile AND one batch;
  the one-shot masked prefill is bit-identical to feeding the prompt
  token-by-token (the fixed-extent-cache contract, ops/attention.py),
  so the padding is purely a throughput lever.
- **Chunked prefill (PR 16).** A long prompt head-of-line-blocks every
  running decode step sharing the replica, so prefill is split into
  page-aligned chunks (``DL4J_TPU_PREFILL_CHUNK_PAGES`` pages each,
  default 1, ``0`` = kill switch): the first chunk is an ordinary
  masked prefill, each later chunk is an EXTEND ticket
  ``[x [1,s,V], mask [1,s], *cache leaves]`` that advances the cache
  from its current frontier. Decode steps from other sessions dispatch
  between a session's chunk tickets, capping inter-token p99 at one
  chunk's latency instead of one prompt's. Chunk buckets ride the same
  power-of-two rung ladder (all rungs pre-warmed), so the compile count
  stays flat; masked extension from a mid-sequence frontier is
  bit-identical by the fixed-extent contract — padded positions land
  beyond the new frontier and are never attended before being
  overwritten.
- **Prefix sharing (PR 16).** Sessions opening with the same system
  prompt adopt each other's sealed cache pages: ``KVPagePool`` keys
  full pages by exact token history, ``prefill`` asks
  ``match_prefix`` for the longest resident chain, reconstructs the
  cache frontier from the shared pages, and extends from there —
  skipping the shared tokens' prefill compute entirely and storing each
  shared page once (``prefix_sharing=`` kwarg / pool flag, default on).
- **State travels with the ticket.** Each session's cache leaves (per
  layer: k/v [1, C, H, dh] f32 + pos [1] i32) are host rows concatenated
  by the batcher exactly like features, and the forward returns the
  advanced leaves which are sliced back per row. The forward itself
  stays stateless → replicas stay interchangeable, and the fleet's
  eviction/requeue machinery applies to decode tickets unchanged.
- **Session affinity is a routing hint, not a correctness need.**
  ``ReplicaSet.submit(..., session=sid)`` pins a session's steps to one
  replica (warm jit cache, stable latency); on replica death the
  affinity map rebinds and the ticket requeues — state rode the ticket,
  so nothing is lost.
- **Paged pool + recoverable eviction.** Between steps the leaves live
  in a ``KVPagePool`` charged in ``page_tokens`` blocks; when the pool
  evicts an idle session, its token history (kept here, tiny) is
  re-prefilled on its next step — bit-identical recovery, counted in
  ``reprefills``.
- **Speculative decoding (PR 18).** ``generate`` with ``draft_net=`` +
  ``speculative=k`` (env ``DL4J_TPU_SPECULATIVE_K``, 0 = kill switch,
  default off) replaces k single-token target launches per round with k
  cheap draft steps plus ONE batched verify forward (the mask-first
  all-position-logits extend variant). Acceptance is exact argmax match
  against the target's own logits — the first mismatch truncates the
  round, the target's logits row supplies the corrected token, and the
  rejected positions roll back (``KVPagePool.truncate``) — so the
  emitted stream is BIT-IDENTICAL to plain greedy decode; only the
  launch count changes. Verify buckets are explicit rungs on the warm
  ladder, so the post-warm compile delta stays 0.

Numeric contract (PRECISION.md / PERF.md §14): everything inside the
streaming tier — prefill, chunk, step, pool round-trip, re-prefill after
eviction — is BIT-IDENTICAL; streaming vs the training forward
(``net.output``) carries the usual compute-dtype TOLERANCE contract.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax import numpy as jnp

from deeplearning4j_tpu.analysis.guards import guarded_by
from deeplearning4j_tpu.observability.metrics import DEFAULT_BUCKETS
from deeplearning4j_tpu.observability.trace import get_tracer as _get_tracer
from deeplearning4j_tpu.serving.batcher import next_bucket
from deeplearning4j_tpu.serving.fleet import ReplicaSet
from deeplearning4j_tpu.serving.kvcache import KVPagePool

__all__ = ["StreamingKVForward", "DecodeEngine", "DecodeSession"]


class StreamingKVForward:
    """Stateless feats-list forward over a streaming net, shaped for
    ``MicroBatcher``: feats arity IS the phase — 2 inputs = prefill,
    1 + n_carries = decode, 2 + n_carries = extend (chunked prefill).

    Prefill ``[x [b,T,V], mask [b,T]]`` runs the masked one-shot
    streaming forward from a fresh fixed-extent cache and returns
    ``[last-real-token logits [b,V], *cache leaves]``. Decode
    ``[x [b,1,V], *cache leaves]`` advances every row's cache one token
    and returns ``[logits [b,V], *new leaves]``. Extend
    ``[x [b,s,V], mask [b,s], *cache leaves]`` is the chunked-prefill
    op: it advances each row's EXISTING cache by its masked segment from
    the row's current frontier and returns the segment's last-real-token
    logits plus the new leaves — bit-identical to feeding those tokens
    one by one (mask-padded rows write only beyond their new frontier,
    which later writes overwrite before anything attends there). Verify
    ``[mask [b,s], x [b,s,V], *cache leaves]`` — MASK-FIRST, which is
    what marks it at the same arity as extend — is the all-position-
    logits extend variant for speculative decode: one batched forward
    advances the cache by the whole draft-proposed segment and returns
    ``[logits [b,s,V], *new leaves]``, the next-token logits at EVERY
    fed position, so the target can judge all k proposals from a single
    launch. Each row of that logits tensor is bit-identical to what the
    single-token decode op would have produced at that position (the
    same fixed-extent contract as extend), which is what makes exact-
    argmax acceptance equal plain greedy decode. Leaves flatten in
    deterministic (sorted-key) pytree order; warm-up's float32 zero rows
    are cast to each leaf's canonical dtype on entry so the jit cache
    sees ONE signature per bucket.
    """

    def __init__(self, net):
        from deeplearning4j_tpu.nn.layers.recurrent import (CARRY_KEYS,
                                                            set_streaming)
        self.net = net
        self._carry_keys = CARRY_KEYS
        self._set_streaming = set_streaming
        self._lock = threading.Lock()
        self._depth = 0
        self._jit_prefill = jax.jit(self._prefill_impl)
        self._jit_decode = jax.jit(self._decode_impl)
        self._jit_extend = jax.jit(self._extend_impl)
        self._jit_verify = jax.jit(self._verify_impl)
        self._carry_def = None
        # eager 1-row probe pins the carry treedef + canonical dtypes
        vocab = int(net.layers[0].conf.n_in)
        self.vocab_size = vocab
        self._enter()
        try:
            probe = self._prefill_impl(
                net.params, net.state,
                jnp.zeros((1, 1, vocab), jnp.float32),
                jnp.ones((1, 1), jnp.float32))
        finally:
            self._exit()
        self.n_carries = len(probe) - 1
        self._carry_dtypes = [l.dtype for l in probe[1:]]
        #: per-row shapes of the decode ticket's cache leaves (for warm)
        self.carry_row_shapes = [tuple(l.shape[1:]) for l in probe[1:]]

    # ------------------------------------------------- streaming-flag nesting
    # replicas share this forward object AND the net; the layer streaming
    # flag is read at trace time, so concurrent device threads must not
    # see another thread's exit while they are still tracing
    def _enter(self):
        with self._lock:
            self._depth += 1
            if self._depth == 1:
                self._set_streaming(self.net.layers, True)

    def _exit(self):
        with self._lock:
            self._depth -= 1
            if self._depth == 0:
                self._set_streaming(self.net.layers, False)

    # ------------------------------------------------------------- internals
    def _extract(self, new_state):
        carries = {}
        for lname, sub in new_state.items():
            c = {k: v for k, v in sub.items() if k in self._carry_keys}
            if c:
                carries[lname] = c
        return carries

    def _prefill_impl(self, params, state, x, mask):
        out, ns = self.net._forward(params, state, x, train=False, rng=None,
                                    fmask=mask)
        lengths = jnp.maximum(
            jnp.sum(mask.astype(jnp.int32), axis=1), 1)
        logits = jnp.take_along_axis(
            out, (lengths - 1)[:, None, None], axis=1)[:, 0, :]
        leaves, self._carry_def = jax.tree_util.tree_flatten(
            self._extract(ns))
        return [logits] + leaves

    def _decode_impl(self, params, x, *leaves):
        carries = jax.tree_util.tree_unflatten(self._carry_def, list(leaves))
        state = {ln: dict(sub) for ln, sub in self.net.state.items()}
        for ln, sub in carries.items():
            merged = dict(state.get(ln, {}))
            merged.update(sub)
            state[ln] = merged
        out, ns = self.net._forward(params, state, x, train=False, rng=None)
        new_leaves, _ = jax.tree_util.tree_flatten(self._extract(ns))
        return [out[:, 0, :]] + new_leaves

    def _extend_impl(self, params, x, mask, *leaves):
        # decode-style carry merge + prefill-style masked advance: each
        # row extends its own cache from its pos frontier
        carries = jax.tree_util.tree_unflatten(self._carry_def, list(leaves))
        state = {ln: dict(sub) for ln, sub in self.net.state.items()}
        for ln, sub in carries.items():
            merged = dict(state.get(ln, {}))
            merged.update(sub)
            state[ln] = merged
        out, ns = self.net._forward(params, state, x, train=False, rng=None,
                                    fmask=mask)
        lengths = jnp.maximum(
            jnp.sum(mask.astype(jnp.int32), axis=1), 1)
        logits = jnp.take_along_axis(
            out, (lengths - 1)[:, None, None], axis=1)[:, 0, :]
        new_leaves, _ = jax.tree_util.tree_flatten(self._extract(ns))
        return [logits] + new_leaves

    def _verify_impl(self, params, mask, x, *leaves):
        # extend's masked advance, but returning the logits at EVERY fed
        # position instead of only the last real token's — the
        # speculative-verify op (padded rows carry garbage logits beyond
        # their segment; the host reads only the real positions)
        carries = jax.tree_util.tree_unflatten(self._carry_def, list(leaves))
        state = {ln: dict(sub) for ln, sub in self.net.state.items()}
        for ln, sub in carries.items():
            merged = dict(state.get(ln, {}))
            merged.update(sub)
            state[ln] = merged
        out, ns = self.net._forward(params, state, x, train=False, rng=None,
                                    fmask=mask)
        new_leaves, _ = jax.tree_util.tree_flatten(self._extract(ns))
        return [out] + new_leaves

    # ----------------------------------------------------------------- entry
    def __call__(self, feats: list):
        self._enter()
        try:
            if len(feats) == 2:
                out = self._jit_prefill(
                    self.net.params, self.net.state,
                    jnp.asarray(feats[0], jnp.float32),
                    jnp.asarray(feats[1], jnp.float32))
            elif len(feats) == 2 + self.n_carries \
                    and np.ndim(feats[0]) == 2:
                # mask-first at extend arity = the verify variant: same
                # per-row shapes in a different input order, so the
                # batcher's compatibility key keeps the two phases in
                # separate buckets without an extra marker input
                leaves = [jnp.asarray(f, dt)
                          for f, dt in zip(feats[2:], self._carry_dtypes)]
                out = self._jit_verify(
                    self.net.params, jnp.asarray(feats[0], jnp.float32),
                    jnp.asarray(feats[1], jnp.float32), *leaves)
            elif len(feats) == 2 + self.n_carries:
                leaves = [jnp.asarray(f, dt)
                          for f, dt in zip(feats[2:], self._carry_dtypes)]
                out = self._jit_extend(
                    self.net.params, jnp.asarray(feats[0], jnp.float32),
                    jnp.asarray(feats[1], jnp.float32), *leaves)
            else:
                leaves = [jnp.asarray(f, dt)
                          for f, dt in zip(feats[1:], self._carry_dtypes)]
                out = self._jit_decode(
                    self.net.params, jnp.asarray(feats[0], jnp.float32),
                    *leaves)
        finally:
            self._exit()
        return [np.asarray(o) for o in out]


class DecodeSession:
    """Host-side session record: token history (tiny ints — the recovery
    source after a pool eviction) + bookkeeping. The heavy cache leaves
    live in the ``KVPagePool``."""

    __slots__ = ("sid", "ids", "created", "last_step")

    def __init__(self, sid: str, ids: List[int]):
        self.sid = sid
        self.ids = list(ids)
        self.created = time.time()
        self.last_step = self.created

    @property
    def tokens(self) -> int:
        return len(self.ids)


@guarded_by("_lock", "_sessions", "prefills", "decode_steps", "reprefills",
            "prefill_chunks", "chunked_prefills", "interleaved_prefills",
            "prefix_hits", "shared_tokens", "spec_rounds", "spec_proposed",
            "spec_accepted", "spec_rejected", "_itok_buckets", "_itok_sum",
            "_itok_count")
class DecodeEngine:
    """Sessionful autoregressive decode over a ``ReplicaSet``.

    ``prefill(sid, ids)`` admits a session (masked prompt forward in
    page-aligned chunks, cache leaves into the pool) and returns
    next-token logits; ``step(sid, token)`` extends it one token. Both
    are synchronous per session; cross-session throughput comes from the
    batcher's window coalescing concurrent sessions' single-token steps
    into one bucket forward (drive sessions from threads, as
    ``serve_bench --decode`` does).

    PR 16 knobs — both default-on, each with a kill switch:

    - ``prefill_chunk_pages`` (env ``DL4J_TPU_PREFILL_CHUNK_PAGES``,
      default 1): pages per prefill chunk; ``0`` disables chunking so
      prompts prefill one-shot as before.
    - ``prefix_sharing`` (env ``DL4J_TPU_KV_PREFIX_SHARING``, default
      on): adopt + publish shared prompt-prefix pages in the pool.

    Both features require token-axis cache carries (the attention
    ``[1, C, H, dh]`` shape) and silently stay off for nets without
    them (e.g. pure-LSTM carries), preserving the legacy path.

    PR 18 knob — **speculative decoding**, default OFF:

    - ``speculative`` (env ``DL4J_TPU_SPECULATIVE_K``, default 0 = kill
      switch) with ``draft_net=``: each ``generate`` round the draft net
      autoregressively proposes ``k`` tokens, then the target verifies
      all of them in ONE batched verify forward (the all-position-logits
      extend variant). Acceptance is exact argmax match — the first
      mismatch truncates the round, the target's own logits row supplies
      the corrected token, and the cache rolls back to the accept
      frontier (``KVPagePool.truncate`` on the draft side, accept-point
      ``put`` on the target side) — so the emitted stream is
      BIT-IDENTICAL to plain greedy decode; speculation only changes how
      many target launches it costs. With ``k=0`` or no ``draft_net``
      the engine is byte-for-byte the plain PR 16 path. Requires
      token-axis carries like the other PR 16 features (silently off
      otherwise) and a draft whose vocab matches the target's (rejected
      with ``ValueError`` at construction).
    """

    def __init__(self, net, *, replicas: int = 1, pool: KVPagePool = None,
                 n_pages: int = 256, page_tokens: int = 16,
                 max_batch: int = 64, batch_window_ms: float = 2.0,
                 max_queue: int = 1024, min_batch: int = 2,
                 min_prompt_bucket: int = 8, stats=None,
                 request_timeout_s: float = 300.0,
                 prefix_sharing: Optional[bool] = None,
                 prefill_chunk_pages: Optional[int] = None,
                 speculative: Optional[int] = None, draft_net=None,
                 scheduler=None):
        self.forward = StreamingKVForward(net)
        # decode session scheduling rides the unified admission core
        # (scheduling/core.py) when one is passed: decode ops submit at
        # the interactive tier by construction (a live token stream IS
        # interactive traffic), so under overload the fleet sheds
        # co-resident batch prefill/predict work first
        self.scheduler = scheduler
        self.fleet = ReplicaSet(self.forward, replicas, max_batch=max_batch,
                                batch_window_ms=batch_window_ms,
                                max_queue=max_queue, min_batch=min_batch,
                                stats=stats, scheduler=scheduler)
        if prefix_sharing is None:
            prefix_sharing = os.environ.get(
                "DL4J_TPU_KV_PREFIX_SHARING", "1").lower() \
                not in ("0", "false", "no", "off")
        if prefill_chunk_pages is None:
            prefill_chunk_pages = int(os.environ.get(
                "DL4J_TPU_PREFILL_CHUNK_PAGES", "1"))
        self.pool = pool if pool is not None \
            else KVPagePool(n_pages, page_tokens,
                            prefix_sharing=bool(prefix_sharing))
        self.min_prompt_bucket = int(min_prompt_bucket)
        self.max_prompt = self._max_prompt(net)
        # both features need carries with a token axis to page/extend on
        rs = self.forward.carry_row_shapes
        can_page = (any(len(s) >= 2 for s in rs)
                    and all(len(s) == 0 or len(s) >= 2 for s in rs))
        self._sharing = (bool(prefix_sharing) and can_page
                         and self.pool.prefix_sharing)
        self._chunk_tokens = (max(0, int(prefill_chunk_pages))
                              * self.pool.page_tokens if can_page else 0)
        self._sessions: Dict[str, DecodeSession] = {}
        self._lock = threading.Lock()
        # same-named knob as ModelServer: a dead fleet must fail a decode
        # session with a deadline error, never hang it forever
        self.request_timeout_s = float(request_timeout_s)
        self.prefills = 0
        self.decode_steps = 0
        self.reprefills = 0   # evicted sessions re-admitted from history
        self.prefill_chunks = 0        # prompt segments submitted
        self.chunked_prefills = 0      # prefills split into >= 2 segments
        self.interleaved_prefills = 0  # ...during which decode advanced
        self.prefix_hits = 0           # prefills that adopted shared pages
        self.shared_tokens = 0         # prefill tokens skipped via sharing
        self.spec_rounds = 0           # draft-propose/target-verify rounds
        self.spec_proposed = 0         # draft tokens proposed
        self.spec_accepted = 0         # proposals matching the target argmax
        self.spec_rejected = 0         # proposals truncated at a mismatch
        # inter-token latency histogram (seconds): one observation per
        # emitted token — plain steps observe their own wall time,
        # speculative rounds amortize theirs over the tokens emitted.
        # Surfaced through describe() into the
        # dl4j_decode_inter_token_seconds family, so the p50/p99 the
        # TRANSFORMER receipts pin is also scrapeable live.
        self._itok_le = tuple(sorted(DEFAULT_BUCKETS))
        self._itok_buckets = {b: 0 for b in self._itok_le}
        self._itok_sum = 0.0
        self._itok_count = 0
        # ---- speculative decode (PR 18): default OFF; k = 0 kills it
        explicit_spec = speculative is not None
        if speculative is None:
            speculative = int(os.environ.get(
                "DL4J_TPU_SPECULATIVE_K", "0") or 0)
        k = max(0, int(speculative))
        if k and draft_net is None and explicit_spec:
            raise ValueError(
                f"speculative={k} needs a draft_net= to propose with — "
                "pass one (zoo.gpt_mini_draft matches zoo.gpt_mini) or "
                "set speculative=0")
        self.spec_k = 0
        self._draft: Optional["DecodeEngine"] = None
        if k and draft_net is not None and can_page:
            dv = int(draft_net.layers[0].conf.n_in)
            if dv != self.forward.vocab_size:
                raise ValueError(
                    f"speculative draft/target vocab mismatch: the draft "
                    f"proposes over {dv} tokens but the target verifies "
                    f"over {self.forward.vocab_size} — exact-argmax "
                    "acceptance needs the SAME tokenizer/vocab on both "
                    "nets; build the draft with zoo.gpt_mini_draft("
                    f"vocab_size={self.forward.vocab_size})")
            draft_ext = self._max_prompt(draft_net)
            if draft_ext < self.max_prompt:
                raise ValueError(
                    f"speculative draft cache extent {draft_ext} is "
                    f"shorter than the target's {self.max_prompt} — the "
                    "draft must track the whole session; build it with "
                    f"max_cache_len={self.max_prompt} (or longer)")
            # the draft rides its OWN single-replica engine (tiny model,
            # own pool, no nested speculation); prefix sharing lets each
            # round's resync adopt the previous round's pages
            self._draft = DecodeEngine(
                draft_net, replicas=1, n_pages=self.pool.n_pages,
                page_tokens=self.pool.page_tokens, max_batch=max_batch,
                batch_window_ms=batch_window_ms, max_queue=max_queue,
                min_batch=min_batch, min_prompt_bucket=min_prompt_bucket,
                request_timeout_s=request_timeout_s,
                prefix_sharing=prefix_sharing,
                prefill_chunk_pages=prefill_chunk_pages,
                speculative=0)
            self.spec_k = k

    @staticmethod
    def _max_prompt(net) -> int:
        caps = [int(getattr(ly, "cache_len", 0) or 0) for ly in net.layers]
        caps = [c for c in caps if c > 0]
        return min(caps) if caps else 256

    # --------------------------------------------------------------- helpers
    def _one_hot(self, ids: Sequence[int], t: int) -> np.ndarray:
        x = np.zeros((1, t, self.forward.vocab_size), np.float32)
        for j, i in enumerate(ids):
            x[0, j, int(i)] = 1.0
        return x

    def _prompt_bucket(self, t: int) -> int:
        return next_bucket(t, self.max_prompt, self.min_prompt_bucket)

    def _extend_seg(self) -> int:
        """Segment size (tokens) for extend tickets: the chunk size, or
        one page when chunking is killed but a shared prefix still needs
        extending from mid-sequence."""
        seg = self._chunk_tokens if self._chunk_tokens \
            else self.pool.page_tokens
        return min(seg, self.max_prompt)

    def _rungs(self, cap: int) -> List[int]:
        """Every value ``next_bucket(seg, cap, min_prompt_bucket)`` can
        produce for seg in 1..cap — the ladder a warm pass must cover."""
        t, rungs = self.min_prompt_bucket, []
        while t < cap:
            rungs.append(t)
            t *= 2
        rungs.append(cap)   # next_bucket caps at the extent
        return rungs

    def warm(self):
        """Precompile every phase ladder: the decode bucket ladder (the
        latency-critical one), the prefill ladder for every prompt rung,
        and — when chunked prefill / prefix sharing is live — the extend
        ladder, including the off-power edge rung where the cache extent
        truncates the final chunk. Each ladder passes an explicit empty
        ``skip`` to ``fleet.warm``: ``shapes_seen`` only records batch
        buckets, so letting the default snapshot stand after the first
        ladder would silently skip all the later ones and push their
        compiles into the timed run."""
        v = self.forward.vocab_size
        carry = list(self.forward.carry_row_shapes)
        compiled = list(self.fleet.warm([(1, v)] + carry, skip=()))
        pf_cap = min(self._chunk_tokens, self.max_prompt) \
            if self._chunk_tokens else self.max_prompt
        for t in self._rungs(pf_cap):
            compiled += self.fleet.warm([(t, v), (t,)], skip=())
        if self._sharing or self._chunk_tokens:
            ext = self._extend_seg()
            ext_rungs = set(self._rungs(ext))
            if self.max_prompt % ext:
                ext_rungs.add(self.max_prompt % ext)
            for t in sorted(ext_rungs):
                compiled += self.fleet.warm([(t, v), (t,)] + carry,
                                            skip=())
        if self.spec_k:
            # explicit verify rungs: every bucket a round can produce —
            # the segment is nxt + up to k proposals, and both the
            # token budget and the cache extent can shrink the cap
            vr = set()
            for cap in range(2, self.spec_k + 2):
                for seg in range(2, cap + 1):
                    vr.add(next_bucket(seg, cap, self.min_prompt_bucket))
            for t in sorted(vr):
                compiled += self.fleet.warm([(t,), (t, v)] + carry,
                                            skip=())
            compiled += self._draft.warm()
        return sorted(set(compiled))

    def _await(self, fut, sid: str, what: str):
        try:
            return fut.result(timeout=self.request_timeout_s)
        except _FutureTimeout:
            from deeplearning4j_tpu.serving.server import \
                DeadlineExceededError
            raise DeadlineExceededError(
                f"decode {what} for session '{sid}' exceeded "
                f"request_timeout_s={self.request_timeout_s:g}s") from None

    # ------------------------------------------------------------- lifecycle
    def _leaves_from_partial(self, partial: dict, shared_t: int):
        """Rebuild a full cache-leaf list from an adopted shared-page
        prefix: token-axis carries get the shared slices below the
        frontier (zeros above — never attended before overwrite), scalar
        position carries become the frontier itself."""
        leaves = []
        for i, rs in enumerate(self.forward.carry_row_shapes):
            dt = self.forward._carry_dtypes[i]
            if i in partial:
                arr = np.zeros((1,) + tuple(rs), dt)
                arr[:, :shared_t] = partial[i]
            else:
                arr = np.full((1,) + tuple(rs), shared_t, dt)
            leaves.append(arr)
        return leaves

    def _observe_inter_token(self, dt: float, n: int = 1) -> None:
        """Fold ``n`` emitted tokens that took ``dt`` seconds each into
        the inter-token histogram."""
        with self._lock:
            for b in self._itok_le:
                if dt <= b:
                    self._itok_buckets[b] += n
                    break
            self._itok_sum += dt * n
            self._itok_count += n

    @staticmethod
    def _tid_attrs(trace_id, **attrs) -> dict:
        """Span attrs with the request trace id attached when one rode
        in — the key that makes the span stitchable (SpanPushBuffer
        forwards only trace-carrying spans to the aggregator)."""
        if trace_id:
            attrs["trace_id"] = str(trace_id)
        return attrs

    def _run_prefill(self, sid: str, ids: List[int],
                     trace_id: Optional[str] = None) -> np.ndarray:
        t = len(ids)
        if t < 1:
            raise ValueError("prefill needs at least one prompt token")
        if t > self.max_prompt:
            raise ValueError(f"prompt of {t} tokens exceeds the cache "
                             f"extent {self.max_prompt}")
        with _get_tracer().span(
                "decode_prefill",
                **self._tid_attrs(trace_id, sid=sid, tokens=t)):
            return self._run_prefill_inner(sid, ids, t, trace_id)

    def _run_prefill_inner(self, sid: str, ids: List[int], t: int,
                           trace_id: Optional[str]):
        ext = self._extend_seg()
        pos, leaves, logits = 0, None, None
        if self._sharing:
            # adopt the longest resident page chain of this prompt;
            # alignment keeps later extend buckets on warmed rungs
            shared_t, partial = self.pool.match_prefix(
                sid, ids, align_tokens=ext)
            if shared_t:
                leaves = self._leaves_from_partial(partial, shared_t)
                pos = shared_t
                with self._lock:
                    self.prefix_hits += 1
                    self.shared_tokens += shared_t
        ds0 = self.decode_steps
        chunks = 0
        while pos < t:
            if leaves is None:
                # fresh cache: masked prefill (whole prompt, or the
                # first chunk when chunking is on)
                cap = min(self._chunk_tokens, self.max_prompt) \
                    if self._chunk_tokens else self.max_prompt
                seg = min(t, cap)
                bt = next_bucket(seg, cap, self.min_prompt_bucket)
                x = self._one_hot(ids[:seg], bt)
                mask = np.zeros((1, bt), np.float32)
                mask[0, :seg] = 1.0
                feats = [x, mask]
            else:
                # extend the existing cache by one page-aligned segment;
                # the bucket cap never overruns the cache extent
                cap = min(ext, self.max_prompt - pos)
                seg = min(t - pos, cap)
                bt = next_bucket(seg, cap, self.min_prompt_bucket)
                x = self._one_hot(ids[pos:pos + seg], bt)
                mask = np.zeros((1, bt), np.float32)
                mask[0, :seg] = 1.0
                feats = [x, mask] + list(leaves)
            res = self._await(self.fleet.submit(feats, session=sid,
                                                trace_id=trace_id),
                              sid, "prefill")
            logits, leaves = res[0], list(res[1:])
            pos += seg
            chunks += 1
        with self._lock:
            self.prefill_chunks += chunks
            if chunks > 1:
                self.chunked_prefills += 1
                if self.decode_steps > ds0:
                    self.interleaved_prefills += 1
        self.pool.put(sid, t, leaves,
                      ids=ids if self._sharing else None)
        return logits[0], leaves

    def prefill(self, sid: str, ids: Sequence[int],
                trace_id: Optional[str] = None) -> np.ndarray:
        """Admit session ``sid`` with prompt token ids; returns the
        next-token logits row [V]. ``trace_id`` (the request's
        ``X-DL4J-Trace-Id``) rides the engine's spans and the batcher
        tickets so the session's work stitches into the aggregator's
        per-request waterfall."""
        ids = [int(i) for i in ids]
        with self._lock:
            self._sessions[sid] = DecodeSession(sid, ids)
            self.prefills += 1
        return self._run_prefill(sid, ids, trace_id=trace_id)[0]

    def step(self, sid: str, token: int,
             trace_id: Optional[str] = None) -> np.ndarray:
        """Feed one decoded token into session ``sid``; returns the
        next-token logits row [V]. Transparently re-prefills from token
        history when the pool evicted this session between steps."""
        t_start = time.perf_counter()
        with self._lock:
            sess = self._sessions.get(sid)
        if sess is None:
            raise KeyError(f"unknown decode session '{sid}'")
        if sess.tokens + 1 > self.max_prompt:
            # the session can never advance again — release its pool
            # pages so the capacity returns to live sessions (the tiny
            # host record stays for close_session bookkeeping)
            self.pool.drop(sid)
            raise ValueError(f"session '{sid}' is at the cache extent "
                             f"{self.max_prompt}")
        leaves = self.pool.get(sid)
        if leaves is None:
            # evicted between steps: recover from history — the one-shot
            # re-prefill is bit-identical to the steps it replaces (and
            # carries the SAME trace id, so a stitched waterfall shows
            # the recovery inline with the request that paid for it)
            with self._lock:
                self.reprefills += 1
            leaves = self._run_prefill(sid, sess.ids, trace_id=trace_id)[1]
        x = self._one_hot([token], 1)
        with _get_tracer().span("decode_step",
                                **self._tid_attrs(trace_id, sid=sid)):
            res = self._await(self.fleet.submit([x] + list(leaves),
                                                session=sid,
                                                trace_id=trace_id),
                              sid, "step")
        logits, new_leaves = res[0], res[1:]
        sess.ids.append(int(token))
        sess.last_step = time.time()
        with self._lock:
            self.decode_steps += 1
        # passing the history keeps sealing shareable pages as the
        # session decodes; divergent continuations seal distinct keys,
        # so shared prompt pages stay copy-on-write
        self.pool.put(sid, sess.tokens, new_leaves,
                      ids=sess.ids if self._sharing else None)
        self._observe_inter_token(time.perf_counter() - t_start)
        return logits[0]

    # ---------------------------------------------------------- speculative
    def _rollback(self, sid: str, to_tokens: int) -> bool:
        """Roll session ``sid`` back to its first ``to_tokens`` fed
        tokens: refcount-safe page release via ``pool.truncate`` (the
        position carries move back to the new frontier; the pageable
        leaves' stale tail is dropped by the pool) plus the history trim.
        Returns ``False`` when the pool can't truncate (dense entry, or
        evicted) — the caller re-prefills from history instead."""
        with self._lock:
            sess = self._sessions.get(sid)
        if sess is None or to_tokens < 1 or to_tokens > sess.tokens:
            return False
        others = {}
        for i, rs in enumerate(self.forward.carry_row_shapes):
            if len(rs) < 2:
                others[i] = np.full((1,) + tuple(rs), to_tokens,
                                    self.forward._carry_dtypes[i])
        if not self.pool.truncate(sid, to_tokens, others=others):
            return False
        del sess.ids[to_tokens:]
        return True

    def _sync_logits(self, sid: str, want: List[int],
                     trace_id: Optional[str] = None) -> np.ndarray:
        """Next-token logits with session ``sid``'s fed history equal to
        ``want`` — the draft-side resync between speculative rounds.
        Reuses the live session when its history is a prefix of ``want``
        (stepping just the missing suffix — the common case: rounds
        extend each other), rolls a diverged tail back to the common
        prefix via ``_rollback`` first, and otherwise falls back to a
        full prefill (which, with prefix sharing on, re-adopts its own
        sealed pages, so even the fallback is incremental)."""
        with self._lock:
            sess = self._sessions.get(sid)
        if sess is not None:
            have = list(sess.ids)
            n = 0
            for a, b in zip(have, want):
                if a != b:
                    break
                n += 1
            if n < len(have):
                # diverged tail (the previous round's rejected drafts)
                have = have[:n] if n >= 1 and self._rollback(sid, n) \
                    else None
            if have is not None and len(have) < len(want):
                logits = None
                for t in want[len(have):]:
                    logits = self.step(sid, t, trace_id=trace_id)
                return logits
        return self.prefill(sid, want, trace_id=trace_id)

    def _propose(self, sid: str, want: List[int], k: int,
                 trace_id: Optional[str] = None) -> List[int]:
        """``k`` greedy draft proposals continuing ``want`` — runs on the
        draft engine (its own fleet/pool); the last proposal is left
        un-fed, the next round's resync settles it."""
        d = self._draft
        logits = d._sync_logits(sid, want, trace_id=trace_id)
        props: List[int] = []
        for _ in range(k):
            t = int(np.argmax(logits))
            props.append(t)
            if len(props) < k:
                logits = d.step(sid, t, trace_id=trace_id)
        return props

    def _spec_round(self, sid: str, nxt: int, max_new: int,
                    trace_id: Optional[str] = None):
        """One draft-propose / target-verify round: the draft proposes
        ``k`` tokens continuing ``nxt``, the target verifies all of them
        in ONE batched verify forward, and exact argmax match decides
        acceptance — the first mismatch truncates the round and the
        target's own logits row supplies the corrected next token, so
        the emitted stream is bit-identical to plain greedy decode.
        Returns ``(emitted, next_token)`` where ``emitted`` (>= 1
        tokens, starting with ``nxt``) is exactly what was fed and kept,
        or ``None`` when speculation can't run here (cache extent too
        close) and the caller should take a plain step."""
        with self._lock:
            sess = self._sessions.get(sid)
        if sess is None:
            raise KeyError(f"unknown decode session '{sid}'")
        base = sess.tokens
        k = min(self.spec_k, int(max_new), self.max_prompt - base - 1)
        if k < 1:
            return None
        props = self._propose(sid, sess.ids + [int(nxt)], k,
                              trace_id=trace_id)
        leaves = self.pool.get(sid)
        if leaves is None:
            # evicted mid-round: the same bit-identical re-prefill
            # recovery as step()
            with self._lock:
                self.reprefills += 1
            leaves = self._run_prefill(sid, sess.ids, trace_id=trace_id)[1]
        seq = [int(nxt)] + props
        cap = min(self.spec_k + 1, self.max_prompt - base)
        bt = next_bucket(len(seq), cap, self.min_prompt_bucket)
        x = self._one_hot(seq, bt)
        mask = np.zeros((1, bt), np.float32)
        mask[0, :len(seq)] = 1.0
        # mask-first feats mark the verify (all-position-logits) variant
        with _get_tracer().span(
                "decode_verify",
                **self._tid_attrs(trace_id, sid=sid, proposed=k)):
            res = self._await(self.fleet.submit([mask, x] + list(leaves),
                                                session=sid,
                                                trace_id=trace_id),
                              sid, "verify")
        rows, new_leaves = res[0][0], list(res[1:])
        emitted = [int(nxt)]
        accepted = 0
        nxt2 = None
        for i in range(k):
            g = int(np.argmax(rows[i]))
            if props[i] == g:
                emitted.append(g)
                accepted += 1
            else:
                nxt2 = g    # the target's own corrected token
                break
        if nxt2 is None:
            # full accept: the last logits row is a free plain step
            nxt2 = int(np.argmax(rows[k]))
        kept = base + len(emitted)
        if len(emitted) < len(seq):
            # roll back to the accept frontier: position carries move
            # back; the pageable leaves keep their stale tail, which the
            # fixed-extent contract guarantees is overwritten before it
            # is ever attended (and the pool stores only kept tokens)
            for i, rs in enumerate(self.forward.carry_row_shapes):
                if len(rs) < 2:
                    new_leaves[i] = np.full(
                        (1,) + tuple(rs), kept,
                        self.forward._carry_dtypes[i])
        sess.ids.extend(emitted)
        sess.last_step = time.time()
        with self._lock:
            self.spec_rounds += 1
            self.spec_proposed += k
            self.spec_accepted += accepted
            self.spec_rejected += k - accepted
        self.pool.put(sid, sess.tokens, new_leaves,
                      ids=sess.ids if self._sharing else None)
        return emitted, nxt2

    def generate(self, sid: str, ids: Sequence[int], n_tokens: int,
                 *, step_times: Optional[list] = None,
                 trace_id: Optional[str] = None) -> List[int]:
        """Greedy decode: prefill then ``n_tokens`` argmax tokens —
        plain single-token steps, or draft-propose/target-verify rounds
        when speculation is on (same stream either way, bit-identical).
        Returns the generated ids; ``step_times`` (if given) collects
        per-token wall seconds — the inter-token latency sample stream
        (a speculative round's wall time is amortized over the tokens it
        emitted); ``trace_id`` rides every span and ticket the
        generation dispatches."""
        n = int(n_tokens)
        logits = self.prefill(sid, ids, trace_id=trace_id)
        out: List[int] = []
        if n <= 0:
            return out
        nxt = int(np.argmax(logits))
        while len(out) < n:
            left = n - len(out)
            if self.spec_k and left >= 2:
                t0 = time.perf_counter()
                r = self._spec_round(sid, nxt, left - 1,
                                     trace_id=trace_id)
                if r is not None:
                    emitted, nxt = r
                    dt = (time.perf_counter() - t0) / len(emitted)
                    self._observe_inter_token(dt, n=len(emitted))
                    if step_times is not None:
                        step_times.extend([dt] * len(emitted))
                    out.extend(emitted)
                    continue
            out.append(nxt)
            t0 = time.perf_counter()
            logits = self.step(sid, nxt, trace_id=trace_id)
            if step_times is not None:
                step_times.append(time.perf_counter() - t0)
            if len(out) < n:
                # the final step's argmax would be discarded — skip it
                nxt = int(np.argmax(logits))
        return out

    def close_session(self, sid: str) -> bool:
        with self._lock:
            known = self._sessions.pop(sid, None) is not None
        # pool.drop releases this session's page references under the
        # POOL lock (KVPagePool is @guarded_by): shared pages survive
        # for their other holders, exclusively-held pages free here
        self.pool.drop(sid)
        self.fleet.forget_session(sid)
        if self._draft is not None:
            self._draft.close_session(sid)
        return known

    # ----------------------------------------------------------------- state
    @property
    def sessions(self) -> List[str]:
        with self._lock:
            return list(self._sessions)

    def describe(self) -> dict:
        d = self.pool.describe()
        d.update(prefills=self.prefills, decode_steps=self.decode_steps,
                 reprefills=self.reprefills,
                 affinity_hits=self.fleet.affinity_hits,
                 affinity_misses=self.fleet.affinity_misses,
                 sessions_live=len(self._sessions),
                 prefill_chunks=self.prefill_chunks,
                 chunked_prefills=self.chunked_prefills,
                 interleaved_prefills=self.interleaved_prefills,
                 prefix_hits=self.prefix_hits,
                 shared_tokens=self.shared_tokens,
                 prefill_chunk_tokens=self._chunk_tokens,
                 prefix_sharing=self._sharing)
        with self._lock:
            if self._itok_count:
                d["inter_token_hist"] = {
                    "buckets": {str(b): c
                                for b, c in self._itok_buckets.items()},
                    "sum": round(self._itok_sum, 6),
                    "count": self._itok_count,
                }
        steps = self.decode_steps + self.spec_rounds
        d.update(speculative_k=self.spec_k,
                 spec_rounds=self.spec_rounds,
                 spec_proposed=self.spec_proposed,
                 spec_accepted=self.spec_accepted,
                 spec_rejected=self.spec_rejected,
                 # tokens emitted per target decode launch: plain steps
                 # emit 1 each; a verify round emits 1 + its accepts
                 spec_accept_tokens_per_step=(
                     round((steps + self.spec_accepted) / steps, 4)
                     if (self.spec_k and steps) else None),
                 # rollbacks live in the DRAFT's pool (the target resets
                 # position carries host-side instead)
                 spec_draft_truncations=(
                     self._draft.pool.truncations
                     if self._draft is not None else None))
        return d

    def stop(self):
        if self._draft is not None:
            self._draft.stop()
        self.fleet.stop()
