"""Autoregressive decode serving: prefill/decode phase split over the
micro-batcher, session state in a paged KV pool, session-affine routing.

The transformer streaming path (nn/layers/attention.py) is a pure
function of (params, cache state, next tokens) — so decode serving rides
the EXISTING batching runtime unchanged by making the per-session cache
part of the ticket:

- **Phase split for free.** ``MicroBatcher`` coalesces only tickets
  whose per-input row shapes match. Prefill tickets are
  ``[x [1, T, V], mask [1, T]]`` and decode tickets are ``[x [1, 1, V],
  *cache leaves]`` — different arity and shapes, so the batcher's own
  compatibility key IS the prefill/decode bucket split: decode steps
  from many sessions coalesce into one bucket-B single-token forward,
  prompts coalesce with same-length prompts, and neither phase ever
  pads against the other.
- **Prompt length ladder.** Prompts are right-padded (mask-marked) to a
  power-of-two rung so nearby lengths share one compile AND one batch;
  the one-shot masked prefill is bit-identical to feeding the prompt
  token-by-token (the fixed-extent-cache contract, ops/attention.py),
  so the padding is purely a throughput lever.
- **State travels with the ticket.** Each session's cache leaves (per
  layer: k/v [1, C, H, dh] f32 + pos [1] i32) are host rows concatenated
  by the batcher exactly like features, and the forward returns the
  advanced leaves which are sliced back per row. The forward itself
  stays stateless → replicas stay interchangeable, and the fleet's
  eviction/requeue machinery applies to decode tickets unchanged.
- **Session affinity is a routing hint, not a correctness need.**
  ``ReplicaSet.submit(..., session=sid)`` pins a session's steps to one
  replica (warm jit cache, stable latency); on replica death the
  affinity map rebinds and the ticket requeues — state rode the ticket,
  so nothing is lost.
- **Paged pool + recoverable eviction.** Between steps the leaves live
  in a ``KVPagePool`` charged in ``page_tokens`` blocks; when the pool
  evicts an idle session, its token history (kept here, tiny) is
  re-prefilled on its next step — bit-identical recovery, counted in
  ``reprefills``.

Numeric contract (PRECISION.md / PERF.md §14): everything inside the
streaming tier — prefill, chunk, step, pool round-trip, re-prefill after
eviction — is BIT-IDENTICAL; streaming vs the training forward
(``net.output``) carries the usual compute-dtype TOLERANCE contract.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax import numpy as jnp

from deeplearning4j_tpu.analysis.guards import guarded_by
from deeplearning4j_tpu.serving.batcher import next_bucket
from deeplearning4j_tpu.serving.fleet import ReplicaSet
from deeplearning4j_tpu.serving.kvcache import KVPagePool

__all__ = ["StreamingKVForward", "DecodeEngine", "DecodeSession"]


class StreamingKVForward:
    """Stateless feats-list forward over a streaming net, shaped for
    ``MicroBatcher``: 2 inputs = prefill, 1 + n_carries inputs = decode.

    Prefill ``[x [b,T,V], mask [b,T]]`` runs the masked one-shot
    streaming forward from a fresh fixed-extent cache and returns
    ``[last-real-token logits [b,V], *cache leaves]``. Decode
    ``[x [b,1,V], *cache leaves]`` advances every row's cache one token
    and returns ``[logits [b,V], *new leaves]``. Leaves flatten in
    deterministic (sorted-key) pytree order; warm-up's float32 zero rows
    are cast to each leaf's canonical dtype on entry so the jit cache
    sees ONE signature per bucket.
    """

    def __init__(self, net):
        from deeplearning4j_tpu.nn.layers.recurrent import (CARRY_KEYS,
                                                            set_streaming)
        self.net = net
        self._carry_keys = CARRY_KEYS
        self._set_streaming = set_streaming
        self._lock = threading.Lock()
        self._depth = 0
        self._jit_prefill = jax.jit(self._prefill_impl)
        self._jit_decode = jax.jit(self._decode_impl)
        self._carry_def = None
        # eager 1-row probe pins the carry treedef + canonical dtypes
        vocab = int(net.layers[0].conf.n_in)
        self.vocab_size = vocab
        self._enter()
        try:
            probe = self._prefill_impl(
                net.params, net.state,
                jnp.zeros((1, 1, vocab), jnp.float32),
                jnp.ones((1, 1), jnp.float32))
        finally:
            self._exit()
        self.n_carries = len(probe) - 1
        self._carry_dtypes = [l.dtype for l in probe[1:]]
        #: per-row shapes of the decode ticket's cache leaves (for warm)
        self.carry_row_shapes = [tuple(l.shape[1:]) for l in probe[1:]]

    # ------------------------------------------------- streaming-flag nesting
    # replicas share this forward object AND the net; the layer streaming
    # flag is read at trace time, so concurrent device threads must not
    # see another thread's exit while they are still tracing
    def _enter(self):
        with self._lock:
            self._depth += 1
            if self._depth == 1:
                self._set_streaming(self.net.layers, True)

    def _exit(self):
        with self._lock:
            self._depth -= 1
            if self._depth == 0:
                self._set_streaming(self.net.layers, False)

    # ------------------------------------------------------------- internals
    def _extract(self, new_state):
        carries = {}
        for lname, sub in new_state.items():
            c = {k: v for k, v in sub.items() if k in self._carry_keys}
            if c:
                carries[lname] = c
        return carries

    def _prefill_impl(self, params, state, x, mask):
        out, ns = self.net._forward(params, state, x, train=False, rng=None,
                                    fmask=mask)
        lengths = jnp.maximum(
            jnp.sum(mask.astype(jnp.int32), axis=1), 1)
        logits = jnp.take_along_axis(
            out, (lengths - 1)[:, None, None], axis=1)[:, 0, :]
        leaves, self._carry_def = jax.tree_util.tree_flatten(
            self._extract(ns))
        return [logits] + leaves

    def _decode_impl(self, params, x, *leaves):
        carries = jax.tree_util.tree_unflatten(self._carry_def, list(leaves))
        state = {ln: dict(sub) for ln, sub in self.net.state.items()}
        for ln, sub in carries.items():
            merged = dict(state.get(ln, {}))
            merged.update(sub)
            state[ln] = merged
        out, ns = self.net._forward(params, state, x, train=False, rng=None)
        new_leaves, _ = jax.tree_util.tree_flatten(self._extract(ns))
        return [out[:, 0, :]] + new_leaves

    # ----------------------------------------------------------------- entry
    def __call__(self, feats: list):
        self._enter()
        try:
            if len(feats) == 2:
                out = self._jit_prefill(
                    self.net.params, self.net.state,
                    jnp.asarray(feats[0], jnp.float32),
                    jnp.asarray(feats[1], jnp.float32))
            else:
                leaves = [jnp.asarray(f, dt)
                          for f, dt in zip(feats[1:], self._carry_dtypes)]
                out = self._jit_decode(
                    self.net.params, jnp.asarray(feats[0], jnp.float32),
                    *leaves)
        finally:
            self._exit()
        return [np.asarray(o) for o in out]


class DecodeSession:
    """Host-side session record: token history (tiny ints — the recovery
    source after a pool eviction) + bookkeeping. The heavy cache leaves
    live in the ``KVPagePool``."""

    __slots__ = ("sid", "ids", "created", "last_step")

    def __init__(self, sid: str, ids: List[int]):
        self.sid = sid
        self.ids = list(ids)
        self.created = time.time()
        self.last_step = self.created

    @property
    def tokens(self) -> int:
        return len(self.ids)


@guarded_by("_lock", "_sessions", "prefills", "decode_steps", "reprefills")
class DecodeEngine:
    """Sessionful autoregressive decode over a ``ReplicaSet``.

    ``prefill(sid, ids)`` admits a session (one-shot masked prompt
    forward, cache leaves into the pool) and returns next-token logits;
    ``step(sid, token)`` extends it one token. Both are synchronous per
    session; cross-session throughput comes from the batcher's window
    coalescing concurrent sessions' single-token steps into one bucket
    forward (drive sessions from threads, as ``serve_bench --decode``
    does).
    """

    def __init__(self, net, *, replicas: int = 1, pool: KVPagePool = None,
                 n_pages: int = 256, page_tokens: int = 16,
                 max_batch: int = 64, batch_window_ms: float = 2.0,
                 max_queue: int = 1024, min_batch: int = 2,
                 min_prompt_bucket: int = 8, stats=None,
                 request_timeout_s: float = 300.0):
        self.forward = StreamingKVForward(net)
        self.fleet = ReplicaSet(self.forward, replicas, max_batch=max_batch,
                                batch_window_ms=batch_window_ms,
                                max_queue=max_queue, min_batch=min_batch,
                                stats=stats)
        self.pool = pool if pool is not None \
            else KVPagePool(n_pages, page_tokens)
        self.min_prompt_bucket = int(min_prompt_bucket)
        self.max_prompt = self._max_prompt(net)
        self._sessions: Dict[str, DecodeSession] = {}
        self._lock = threading.Lock()
        # same-named knob as ModelServer: a dead fleet must fail a decode
        # session with a deadline error, never hang it forever
        self.request_timeout_s = float(request_timeout_s)
        self.prefills = 0
        self.decode_steps = 0
        self.reprefills = 0   # evicted sessions re-admitted from history

    @staticmethod
    def _max_prompt(net) -> int:
        caps = [int(getattr(ly, "cache_len", 0) or 0) for ly in net.layers]
        caps = [c for c in caps if c > 0]
        return min(caps) if caps else 256

    # --------------------------------------------------------------- helpers
    def _one_hot(self, ids: Sequence[int], t: int) -> np.ndarray:
        x = np.zeros((1, t, self.forward.vocab_size), np.float32)
        for j, i in enumerate(ids):
            x[0, j, int(i)] = 1.0
        return x

    def _prompt_bucket(self, t: int) -> int:
        return next_bucket(t, self.max_prompt, self.min_prompt_bucket)

    def warm(self):
        """Precompile both phase ladders: the decode bucket ladder (the
        latency-critical one) and the prefill ladder for every prompt
        rung."""
        v = self.forward.vocab_size
        compiled = list(self.fleet.warm(
            [(1, v)] + list(self.forward.carry_row_shapes)))
        t = self.min_prompt_bucket
        rungs = []
        while t < self.max_prompt:
            rungs.append(t)
            t *= 2
        rungs.append(self.max_prompt)   # next_bucket caps at the extent
        for t in rungs:
            compiled += self.fleet.warm([(t, v), (t,)])
        return compiled

    def _await(self, fut, sid: str, what: str):
        try:
            return fut.result(timeout=self.request_timeout_s)
        except _FutureTimeout:
            from deeplearning4j_tpu.serving.server import \
                DeadlineExceededError
            raise DeadlineExceededError(
                f"decode {what} for session '{sid}' exceeded "
                f"request_timeout_s={self.request_timeout_s:g}s") from None

    # ------------------------------------------------------------- lifecycle
    def _run_prefill(self, sid: str, ids: List[int]) -> np.ndarray:
        t = len(ids)
        if t < 1:
            raise ValueError("prefill needs at least one prompt token")
        if t > self.max_prompt:
            raise ValueError(f"prompt of {t} tokens exceeds the cache "
                             f"extent {self.max_prompt}")
        bt = self._prompt_bucket(t)
        x = self._one_hot(ids, bt)
        mask = np.zeros((1, bt), np.float32)
        mask[0, :t] = 1.0
        res = self._await(self.fleet.submit([x, mask], session=sid),
                          sid, "prefill")
        logits, leaves = res[0], list(res[1:])
        self.pool.put(sid, t, leaves)
        return logits[0], leaves

    def prefill(self, sid: str, ids: Sequence[int]) -> np.ndarray:
        """Admit session ``sid`` with prompt token ids; returns the
        next-token logits row [V]."""
        ids = [int(i) for i in ids]
        with self._lock:
            self._sessions[sid] = DecodeSession(sid, ids)
            self.prefills += 1
        return self._run_prefill(sid, ids)[0]

    def step(self, sid: str, token: int) -> np.ndarray:
        """Feed one decoded token into session ``sid``; returns the
        next-token logits row [V]. Transparently re-prefills from token
        history when the pool evicted this session between steps."""
        with self._lock:
            sess = self._sessions.get(sid)
        if sess is None:
            raise KeyError(f"unknown decode session '{sid}'")
        if sess.tokens + 1 > self.max_prompt:
            raise ValueError(f"session '{sid}' is at the cache extent "
                             f"{self.max_prompt}")
        leaves = self.pool.get(sid)
        if leaves is None:
            # evicted between steps: recover from history — the one-shot
            # re-prefill is bit-identical to the steps it replaces
            with self._lock:
                self.reprefills += 1
            leaves = self._run_prefill(sid, sess.ids)[1]
        x = self._one_hot([token], 1)
        res = self._await(self.fleet.submit([x] + list(leaves),
                                            session=sid), sid, "step")
        logits, new_leaves = res[0], res[1:]
        sess.ids.append(int(token))
        sess.last_step = time.time()
        with self._lock:
            self.decode_steps += 1
        self.pool.put(sid, sess.tokens, new_leaves)
        return logits[0]

    def generate(self, sid: str, ids: Sequence[int], n_tokens: int,
                 *, step_times: Optional[list] = None) -> List[int]:
        """Greedy decode: prefill then ``n_tokens`` argmax steps. Returns
        the generated ids; ``step_times`` (if given) collects per-step
        wall seconds — the inter-token latency sample stream."""
        logits = self.prefill(sid, ids)
        out = []
        nxt = int(np.argmax(logits))
        for _ in range(int(n_tokens)):
            out.append(nxt)
            t0 = time.perf_counter()
            logits = self.step(sid, nxt)
            if step_times is not None:
                step_times.append(time.perf_counter() - t0)
            nxt = int(np.argmax(logits))
        return out

    def close_session(self, sid: str) -> bool:
        with self._lock:
            known = self._sessions.pop(sid, None) is not None
        self.pool.drop(sid)
        self.fleet.forget_session(sid)
        return known

    # ----------------------------------------------------------------- state
    @property
    def sessions(self) -> List[str]:
        with self._lock:
            return list(self._sessions)

    def describe(self) -> dict:
        d = self.pool.describe()
        d.update(prefills=self.prefills, decode_steps=self.decode_steps,
                 reprefills=self.reprefills,
                 affinity_hits=self.fleet.affinity_hits,
                 affinity_misses=self.fleet.affinity_misses,
                 sessions_live=len(self._sessions))
        return d

    def stop(self):
        self.fleet.stop()
