"""Versioned weight publication: the train-to-serve live-reload seam.

The resilience supervisor (resilience/supervisor.py) writes
``step_<n>`` checkpoints plus an atomic ``LATEST`` pointer for *resume*;
this module promotes those checkpoints into a **publication store** for
*serving* — the TF-paper versioned-model story (PAPERS.md): a training
run publishes, a serving fleet hot-swaps onto the newest publication,
a canary that fails its gates is rolled back by repointing, never by
rewriting weights.

Store layout (``root/``)::

    v_000001/            one published version = one complete checkpoint
      tree/              orbax param/state/opt trees (copied verbatim)
      meta.json          the checkpoint's own metadata
      layout.json        schema-v2 layout manifest (when the save had one)
      publication.json   {version, fingerprint, source, status, ...}
    v_000002/
    LATEST               atomic pointer -> the version serving should run

Discipline mirrors the checkpoint machinery it feeds from:

- **Atomic landing.** A publication is staged under a dot-temp dir and
  ``os.replace``d into its ``v_%06d`` name — a reader never sees a
  half-copied version. ``LATEST`` lands the same way (tmp + rename),
  exactly the supervisor's pointer idiom.
- **Monotonic versions.** Version numbers only grow; a rollback moves
  the LATEST pointer *backwards across* versions, it never renumbers.
- **Fingerprint stamping.** Every publication records the PR 10
  ``compilecache.manifest.model_fingerprint`` of a structure-only net
  built from the checkpoint's own config — the compatibility key
  ``ModelServer.hot_swap`` checks before binding the weights to the
  live jit cache (same fingerprint ⇒ same param pytree structure ⇒
  the already-compiled bucket executables serve the new weights with
  0 fresh compiles).
- **Rollback as a verb.** ``rollback()`` marks the current LATEST
  version ``rejected`` (publication.json rewritten atomically, with the
  reason) and repoints LATEST at the newest non-rejected predecessor.
  Rejected versions are never candidates for LATEST again, but their
  bits stay on disk until retention GC ages them out — a post-mortem
  can still load exactly what was rolled back.
- **Retention.** ``keep`` newest versions survive GC; the LATEST target
  is never deleted regardless of age.

See SERVING.md §Live reload; receipts: scripts/chaos_livereload.py ->
LIVERELOAD_r01.json, gated by BUDGETS.json ``live_reload``.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
from typing import List, Optional

from deeplearning4j_tpu.utils.checkpoint import (find_latest_checkpoint,
                                                 is_valid_checkpoint,
                                                 read_checkpoint_meta)

__all__ = ["WeightStore", "Publication", "load_net"]

_VER_DIR = re.compile(r"^v_(\d{6})$")
_LATEST = "LATEST"
PUBLICATION_META = "publication.json"


class Publication:
    """One published version: a complete checkpoint directory plus its
    ``publication.json`` stamp. Restorable directly — ``path`` is a
    valid checkpoint path for ``restore_*`` / :func:`load_net`."""

    __slots__ = ("version", "path", "meta")

    def __init__(self, version: int, path: str, meta: dict):
        self.version = int(version)
        self.path = path
        self.meta = meta

    @property
    def fingerprint(self) -> Optional[str]:
        return self.meta.get("fingerprint")

    @property
    def status(self) -> str:
        return self.meta.get("status", "published")

    @property
    def rejected(self) -> bool:
        return self.status == "rejected"

    def describe(self) -> dict:
        return {"version": self.version, "path": self.path, **self.meta}

    def __repr__(self):
        return (f"Publication(v{self.version}, {self.status}, "
                f"fp={self.fingerprint})")


def load_net(path: str, mesh=None, **restore_kw):
    """Restore the net a publication (or any checkpoint directory)
    holds, dispatching on the checkpoint's own ``kind``. Single-device
    restore places leaves directly (no compiler involvement), so a
    reload's cost is I/O, not XLA. Single-device leaves are then
    round-tripped through host memory to shed the restore's *committed*
    device placement — jit keys on committedness, so without this a
    server booted from a publication and later hot-swapped would pay
    one retrace per swap instead of hitting its warm cache."""
    from deeplearning4j_tpu.utils.checkpoint import (
        restore_computation_graph, restore_multi_layer_network)
    kind = read_checkpoint_meta(path)["kind"]
    fn = (restore_computation_graph if kind == "graph"
          else restore_multi_layer_network)
    net = fn(path, mesh=mesh, **restore_kw)
    if mesh is None:
        import jax
        import jax.numpy as jnp
        import numpy as np

        def _uncommit(tree):
            return jax.tree_util.tree_map(
                lambda a: jnp.asarray(np.asarray(a)), tree)
        net.params = _uncommit(net.params)
        if net.state:
            net.state = _uncommit(net.state)
    return net


def _fingerprint_of_checkpoint(path: str) -> str:
    """The PR 10 model fingerprint of the checkpoint's config: built
    from a structure-only net (no parameter materialization), so
    publishing is cheap even for big models."""
    from deeplearning4j_tpu.compilecache.manifest import model_fingerprint
    meta = read_checkpoint_meta(path)
    if meta["kind"] == "graph":
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = ComputationGraphConfiguration.from_json(meta["config"])
        net = ComputationGraph(conf).init(structure_only=True)
    else:
        from deeplearning4j_tpu.nn.conf.core import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = MultiLayerConfiguration.from_json(meta["config"])
        net = MultiLayerNetwork(conf).init(structure_only=True)
    return model_fingerprint(net)


class WeightStore:
    """The versioned publication store (module docstring has the
    layout + discipline). Safe for one publisher process; readers
    (serving hosts, orchestrators) may poll concurrently — every state
    change lands via rename."""

    def __init__(self, root: str, *, keep: int = 3):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.root = os.path.abspath(root)
        self.keep = int(keep)
        os.makedirs(self.root, exist_ok=True)

    # ------------------------------------------------------------- reading
    def versions(self, include_rejected: bool = True) -> List[Publication]:
        """All publications, oldest first. Staged temp dirs and corpses
        GC'd mid-scan are skipped (the find_latest_checkpoint race
        stance)."""
        out = []
        for name in sorted(os.listdir(self.root)):
            m = _VER_DIR.match(name)
            if m is None:
                continue
            path = os.path.join(self.root, name)
            try:
                with open(os.path.join(path, PUBLICATION_META)) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                continue  # mid-publish or mid-GC — not a version yet/anymore
            pub = Publication(int(m.group(1)), path, meta)
            if include_rejected or not pub.rejected:
                out.append(pub)
        return out

    def get(self, version: int) -> Publication:
        path = os.path.join(self.root, f"v_{int(version):06d}")
        with open(os.path.join(path, PUBLICATION_META)) as f:
            return Publication(version, path, json.load(f))

    def latest(self) -> Optional[Publication]:
        """The publication the LATEST pointer names, or None for an
        empty store."""
        try:
            with open(os.path.join(self.root, _LATEST)) as f:
                name = f.read().strip()
        except FileNotFoundError:
            return None
        m = _VER_DIR.match(name)
        if m is None:
            return None
        try:
            return self.get(int(m.group(1)))
        except (OSError, ValueError):
            return None

    # ----------------------------------------------------------- publishing
    def _write_latest(self, version: int) -> None:
        # the supervisor's pointer idiom: tmp in the same dir + rename
        tmp = os.path.join(self.root, "." + _LATEST + ".tmp")
        with open(tmp, "w") as f:
            f.write(f"v_{int(version):06d}")
        os.replace(tmp, os.path.join(self.root, _LATEST))

    def _write_publication_meta(self, path: str, meta: dict) -> None:
        tmp = os.path.join(path, "." + PUBLICATION_META + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(path, PUBLICATION_META))

    def publish(self, checkpoint_path: str, *, source: Optional[str] = None,
                extra: Optional[dict] = None) -> Publication:
        """Promote one complete checkpoint into the next version:
        copy, stamp, land atomically, repoint LATEST, GC retention.
        Returns the new :class:`Publication` (now == ``latest()``)."""
        checkpoint_path = os.path.abspath(checkpoint_path)
        if not is_valid_checkpoint(checkpoint_path):
            raise ValueError(
                f"not a complete checkpoint: {checkpoint_path} (needs the "
                "orbax tree dir AND meta.json — partial saves are not "
                "publishable)")
        fingerprint = _fingerprint_of_checkpoint(checkpoint_path)
        ckpt_meta = read_checkpoint_meta(checkpoint_path)
        prev = self.versions()
        version = (prev[-1].version + 1) if prev else 1
        name = f"v_{version:06d}"
        final = os.path.join(self.root, name)
        staged = os.path.join(self.root, f".{name}.tmp-{os.getpid()}")
        if os.path.isdir(staged):
            shutil.rmtree(staged)
        shutil.copytree(checkpoint_path, staged)
        meta = {
            "schema": 1,
            "version": version,
            "fingerprint": fingerprint,
            "source": source if source is not None else checkpoint_path,
            "published_unix": time.time(),
            "status": "published",
            "iteration": ckpt_meta.get("iteration"),
            "epoch": ckpt_meta.get("epoch"),
            "kind": ckpt_meta.get("kind"),
        }
        if extra:
            for k in extra:
                if k in meta:
                    raise ValueError(f"extra key {k!r} shadows a "
                                     "publication field")
            meta.update(extra)
        self._write_publication_meta(staged, meta)
        os.replace(staged, final)          # the version exists, atomically
        self._write_latest(version)
        self._gc()
        return Publication(version, final, meta)

    def publish_latest(self, checkpoint_dir: str, **kw) -> Publication:
        """Promote the newest *valid* ``step_<n>`` checkpoint under a
        supervisor checkpoint directory (``resilient_fit``'s
        ``checkpoint_dir``) — the one-call train→publish bridge."""
        ckpt = find_latest_checkpoint(checkpoint_dir)
        if ckpt is None:
            raise FileNotFoundError(
                f"no complete checkpoint under {checkpoint_dir}")
        return self.publish(ckpt, **kw)

    # ------------------------------------------------------------- rollback
    def rollback(self, reason: str = "") -> Publication:
        """The verb: mark the current LATEST version ``rejected`` (with
        the reason, for the post-mortem) and repoint LATEST at the
        newest non-rejected predecessor. Returns the publication LATEST
        now names. Raises RuntimeError when no good predecessor exists —
        a fleet must not silently keep serving a version its gates just
        killed."""
        cur = self.latest()
        if cur is None:
            raise RuntimeError("empty store: nothing to roll back")
        meta = dict(cur.meta)
        meta["status"] = "rejected"
        meta["rejected_unix"] = time.time()
        meta["rejected_reason"] = reason
        self._write_publication_meta(cur.path, meta)
        good = [p for p in self.versions(include_rejected=False)
                if p.version < cur.version]
        if not good:
            raise RuntimeError(
                f"v{cur.version} rejected but no earlier non-rejected "
                "version exists to roll back to")
        self._write_latest(good[-1].version)
        return good[-1]

    # ------------------------------------------------------------ retention
    def _gc(self) -> None:
        """Keep the newest ``keep`` versions plus whatever LATEST names
        (a rollback target older than the window must survive)."""
        pubs = self.versions()
        if len(pubs) <= self.keep:
            return
        latest = self.latest()
        protect = {p.version for p in pubs[-self.keep:]}
        if latest is not None:
            protect.add(latest.version)
        for p in pubs:
            if p.version not in protect:
                shutil.rmtree(p.path, ignore_errors=True)

    def describe(self) -> dict:
        latest = self.latest()
        return {
            "root": self.root,
            "latest_version": latest.version if latest else None,
            "versions": [p.describe() for p in self.versions()],
        }
