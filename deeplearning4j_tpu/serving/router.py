"""Cross-host serving federation: a queue-depth front-door router over
N single-host fleets.

Single-host serving tops out at one process: ``ReplicaSet`` already
routes tickets across N batcher workers *inside* a host, but every
request still lands on one admission queue, one device pool, one
failure domain. This module is the same design played one level up —
the TF-paper cluster serving model (PAPERS.md): many hosts behind one
front door, routed by live load, federated through the PR 8 metrics
plane.

- **Least-loaded routing for stateless /predict.** Each backend host
  pushes its metrics snapshot (``HeartbeatPusher`` -> the router's
  ``/api/metrics_push``); the router scores every routable host by its
  pushed ``dl4j_serving_queue_depth`` plus the router's own in-flight
  count to that host (the between-pushes signal), and proxies the
  request to the minimum — round-robin on ties, exactly the
  ``ReplicaSet._pick`` shape over hosts instead of replicas.
- **Session-affine routing for /decode.** A decode session's KV cache
  is warm on ONE host; the router pins ``sid -> host`` and keeps the
  session's full token history. Every forwarded ``step`` carries that
  history, so when the pinned host dies (connection error) or goes
  heartbeat-stale, the router re-pins to a survivor and the survivor's
  ``DecodeEngine`` re-prefills from the history — the PR 13
  eviction-recovery contract across processes, bit-identical (the
  history is appended only after a step's reply lands, so a lost reply
  replays exactly).
- **Host eviction + in-flight retry.** A connection-level failure
  evicts the host (status ``dead``) and retries the request on a
  survivor — safe for /predict (pure function of the payload) and for
  /decode (recovery-by-history makes the step idempotent). This is the
  PR 9 replica-eviction/requeue semantics one level up: a request
  escapes with an error only when EVERY host is gone.
- **Global backpressure.** When every routable host answers 503 the
  router answers 503 with ``Retry-After`` = the MINIMUM of the hosts'
  derived Retry-After values (header if the host replied, pushed
  ``dl4j_serving_retry_after_seconds`` gauge otherwise): the client
  should return when the SOONEST host expects headroom.
- **Degraded health, federated scoreboard.** ``GET /healthz`` answers
  ``ok`` / ``degraded`` (both 200) / ``unhealthy`` (503, no hosts
  left) — the PR 9 fleet semantics; ``GET /api/fleet`` serves the
  federation scoreboard plus the live routing table, and a router
  given ``push_url`` pushes its own snapshot (routing table in the
  health payload) to a dashboard UIServer, which renders it.
- **Heartbeat auto-eviction.** A host that stops pushing is first
  skipped (stale, past ``stale_after_s``) and then EVICTED once its
  silence exceeds ``evict_after_factor × stale_after_s`` — mirroring
  ``MetricsFederation.health()``'s own auto-evict, so the routing
  table and the scoreboard forget a dead host on the same clock. A
  host that never pushed at all stays trusted (the metrics plane is a
  routing signal, not an admission gate).
- **Canary routing + rollback as a verb** (SERVING.md §Live reload).
  ``start_canary(url, version=...)`` pins a traffic *fraction* to one
  canary-version host via a token bucket (the canary can never exceed
  its fraction — containment is structural, not statistical), keeps
  it out of stable routing and decode pinning, and snapshots a
  baseline of the fleet's pushed serving counters.
  ``evaluate_canary()`` differences live federation metrics against
  that baseline — error-rate delta, NaN-sentinel rows, p99 ratio vs
  the stable hosts — and answers pass / fail(+killing gate) / wait.
  ``promote_canary()`` admits the host to stable routing;
  ``rollback_canary()`` quarantines it (it still holds the bad
  weights), drops its decode pins so sessions fail over by
  re-prefill, and flushes a flight-recorder artifact (reason
  ``"rollback"``) naming the rejected version and the metric delta
  that killed it. ``reinstate(url)`` lifts the quarantine after the
  host has been swapped back to good weights.
- **Trace stitching + fleet SLOs** (OBSERVABILITY.md §Request tracing
  & SLOs). Every proxied hop records its [send, recv] window on the
  router's clock; backend hosts push their request-scoped span batches
  over the same ``/api/metrics_push`` wire; ``GET /api/trace/<id>``
  serves the clock-skew-rebased per-request waterfall stitched by
  ``TraceStore``. An ``SLOEngine`` over the same federation rows
  exposes ``dl4j_slo_*`` attainment / burn-rate / budget-remaining
  gauges on the router's ``/metrics`` and ``/api/fleet``.

The router never imports jax — it is a pure dispatch process, cheap
enough to front accelerator hosts without stealing their cores.
Receipts: ``scripts/crosshost_serve_bench.py`` -> CROSSHOST_SERVE_r01,
gated by BUDGETS.json ``cross_host_serving``. See SERVING.md
"Cross-host federation".
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import urlparse

from deeplearning4j_tpu.analysis.guards import guarded_by
from deeplearning4j_tpu.observability import metrics as _obs_metrics
from deeplearning4j_tpu.observability import slo as _obs_slo
from deeplearning4j_tpu.observability.distributed import (HeartbeatPusher,
                                                          MetricsFederation,
                                                          TRACE_HEADER,
                                                          TraceStore,
                                                          new_trace_id)
from deeplearning4j_tpu.scheduling import core as _sched

__all__ = ["FrontDoorRouter", "HostHandle", "NoHostsError",
           "BACKEND_HEADER"]

#: echoed on every proxied reply: which backend host served it
BACKEND_HEADER = "X-DL4J-Backend"

#: Retry-After floor when no host supplied a derived value (matches the
#: ServingStats clamp's low end)
_RETRY_AFTER_FLOOR_S = 0.05

LIVE, DEAD = "live", "dead"


class NoHostsError(RuntimeError):
    """Every backend host is evicted or stale — nothing to route to."""


class _HostDown(Exception):
    """Connection-level failure talking to one host (refused / reset /
    timeout) — triggers eviction + retry, never escapes the router."""


@guarded_by("_lock", "_idle", "in_flight", "picks", "status", "errors")
class HostHandle:
    """One backend host: address, status, a small keep-alive connection
    pool, and the router-side load/accounting counters."""

    def __init__(self, base_url: str, timeout_s: float = 60.0):
        self.base_url = base_url.rstrip("/")
        u = urlparse(self.base_url)
        self.addr = (u.hostname or "127.0.0.1", u.port or 80)
        self.timeout_s = float(timeout_s)
        self.status = LIVE
        self.in_flight = 0
        self.picks = 0
        self.errors = 0
        #: unix time of the host's last observed federation push —
        #: derived from pushed heartbeat age, so it survives the
        #: federation's own auto-evict dropping the row (the router
        #: still knows how long this host has been silent)
        self.last_push_unix: Optional[float] = None
        self._lock = threading.Lock()
        self._idle: List[http.client.HTTPConnection] = []

    # ------------------------------------------------------- connection pool
    def acquire(self) -> http.client.HTTPConnection:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return http.client.HTTPConnection(*self.addr,
                                          timeout=self.timeout_s)

    def release(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if self.status == LIVE and len(self._idle) < 32:
                self._idle.append(conn)
                return
        conn.close()

    def discard(self, conn: http.client.HTTPConnection) -> None:
        try:
            conn.close()
        except Exception:
            pass

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for c in idle:
            self.discard(c)

    # ------------------------------------------------------------ accounting
    def enter(self) -> None:
        with self._lock:
            self.in_flight += 1
            self.picks += 1

    def leave(self) -> None:
        with self._lock:
            self.in_flight -= 1

    def describe(self) -> dict:
        with self._lock:
            return {"url": self.base_url, "status": self.status,
                    "in_flight": self.in_flight, "picks": self.picks,
                    "errors": self.errors}


@guarded_by("_lock", "_hosts", "_rr", "_affinity", "_history",
            "requests_total", "decode_steps_total", "retried_total",
            "evicted_total", "failovers_total", "affinity_hits",
            "affinity_misses", "shed_total", "auto_evicted_total",
            "rollbacks_total", "promotions_total", "_quarantined",
            "_canary", "_canary_credit", "canary_routed_total")
class FrontDoorRouter:
    """The front door: an HTTP server federating N backend
    ``ModelServer`` hosts.

    ``hosts`` are backend base URLs (``http://127.0.0.1:9500``); more
    can join live via :meth:`add_host` (the bench grows the fleet
    mid-run to measure scaling through ONE router). ``stale_after_s``
    is the heartbeat-age bound past which a host stops receiving new
    requests (it is not evicted — a paused host resumes when its pushes
    resume; eviction is for connection-level death).
    """

    def __init__(self, hosts=(), host: str = "127.0.0.1", port: int = 0,
                 *, stale_after_s: float = 10.0,
                 evict_after_factor: Optional[float] = 4.0,
                 request_timeout_s: float = 120.0,
                 federation: Optional[MetricsFederation] = None,
                 push_url: Optional[str] = None,
                 push_interval_s: float = 2.0,
                 scheduler=None, sched_capacity: Optional[int] = None):
        self.host = host
        self.port = port
        self.request_timeout_s = float(request_timeout_s)
        #: front-door admission (SERVING.md §Traffic engine): tenant
        #: quotas and deadline sheds run HERE, before a doomed request
        #: costs a backend round trip; class watermarks run here too
        #: when ``sched_capacity`` (aggregate queue bound) is set,
        #: otherwise the hosts' own schedulers enforce them. Default
        #: SchedulingCore = no quotas, so legacy traffic is untouched;
        #: scheduler=False disables front-door admission entirely.
        if scheduler is False:
            self.scheduler = None
        elif scheduler is None:
            self.scheduler = _sched.SchedulingCore()
        else:
            self.scheduler = scheduler
        self.sched_capacity = sched_capacity
        self.federation = federation if federation is not None else \
            MetricsFederation(stale_after_s=stale_after_s)
        #: auto-eviction threshold as a multiple of the federation's
        #: ``stale_after_s`` (mirrors MetricsFederation.health); None
        #: disables — stale hosts are then only skipped, never evicted
        #: request-scoped span index (OBSERVABILITY.md §Request
        #: tracing): hosts' pushed span batches land here via
        #: /api/metrics_push, the router's own per-hop send/recv
        #: anchors enter in _proxy, and GET /api/trace/<id> serves the
        #: stitched waterfall. Internally locked.
        self.trace_store = TraceStore()
        #: fleet-level SLO engine fed from the SAME federation rows the
        #: router routes by; its gauge families ride the router's
        #: /metrics exposition and push_url heartbeats. Internally
        #: locked.
        self.slo_engine = _obs_slo.SLOEngine(_obs_slo.default_serving_slos(
            p99_bound_ms=float(os.environ.get("DL4J_TPU_SLO_P99_MS",
                                              "500"))))
        self.evict_after_factor = (None if evict_after_factor is None
                                   else float(evict_after_factor))
        if self.evict_after_factor is not None \
                and self.evict_after_factor < 1.0:
            raise ValueError("evict_after_factor must be >= 1 (eviction "
                             "below the stale bound would drop hosts the "
                             "router still routes to)")
        self._hosts: List[HostHandle] = []
        self._lock = threading.Lock()
        self._rr = 0                       # round-robin tiebreak cursor
        #: sid -> pinned HostHandle (the affinity map, one level up)
        self._affinity: Dict[str, HostHandle] = {}
        #: sid -> full token history (prompt + accepted steps) — the
        #: cross-host recovery source; ints, so it stays tiny
        self._history: Dict[str, List[int]] = {}
        self.requests_total = 0
        self.decode_steps_total = 0
        self.retried_total = 0            # in-flight retries onto survivors
        self.evicted_total = 0
        self.failovers_total = 0          # decode sessions re-pinned
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.shed_total = 0               # global-backpressure 503s
        self.auto_evicted_total = 0       # heartbeat-silence evictions
        # ---- canary state (SERVING.md §Live reload) ----
        self._canary: Optional[dict] = None
        self._canary_credit = 0.0         # token bucket: += fraction/request
        self.canary_routed_total = 0
        self.rollbacks_total = 0
        self.promotions_total = 0
        #: hosts rolled back while still holding rejected weights — out
        #: of ALL routing until reinstate()
        self._quarantined: set = set()
        self.last_rollback_artifact: Optional[str] = None
        self._registry_collector = None
        self._httpd = None
        self._thread = None
        self._pusher: Optional[HeartbeatPusher] = None
        self._push_url = push_url
        self._push_interval_s = float(push_interval_s)
        for u in hosts:
            self.add_host(u)

    # -------------------------------------------------------------- topology
    def add_host(self, base_url: str) -> HostHandle:
        h = HostHandle(base_url, timeout_s=self.request_timeout_s)
        with self._lock:
            self._hosts.append(h)
        return h

    @property
    def hosts(self) -> List[HostHandle]:
        with self._lock:
            return list(self._hosts)

    def _fed_rows(self) -> Dict[str, dict]:
        """Federation health rows keyed by the pushing host's
        self-reported ``server_url`` (ModelServer puts it in the health
        payload) — the join between 'who pushed' and 'where I proxy'."""
        rows = {}
        for row in self.federation.health():
            url = (row.get("health") or {}).get("server_url")
            if url:
                rows[url.rstrip("/")] = row
        return rows

    def _evict(self, h: HostHandle) -> None:
        with self._lock:
            if h.status == DEAD:
                return
            h.status = DEAD
            h.errors += 1
            self.evicted_total += 1
        h.close()

    def _auto_evict(self, h: HostHandle) -> None:
        """Heartbeat-silence eviction (the MetricsFederation.health
        mirror): the host stops being a routing candidate permanently —
        a resurrected process rejoins via add_host, with fresh state."""
        with self._lock:
            if h.status == DEAD:
                return
            h.status = DEAD
            self.evicted_total += 1
            self.auto_evicted_total += 1
        h.close()

    # --------------------------------------------------------------- routing
    def _routable(self, exclude=()) -> List[HostHandle]:
        """Hosts new STABLE work may go to: not evicted, not
        quarantined (rolled-back canary weights), not the active canary
        host (it only receives its token-bucket fraction), not
        heartbeat-stale — and hosts silent past ``evict_after_factor ×
        stale_after_s`` are auto-evicted here, on the routing path, the
        same place staleness is already observed. A host that has never
        pushed is trusted (the metrics plane is a routing signal, not
        an admission gate)."""
        fed = self._fed_rows()
        now = time.time()
        with self._lock:
            canary_host = self._canary["host"] if self._canary else None
            quarantined = set(self._quarantined)
        out = []
        for h in self.hosts:
            if h.status != LIVE or h in exclude:
                continue
            row = fed.get(h.base_url)
            if row is not None:
                # stamp observed push recency so the silence clock keeps
                # running even after the federation drops the row
                h.last_push_unix = now - float(row["heartbeat_age_s"])
            if self.evict_after_factor is not None \
                    and h.last_push_unix is not None \
                    and (now - h.last_push_unix
                         > self.evict_after_factor
                         * self.federation.stale_after_s):
                self._auto_evict(h)
                continue
            if h in quarantined or h is canary_host:
                continue
            if row is not None and not row["live"]:
                continue
            out.append((h, row))
        return out

    def _pick(self, exclude=()) -> Optional[HostHandle]:
        """Least-loaded routable host: pushed queue depth + local
        in-flight, round-robin on ties — ``ReplicaSet._pick`` over
        hosts."""
        cands = self._routable(exclude)
        if not cands:
            return None
        scored = []
        for h, row in cands:
            depth = (row or {}).get("queue_depth") or 0
            scored.append((depth + h.in_flight, h))
        best = min(s for s, _ in scored)
        ties = [h for s, h in scored if s == best]
        with self._lock:
            self._rr += 1
            return ties[self._rr % len(ties)]

    def _pick_affine(self, sid: str) -> Optional[HostHandle]:
        """The session's pinned host while it remains routable; a
        fresh least-loaded pin otherwise (first touch = miss, re-pin
        after host loss = failover, both counted)."""
        with self._lock:
            pinned = self._affinity.get(sid)
        if pinned is not None:
            fed_row = self._fed_rows().get(pinned.base_url)
            stale = fed_row is not None and not fed_row["live"]
            if pinned.status == LIVE and not stale:
                with self._lock:
                    self.affinity_hits += 1
                return pinned
        h = self._pick()
        if h is None:
            return None
        with self._lock:
            if pinned is not None:
                self.failovers_total += 1
            self.affinity_misses += 1
            self._affinity[sid] = h
        return h

    def _min_retry_after(self, collected: List[float]) -> float:
        """The aggregated Retry-After for a fleet-wide 503: the soonest
        any host expects headroom — reply headers first, pushed
        ``retry_after_s`` gauges as the fallback."""
        vals = list(collected)
        for row in self._fed_rows().values():
            ra = row.get("retry_after_s")
            if ra is not None:
                vals.append(float(ra))
        return min(vals) if vals else _RETRY_AFTER_FLOOR_S

    # ---------------------------------------------------------------- canary
    def _serving_counters(self, url: str) -> Optional[dict]:
        """The host's pushed canary-gate slice (``health["serving"]``
        from ModelServer._push_health), or None before its first push."""
        row = self._fed_rows().get(url.rstrip("/"))
        if row is None:
            return None
        return (row.get("health") or {}).get("serving")

    def start_canary(self, base_url: str, *, version=None,
                     fraction: float = 0.1,
                     max_error_rate_delta: float = 0.02,
                     max_nan_rows: int = 0,
                     max_p99_ratio: float = 3.0,
                     min_requests: int = 20) -> dict:
        """Begin canarying one host: it leaves stable routing and
        receives exactly ``fraction`` of /predict traffic via a token
        bucket (credit accrues per request; the canary is picked only
        when a whole token is banked, so its share can NEVER exceed the
        fraction — containment by construction). The host may already
        be registered (add_host) or is registered here. Baselines for
        the promotion gates are snapshotted from the live federation
        plane now; ``evaluate_canary`` differences against them.

        Gates: ``max_error_rate_delta`` (canary errors per canary
        request above the stable fleet's rate), ``max_nan_rows``
        (NaN-sentinel rows since baseline — 0 means one poisoned reply
        kills it), ``max_p99_ratio`` (canary p99 over the stable
        median), all judged only after ``min_requests`` canary
        requests."""
        if not 0.0 < fraction <= 0.5:
            raise ValueError("canary fraction must be in (0, 0.5] — above "
                             "half, the 'canary' is the fleet")
        url = base_url.rstrip("/")
        h = next((x for x in self.hosts if x.base_url == url), None)
        if h is None:
            h = self.add_host(url)
        with self._lock:
            if self._canary is not None:
                raise RuntimeError(
                    f"canary already active (v{self._canary['version']} on "
                    f"{self._canary['host'].base_url}) — promote or roll "
                    "back first")
            if h in self._quarantined:
                raise RuntimeError(f"{url} is quarantined (rolled back) — "
                                   "reinstate() it first")
        baseline = {"canary": self._serving_counters(url) or {},
                    "stable": {x.base_url: self._serving_counters(x.base_url)
                               for x in self.hosts
                               if x is not h and x.status == LIVE}}
        canary = {"host": h, "version": version, "fraction": float(fraction),
                  "gates": {"max_error_rate_delta": float(
                                max_error_rate_delta),
                            "max_nan_rows": int(max_nan_rows),
                            "max_p99_ratio": float(max_p99_ratio),
                            "min_requests": int(min_requests)},
                  "baseline": baseline, "started_unix": time.time(),
                  "routed": 0}
        with self._lock:
            self._canary = canary
            self._canary_credit = 0.0
        return {"host": url, "version": version, "fraction": fraction}

    def _pick_canary_admitted(self, tried) -> Optional[HostHandle]:
        """The /predict pick: the canary host when the token bucket has
        banked a whole token (and the canary is still alive and not yet
        tried), the stable least-loaded pick otherwise. A canary that
        fails mid-request lands in ``tried`` and the retry goes stable —
        the client never pays for the canary's death."""
        with self._lock:
            can = self._canary
            take = False
            if can is not None and not tried \
                    and can["host"].status == LIVE:
                self._canary_credit += can["fraction"]
                if self._canary_credit >= 1.0:
                    self._canary_credit -= 1.0
                    can["routed"] += 1
                    self.canary_routed_total += 1
                    take = True
        if take:
            return can["host"]
        return self._pick(exclude=tried)

    def evaluate_canary(self) -> dict:
        """Judge the active canary against its gates using live
        federation deltas. Returns a verdict dict: ``decision`` is
        ``"pass"`` / ``"fail"`` / ``"wait"`` (not enough canary traffic
        yet, or no push since baseline); on fail, ``killed_by`` names
        the gate and the measured delta — exactly what the rollback
        flight record carries."""
        with self._lock:
            can = self._canary
        if can is None:
            raise RuntimeError("no active canary")
        gates = can["gates"]
        url = can["host"].base_url
        now = self._serving_counters(url)
        base = can["baseline"]["canary"]
        verdict = {"version": can["version"], "host": url,
                   "fraction": can["fraction"], "routed": can["routed"],
                   "decision": "wait", "killed_by": None, "deltas": {}}
        if now is None:
            return verdict  # nothing pushed since the canary booted
        d_req = (now.get("requests_total") or 0) \
            - (base.get("requests_total") or 0)
        d_err = (now.get("errors_total") or 0) \
            - (base.get("errors_total") or 0)
        d_nan = (now.get("nan_rows_total") or 0) \
            - (base.get("nan_rows_total") or 0)
        verdict["deltas"] = {"requests": d_req, "errors": d_err,
                             "nan_rows": d_nan}
        # stable p99 median for the ratio gate, from live pushes
        stable_p99 = sorted(
            s["latency_p99_ms"]
            for s in (self._serving_counters(u)
                      for u in can["baseline"]["stable"])
            if s and s.get("latency_p99_ms") is not None)
        p99 = now.get("latency_p99_ms")
        if p99 is not None and stable_p99:
            med = stable_p99[len(stable_p99) // 2]
            if med > 0:
                verdict["deltas"]["p99_ratio"] = round(p99 / med, 3)
        # NaN gate first: a poisoned version must die before min_requests
        # worth of users see it — one bad reply is already the evidence
        if d_nan > gates["max_nan_rows"]:
            verdict.update(decision="fail", killed_by={
                "gate": "max_nan_rows", "bound": gates["max_nan_rows"],
                "measured": d_nan})
            return verdict
        if d_req < gates["min_requests"]:
            return verdict
        err_rate = d_err / d_req if d_req else 0.0
        if err_rate > gates["max_error_rate_delta"]:
            verdict.update(decision="fail", killed_by={
                "gate": "max_error_rate_delta",
                "bound": gates["max_error_rate_delta"],
                "measured": round(err_rate, 4)})
            return verdict
        ratio = verdict["deltas"].get("p99_ratio")
        if ratio is not None and ratio > gates["max_p99_ratio"]:
            verdict.update(decision="fail", killed_by={
                "gate": "max_p99_ratio", "bound": gates["max_p99_ratio"],
                "measured": ratio})
            return verdict
        verdict["decision"] = "pass"
        return verdict

    def promote_canary(self) -> dict:
        """Admit the canary host to stable routing (the token bucket
        stops; it now competes least-loaded like everyone else).
        Promotion is the caller's decision — evaluate first; this does
        not re-judge."""
        with self._lock:
            can = self._canary
            if can is None:
                raise RuntimeError("no active canary")
            self._canary = None
            self._canary_credit = 0.0
            self.promotions_total += 1
        return {"promoted": can["host"].base_url,
                "version": can["version"], "routed": can["routed"]}

    def rollback_canary(self, verdict: Optional[dict] = None,
                        reason: str = "") -> dict:
        """The rollback verb, router side: quarantine the canary host
        (it still HOLDS the rejected weights — it must not rejoin
        stable routing until reinstate()), drop its decode pins so
        sessions fail over by history re-prefill, and flush a
        flight-recorder artifact (reason ``"rollback"``) naming the
        rejected version and the gate delta that killed it. The weight
        store's own ``rollback()`` (serving/publish.py) repoints LATEST
        — the orchestrator calls both, chaos_livereload.py is the
        receipt."""
        with self._lock:
            can = self._canary
            if can is None:
                raise RuntimeError("no active canary")
            h = can["host"]
            self._canary = None
            self._canary_credit = 0.0
            self._quarantined.add(h)
            self.rollbacks_total += 1
            dropped = [sid for sid, ph in self._affinity.items() if ph is h]
            for sid in dropped:
                del self._affinity[sid]
        detail = {"rejected_version": can["version"],
                  "host": h.base_url, "routed": can["routed"],
                  "fraction": can["fraction"],
                  "reason": reason or None,
                  "killed_by": (verdict or {}).get("killed_by"),
                  "deltas": (verdict or {}).get("deltas")}
        from deeplearning4j_tpu.observability.flightrec import (
            get_flight_recorder)
        rec = get_flight_recorder()
        if rec is not None:
            rec.record_event("canary_rollback",
                             detail=json.dumps(detail, sort_keys=True))
            self.last_rollback_artifact = rec.flush("rollback")
        return {"rolled_back": h.base_url, "version": can["version"],
                "quarantined": True, "sessions_dropped": len(dropped),
                "artifact": self.last_rollback_artifact, **detail}

    def reinstate(self, base_url: str) -> bool:
        """Lift a rolled-back host's quarantine — AFTER it has been
        swapped back to good weights (hot_swap / restart on a good
        publication). Returns whether anything changed."""
        url = base_url.rstrip("/")
        with self._lock:
            for h in list(self._quarantined):
                if h.base_url == url:
                    self._quarantined.discard(h)
                    return True
        return False

    # ---------------------------------------------------------------- proxy
    def _proxy(self, h: HostHandle, path: str, body: bytes,
               trace_id: str, headers=None):
        """One request/reply over the host's pooled connection. Raises
        ``_HostDown`` on any connection-level failure. ``headers``
        carries the end-to-end scheduling headers (tenant / priority /
        deadline) hop to hop, exactly like the trace id. Every hop's
        [send, recv] window lands in the trace store on the router's
        own clock — the anchors the stitcher rebases every remote
        instance's spans against (a dead hop records with no status:
        the waterfall shows the attempt that failed over)."""
        conn = h.acquire()  # analysis: ok(C001) — pooled connection, not a lock; released/discarded below
        send_unix = time.time()
        try:
            hdrs = {"Content-Type": "application/json",
                    TRACE_HEADER: trace_id}
            if headers:
                hdrs.update(headers)
            conn.request("POST", path, body, hdrs)
            resp = conn.getresponse()
            data = resp.read()
            retry_after = resp.getheader("Retry-After")
            h.release(conn)
            self.trace_store.observe_network(
                trace_id, host=h.base_url, path=path,
                send_unix=send_unix, recv_unix=time.time(),
                status=resp.status)
            return resp.status, data, retry_after
        except (OSError, http.client.HTTPException) as e:
            h.discard(conn)
            self.trace_store.observe_network(
                trace_id, host=h.base_url, path=path,
                send_unix=send_unix, recv_unix=time.time())
            raise _HostDown(f"{h.base_url}: {type(e).__name__}: {e}")

    def _route(self, path: str, body: bytes, trace_id: str,
               pick_fn, headers=None, shed_klass=None) -> tuple:
        """Pick -> proxy -> on host death evict + retry on a survivor;
        on fleet-wide 503, shed with the aggregated Retry-After (and
        the shed class, accounted per class in the scheduler).
        Returns (status, payload bytes, headers list)."""
        tried: List[HostHandle] = []
        retry_afters: List[float] = []
        while True:
            h = pick_fn(tried)
            if h is None:
                break
            h.enter()
            try:
                status, data, ra = self._proxy(h, path, body, trace_id,
                                               headers)
            except _HostDown:
                self._evict(h)
                tried.append(h)
                with self._lock:
                    self.retried_total += 1
                continue
            finally:
                h.leave()
            if status == 503:
                # overloaded (or draining) host: try the others before
                # bouncing the client — that IS the front door's job
                if ra is not None:
                    try:
                        retry_afters.append(float(ra))
                    except ValueError:
                        pass
                tried.append(h)
                continue
            return status, data, [(BACKEND_HEADER, h.base_url)], h
        if tried:
            with self._lock:
                self.shed_total += 1
            k = _sched.normalize_class(shed_klass)
            if self.scheduler is not None:
                self.scheduler.record_shed(k)
            ra = self._min_retry_after(retry_afters)
            return (503,
                    json.dumps({"error": "all hosts overloaded or "
                                         "unreachable"}).encode(),
                    [("Retry-After", f"{ra:g}"),
                     (_sched.SHED_CLASS_HEADER, k)], None)
        raise NoHostsError("no routable backend hosts")

    def _front_door_admit(self, sched) -> Optional[tuple]:
        """Tentpole: run the scheduler BEFORE any backend round trip.
        Quota and deadline sheds are decided entirely from router-local
        state (token buckets; the min pushed retry_after_s as the wait
        estimate), so a doomed request costs nothing downstream. The
        class watermark runs here only when ``sched_capacity`` gives
        the router an aggregate queue bound — otherwise the hosts'
        own schedulers enforce it against their real capacity. Returns
        a (status, body, headers) 503 triple on shed, None on admit."""
        if self.scheduler is None:
            return None
        sched = sched or {}
        depth = capacity = None
        if self.sched_capacity:
            capacity = self.sched_capacity
            depth = sum(int(r.get("queue_depth") or 0)
                        for r in self._fed_rows().values() if r["live"])
        wait = None
        if sched.get("deadline_ms") is not None:
            wait = self._min_retry_after([])
        try:
            self.scheduler.admit(
                tenant=sched.get("tenant"), klass=sched.get("klass"),
                deadline_ms=sched.get("deadline_ms"),
                depth=depth, capacity=capacity, wait_estimate_s=wait)
        except _sched.ShedError as e:
            with self._lock:
                self.shed_total += 1
            ra = self._min_retry_after([])
            return (503, json.dumps({"error": f"overloaded: {e}"}).encode(),
                    [("Retry-After", f"{ra:g}"),
                     (_sched.SHED_CLASS_HEADER, e.klass)])
        return None

    # ------------------------------------------------------------- endpoints
    def handle_predict(self, body: bytes, trace_id: str,
                       sched=None) -> tuple:
        shed = self._front_door_admit(sched)
        if shed is not None:
            return shed
        with self._lock:
            self.requests_total += 1
        return self._route("/predict", body, trace_id,
                           self._pick_canary_admitted,
                           headers=_sched.build_sched_headers(sched),
                           shed_klass=(sched or {}).get("klass"))[:3]

    def handle_decode(self, payload: dict, trace_id: str,
                      sched=None) -> tuple:
        """Session-affine proxy for the host /decode protocol. The
        router owns the canonical token history; the host request
        always carries it, so ANY host can serve the step by
        re-prefilling (the host's DecodeEngine does exactly that for an
        unknown sid)."""
        op = payload.get("op")
        sid = payload.get("sid")
        if not sid or op not in ("prefill", "step", "generate", "close"):
            return (400, json.dumps(
                {"error": "decode payload needs op "
                          "(prefill|step|generate|close) and sid"})
                .encode(), [])
        fwd = _sched.build_sched_headers(sched)
        sk = (sched or {}).get("klass")
        if op != "close":
            # close is cleanup, never shed — a quota-exhausted tenant
            # must still be able to release its pool pages
            shed = self._front_door_admit(sched)
            if shed is not None:
                return shed
        if op == "prefill":
            ids = [int(i) for i in payload.get("ids") or ()]
            if not ids:
                return (400, json.dumps(
                    {"error": "prefill needs ids"}).encode(), [])
            with self._lock:
                self._history[sid] = list(ids)
            body = json.dumps({"op": "prefill", "sid": sid,
                               "ids": ids}).encode()
            status, data, headers, _ = self._route(
                "/decode", body, trace_id,
                lambda tried: (self._pick_affine(sid) if not tried
                               else self._pick(exclude=tried)),
                headers=fwd, shed_klass=sk)
            return status, data, headers
        if op == "close":
            # broadcast to EVERY live host, not just the pinned one: a
            # session that failed over (or whose prefix pages were
            # adopted after an eviction elsewhere) holds pool pages on
            # hosts it is no longer pinned to, and close must release
            # those page references fleet-wide. Close is idempotent on
            # hosts that never saw the sid, so this needs no protocol
            # change — `closed` reports whether ANY host knew it.
            with self._lock:
                self._history.pop(sid, None)
                pinned = self._affinity.pop(sid, None)
                hosts = [h for h in self._hosts if h.status == LIVE]
            if pinned is not None and pinned not in hosts \
                    and pinned.status == LIVE:
                hosts.append(pinned)
            closed = False
            body = json.dumps({"op": "close", "sid": sid}).encode()
            served = pinned
            for h in hosts:
                try:
                    status, data, ra = self._proxy(h, "/decode", body,
                                                   trace_id)
                    if status == 200 and json.loads(
                            data.decode() or "{}").get("closed"):
                        closed = True
                        served = h
                except _HostDown:
                    self._evict(h)
            backend = [(BACKEND_HEADER, served.base_url)] \
                if served is not None else []
            return 200, json.dumps({"closed": closed}).encode(), backend
        if op == "generate":
            # multi-token proxy: the host runs the whole greedy loop
            # (speculatively when its engine carries a draft); the
            # router still owns the canonical history, so failover and
            # replay semantics match step — history grows only by the
            # tokens a 200 reply confirmed
            with self._lock:
                ids = [int(i) for i in (payload.get("ids")
                                        or self._history.get(sid) or ())]
            if not ids:
                return (400, json.dumps(
                    {"error": "generate needs ids (or a prior "
                              "prefill)"}).encode(), [])
            with self._lock:
                self._history[sid] = list(ids)

            def gpick(tried):
                if not tried:
                    return self._pick_affine(sid)
                h = self._pick(exclude=tried)
                if h is not None:
                    with self._lock:
                        self.failovers_total += 1
                        self.affinity_misses += 1
                        self._affinity[sid] = h
                return h

            body = json.dumps({
                "op": "generate", "sid": sid, "ids": ids,
                "n_tokens": int(payload.get("n_tokens", 0))}).encode()
            status, data, headers, _ = self._route(
                "/decode", body, trace_id, gpick,
                headers=fwd, shed_klass=sk)
            if status == 200:
                toks = json.loads(data.decode() or "{}").get("tokens") \
                    or ()
                with self._lock:
                    hist = self._history.get(sid)
                    if hist is not None:
                        hist.extend(int(t) for t in toks)
                    self.decode_steps_total += len(toks)
            return status, data, headers
        # step
        with self._lock:
            history = list(self._history.get(sid) or ())
            self.decode_steps_total += 1
        if not history:
            return (404, json.dumps(
                {"error": f"unknown decode session '{sid}'"}).encode(), [])
        token = int(payload["token"])

        def pick(tried):
            if not tried:
                return self._pick_affine(sid)
            # failover mid-step: the pinned host just died under us —
            # re-pin to a survivor; its engine recovers from `ids`
            h = self._pick(exclude=tried)
            if h is not None:
                with self._lock:
                    self.failovers_total += 1
                    self.affinity_misses += 1
                    self._affinity[sid] = h
            return h

        body = json.dumps({"op": "step", "sid": sid, "token": token,
                           "ids": history}).encode()
        status, data, headers, _ = self._route("/decode", body, trace_id,
                                               pick, headers=fwd,
                                               shed_klass=sk)
        if status == 200:
            # history grows only on a confirmed reply: a retried lost
            # reply re-sends the SAME history, so the survivor's
            # re-prefill replays the session bit-identically
            with self._lock:
                hist = self._history.get(sid)
                if hist is not None:
                    hist.append(token)
        return status, data, headers

    def handle_hosts(self, payload: dict) -> tuple:
        """POST /api/hosts — topology as an HTTP verb, symmetric with
        eviction: ``{"url": ..., "action": "add"}`` registers a backend
        (the autoscaler's cross-host actuator calls this after the
        launcher boots a warm child), ``"evict"`` removes one. The next
        /api/fleet scrape reflects the change — the routing table and
        the federation scoreboard are both derived, not cached."""
        url = str(payload.get("url") or "").rstrip("/")
        action = payload.get("action") or "add"
        if not url or action not in ("add", "evict"):
            return 400, {"error": "needs url and action (add|evict)"}
        if action == "add":
            existing = next((h for h in self.hosts
                             if h.base_url == url and h.status == LIVE),
                            None)
            added = existing is None
            if added:
                self.add_host(url)
            return 200, {"ok": True, "action": "add", "url": url,
                         "added": added, "hosts": len(self.hosts)}
        target = next((h for h in self.hosts
                       if h.base_url == url and h.status == LIVE), None)
        if target is not None:
            self._evict(target)
        return 200, {"ok": True, "action": "evict", "url": url,
                     "evicted": target is not None,
                     "hosts": len(self.hosts)}

    # ----------------------------------------------------------------- state
    def route_table(self) -> List[dict]:
        """Per-host routing rows: status, load signals, traffic — the
        /api/fleet 'routing' section and the dashboard scoreboard."""
        fed = self._fed_rows()
        rows = []
        for h in self.hosts:
            row = fed.get(h.base_url)
            d = h.describe()
            d.update({
                "instance": row["instance"] if row else None,
                "routable": h.status == LIVE and (row is None
                                                  or row["live"]),
                "queue_depth": (row or {}).get("queue_depth"),
                "retry_after_s": (row or {}).get("retry_after_s"),
                "drain_rate_rows_per_s":
                    (row or {}).get("drain_rate_rows_per_s"),
                "heartbeat_age_s": (row or {}).get("heartbeat_age_s"),
            })
            rows.append(d)
        return rows

    def describe(self) -> dict:
        with self._lock:
            can = self._canary
            return {
                "hosts": len(self._hosts),
                "requests_total": self.requests_total,
                "decode_steps_total": self.decode_steps_total,
                "retried_total": self.retried_total,
                "evicted_total": self.evicted_total,
                "auto_evicted_total": self.auto_evicted_total,
                "failovers_total": self.failovers_total,
                "affinity_hits": self.affinity_hits,
                "affinity_misses": self.affinity_misses,
                "shed_total": self.shed_total,
                "sessions_live": len(self._history),
                "canary": (None if can is None else {
                    "host": can["host"].base_url,
                    "version": can["version"],
                    "fraction": can["fraction"],
                    "routed": can["routed"]}),
                "canary_routed_total": self.canary_routed_total,
                "rollbacks_total": self.rollbacks_total,
                "promotions_total": self.promotions_total,
                "quarantined": sorted(h.base_url
                                      for h in self._quarantined),
                "sched": (self.scheduler.snapshot()
                          if self.scheduler is not None else None),
            }

    def healthz(self) -> tuple:
        rows = self.route_table()
        n_live = sum(1 for r in rows if r["routable"])
        if rows and n_live == 0:
            return 503, {"status": "unhealthy",
                         "reason": "no routable backend hosts",
                         "hosts": rows}
        # some hosts down but traffic still flows: degraded, not down —
        # the same PR 9 fleet semantics, one level up
        status = "ok" if n_live == len(rows) else "degraded"
        return 200, {"status": status, "hosts": rows,
                     "router": self.describe()}

    def fleet_payload(self) -> dict:
        payload = self.federation.fleet_payload()
        payload["routing"] = self.route_table()
        payload["router"] = self.describe()
        # advance the SLO windows from the freshest federation rows
        # before reporting — /api/fleet is the bench's polling surface
        self.slo_engine.ingest_fed_rows(self.federation.health())
        payload["slo"] = self.slo_engine.report()
        payload["trace_store"] = self.trace_store.describe()
        return payload

    def _attach_registry_collector(self):
        """The router's own counters as registry families — rendered by
        its ``/metrics`` exposition via the federation AND carried by
        its push_url heartbeats (export_snapshot reads the same
        registry), so a dashboard sees canary/promotion/rollback state
        with no new endpoints."""
        from deeplearning4j_tpu.observability.metrics import MetricFamily

        def _collect():
            d = self.describe()
            L = {"router": f"{self.host}:{self.port}"}
            fams = []

            def fam(name, kind, help, value):
                fams.append(MetricFamily(name, kind, help).add(value, L))

            fam("dl4j_router_requests_total", "counter",
                "/predict requests through the front door",
                d["requests_total"])
            fam("dl4j_router_evicted_total", "counter",
                "Hosts evicted (connection death + heartbeat silence)",
                d["evicted_total"])
            fam("dl4j_router_auto_evicted_total", "counter",
                "Hosts auto-evicted for heartbeat silence past "
                "evict_after_factor x stale_after_s",
                d["auto_evicted_total"])
            fam("dl4j_router_canary_routed_total", "counter",
                "Requests token-bucket-admitted to canary hosts",
                d["canary_routed_total"])
            fam("dl4j_router_canary_fraction", "gauge",
                "Active canary traffic fraction (0 = no canary)",
                (d["canary"] or {}).get("fraction") or 0.0)
            fam("dl4j_router_promotions_total", "counter",
                "Canary versions promoted to stable routing",
                d["promotions_total"])
            fam("dl4j_router_rollbacks_total", "counter",
                "Canary versions rolled back by their gates",
                d["rollbacks_total"])
            # fleet SLO gauges: every scrape/push folds the freshest
            # federation counters into the sliding windows, then
            # renders attainment / burn-rate / budget-remaining
            self.slo_engine.ingest_fed_rows(self.federation.health())
            fams.extend(self.slo_engine.families())
            # front-door scheduler families (dl4j_sched_*) — the
            # router-side view of quota/class/deadline sheds
            if self.scheduler is not None:
                fams.extend(self.scheduler.metric_families(L))
            return fams

        reg = _obs_metrics.get_registry()
        reg.register_collector(_collect)
        self._registry_collector = (reg, _collect)

    # ---------------------------------------------------------------- server
    def start(self) -> "FrontDoorRouter":
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def log_message(self, *args):
                pass

            def _json(self, obj, code=200, headers=()):
                body = obj if isinstance(obj, bytes) \
                    else json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path.startswith("/healthz"):
                    code, obj = router.healthz()
                    self._json(obj, code)
                elif self.path.startswith("/api/fleet"):
                    self._json(router.fleet_payload())
                elif self.path.startswith("/api/trace"):
                    tid = self.path[len("/api/trace"):].strip("/")
                    tid = tid.split("?", 1)[0]
                    if tid:
                        wf = router.trace_store.waterfall(tid)
                        self._json(wf, 200 if wf["found"] else 404)
                    else:
                        self._json({
                            "traces": router.trace_store.trace_ids(),
                            "store": router.trace_store.describe()})
                elif self.path.startswith("/metrics"):
                    if _obs_metrics.wants_prometheus(
                            self.headers.get("Accept", ""), self.path):
                        # merged fleet exposition: every host's pushed
                        # families instance-labeled + the fleet rollup
                        text = router.federation.render_prometheus()
                        body = text.encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            _obs_metrics.PROMETHEUS_CONTENT_TYPE)
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self._json(router.describe())
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):  # noqa: N802
                trace_id = (self.headers.get(TRACE_HEADER)
                            or new_trace_id())
                sched = _sched.parse_sched_headers(self.headers)
                # echo the scheduling headers back like the trace id —
                # the client sees the normalized class it was admitted
                # (or shed) as, plus its own tenant/deadline
                echo = ((TRACE_HEADER, trace_id),
                        (_sched.PRIORITY_HEADER, sched["klass"]))
                if sched["tenant"]:
                    echo += ((_sched.TENANT_HEADER, sched["tenant"]),)
                if sched["deadline_ms"] is not None:
                    echo += ((_sched.DEADLINE_HEADER,
                              f"{sched['deadline_ms']:g}"),)
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                try:
                    if self.path.startswith("/predict"):
                        code, data, hdrs = router.handle_predict(
                            body, trace_id, sched)
                    elif self.path.startswith("/decode"):
                        code, data, hdrs = router.handle_decode(
                            json.loads(body.decode()), trace_id, sched)
                    elif self.path.startswith("/api/hosts"):
                        code, obj = router.handle_hosts(
                            json.loads(body.decode() or "{}"))
                        data, hdrs = json.dumps(obj).encode(), []
                    elif self.path.startswith("/api/metrics_push"):
                        snap = json.loads(body.decode())
                        tag = router.federation.ingest(snap)
                        # same push, second consumer: any span batch
                        # riding the snapshot lands in the trace store
                        router.trace_store.ingest_snapshot(snap)
                        code, data, hdrs = 200, json.dumps(
                            {"ok": True, "instance": tag}).encode(), []
                    else:
                        code, data, hdrs = 404, json.dumps(
                            {"error": "not found"}).encode(), []
                except NoHostsError as e:
                    code, data, hdrs = 503, json.dumps(
                        {"error": str(e)}).encode(), []
                except Exception as e:
                    code, data, hdrs = 400, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(), []
                self._json(data, code, tuple(hdrs) + echo)

        class _RouterHTTPServer(ThreadingHTTPServer):
            request_queue_size = 128
            daemon_threads = True

        self._httpd = _RouterHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._attach_registry_collector()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        if self._push_url:
            # the router is a fleet member too: its pushed health
            # payload carries the routing table, so a dashboard
            # UIServer's scoreboard renders it without new endpoints
            self._pusher = HeartbeatPusher(
                self._push_url, self._push_interval_s,
                health_fn=lambda: {"router_healthy": True,
                                   "server_url": self.url,
                                   "routing": self.route_table(),
                                   "router": self.describe()}).start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._pusher is not None:
            self._pusher.stop()
            self._pusher = None
        if self._registry_collector is not None:
            reg, collect = self._registry_collector
            reg.unregister_collector(collect)
            self._registry_collector = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for h in self.hosts:
            h.close()
