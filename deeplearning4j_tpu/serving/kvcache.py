"""Paged KV-cache pool: block-granular admission + LRU eviction for
decode sessions.

Autoregressive decode serving holds per-session state (each transformer
layer's KV cache plus positions) between requests — unbounded sessions
would grow that footprint without limit. This pool is the admission
tier: capacity is fixed in PAGES of ``page_tokens`` tokens each, every
session is charged ``ceil(tokens / page_tokens)`` pages for the prefix
it has decoded so far, and when an allocation would overflow the pool
the least-recently-used *other* session is evicted — its cached state is
dropped and its pages return to the free pool.

Eviction is RECOVERABLE, mirroring the replica tier's requeue stance
(fleet.py): the decode engine keeps each session's token history (ints —
thousands of times smaller than the KV tensors), so an evicted session
that comes back is transparently re-prefilled from history before its
next step. The session sees extra latency, never a wrong token: one-shot
prefill is bit-identical to the step-by-step path it replaces
(tests/test_transformer.py pins this), so recovery is invisible in the
output stream.

The pool stores each session's cache leaves verbatim (dense per-session
tensors, host-side numpy rows); "paged" here is the ACCOUNTING contract
— block-granular occupancy and eviction à la paged attention — not
physical page sharing between sessions. Occupancy (`pages_used /
n_pages`) and the eviction counter feed ``serve_bench --decode`` and the
metrics registry.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List

__all__ = ["KVPagePool", "CachePoolFullError"]


class CachePoolFullError(RuntimeError):
    """A single session needs more pages than the whole pool holds —
    admission must reject it (no amount of eviction can fit it)."""


class KVPagePool:
    """Fixed-capacity page accounting + LRU store for decode-session
    cache state.

    ``put`` charges/extends a session and stores its cache leaves,
    evicting least-recently-used other sessions as needed; ``get``
    retrieves (and LRU-touches) them; a ``get`` returning ``None`` means
    the session was evicted and must be re-prefilled from history.
    """

    def __init__(self, n_pages: int = 256, page_tokens: int = 16):
        if n_pages < 1 or page_tokens < 1:
            raise ValueError("n_pages and page_tokens must be >= 1")
        self.n_pages = int(n_pages)
        self.page_tokens = int(page_tokens)
        self._lock = threading.Lock()
        # sid -> (pages_held, cache leaves); insertion order = LRU order
        self._table: OrderedDict[str, tuple] = OrderedDict()
        self.evictions = 0          # sessions dropped to free pages
        self.evicted_pages = 0      # pages reclaimed by those drops

    # ------------------------------------------------------------ accounting
    def pages_for(self, tokens: int) -> int:
        return max(1, -(-int(tokens) // self.page_tokens))

    @property
    def pages_used(self) -> int:
        with self._lock:
            return sum(p for p, _ in self._table.values())

    @property
    def occupancy(self) -> float:
        return self.pages_used / self.n_pages

    @property
    def sessions(self) -> List[str]:
        with self._lock:
            return list(self._table)

    def describe(self) -> dict:
        with self._lock:
            used = sum(p for p, _ in self._table.values())
            return {"n_pages": self.n_pages, "page_tokens": self.page_tokens,
                    "pages_used": used, "occupancy": used / self.n_pages,
                    "sessions": len(self._table),
                    "evictions": self.evictions}

    # ----------------------------------------------------------------- store
    def put(self, sid: str, tokens: int, leaves) -> None:
        """Store/refresh ``sid``'s cache leaves and charge it for
        ``tokens`` decoded tokens, evicting LRU peers if the pool is
        full. Raises ``CachePoolFullError`` when the session alone
        exceeds pool capacity."""
        need = self.pages_for(tokens)
        if need > self.n_pages:
            raise CachePoolFullError(
                f"session '{sid}' needs {need} pages "
                f"({tokens} tokens @ {self.page_tokens}/page) but the "
                f"pool holds {self.n_pages}")
        with self._lock:
            self._table.pop(sid, None)   # re-charge at the new token count
            used = sum(p for p, _ in self._table.values())
            while used + need > self.n_pages:
                _victim, (vpages, _) = self._table.popitem(last=False)
                self.evictions += 1
                self.evicted_pages += vpages
                used -= vpages
            self._table[sid] = (need, leaves)

    def get(self, sid: str):
        """Cache leaves for ``sid`` (LRU-touched), or ``None`` if the
        session was evicted (caller re-prefills from token history)."""
        with self._lock:
            entry = self._table.pop(sid, None)
            if entry is None:
                return None
            self._table[sid] = entry   # move to MRU end
            return entry[1]

    def drop(self, sid: str) -> bool:
        """Voluntary release (session closed) — frees its pages without
        counting as an eviction."""
        with self._lock:
            return self._table.pop(sid, None) is not None
