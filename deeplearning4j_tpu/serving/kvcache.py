"""Paged KV-cache pool: block-granular admission, copy-on-write prefix
sharing, and refcounted LRU eviction for decode sessions.

Autoregressive decode serving holds per-session state (each transformer
layer's KV cache plus positions) between requests — unbounded sessions
would grow that footprint without limit. This pool is the admission
tier: capacity is fixed in PAGES of ``page_tokens`` tokens each, every
session is charged for the pages backing the prefix it has decoded so
far, and when an allocation would overflow the pool the least-recently-
used *other* session is released — its private state is dropped, its
references on shared pages are decremented, and only pages nobody still
holds return to the free pool.

**Prefix sharing (the PR 16 tentpole).** Sessions that begin with the
same tokens — the shared-system-prompt shape — produce bit-identical
cache pages (the fixed-extent exact-lowering contract, ops/attention.py),
so FULL pages are keyed by the exact token-history prefix that produced
them: ``tuple(ids[:page_end])``. A ``put`` that seals a page whose key
already exists takes a reference on the existing page instead of storing
a second copy; ``match_prefix`` lets a brand-new session adopt the
longest already-resident page chain of its prompt and skip that much
prefill compute. The key is the exact prefix, not a digest — two
different histories can never alias onto one page, which is what keeps
the decode bit-identity oracle satisfiable. Sharing is copy-on-write by
construction: shared pages are immutable; every session's growing edge
lives in a private TAIL (the partial last page plus the non-pageable
leaves such as positions), so a session that diverges mid-page simply
seals its own distinct page later — no shared state is ever mutated.

Eviction is RECOVERABLE and refcounted: evicting a session releases its
references, and a page survives as long as ANY holder remains
(evict-while-shared keeps it; the last holder's release frees it). The
evicted session's token history (kept by the engine, tiny) re-prefills
it transparently on its next step — and the re-prefill itself re-adopts
whatever pages its peers kept alive, so recovery after an eviction of a
shared session is cheap as well as bit-identical.

Legacy behavior is preserved: a ``put`` without ``ids``, or with leaves
the pool cannot page (no ``[1, extent, ...]`` cache axes — e.g. LSTM
``h``/``c`` carries, or the plain strings the accounting tests store),
falls back to the original dense per-session storage with pure
page-count accounting. Occupancy, the dedup ratio, and the shared-page
gauge feed ``serve_bench --decode`` and the metrics registry.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.analysis.guards import guarded_by

__all__ = ["KVPagePool", "CachePoolFullError"]


class CachePoolFullError(RuntimeError):
    """A single session needs more pages than the whole pool holds —
    admission must reject it (no amount of eviction can fit it)."""


class _Page:
    """One immutable shared page: a refcount plus, per pageable leaf,
    the ``[1, page_tokens, ...]`` slice of that leaf's token axis."""

    __slots__ = ("ref", "slices")

    def __init__(self, slices):
        self.ref = 1
        self.slices = slices


class _Entry:
    """Per-session pool record. ``dense`` holds the legacy verbatim
    leaves; paged sessions instead hold a chain of shared-page keys plus
    a private tail (partial-page slices + non-pageable leaves)."""

    __slots__ = ("tokens", "dense", "chain", "tail", "others")

    def __init__(self):
        self.tokens = 0
        self.dense = None            # legacy verbatim leaves (or None)
        self.chain: List[tuple] = []  # shared-page keys, page order
        self.tail = None             # per-pageable-leaf [1, r, ...] slices
        self.others: List[Tuple[int, object]] = []  # (leaf idx, leaf)

    @property
    def paged(self) -> bool:
        return self.dense is None


@guarded_by("_lock", "_table", "_shared", "_layout", "evictions",
            "evicted_pages", "page_hits", "prefix_matches", "truncations",
            "truncated_pages")
class KVPagePool:
    """Fixed-capacity page accounting + copy-on-write store for
    decode-session cache state.

    ``put`` charges/extends a session and stores its cache leaves
    (deduplicating sealed full pages against the shared store when
    ``ids`` is given), evicting least-recently-used other sessions as
    needed; ``get`` reassembles (and LRU-touches) them; a ``get``
    returning ``None`` means the session was evicted and must be
    re-prefilled from history. ``match_prefix`` adopts an existing
    sessions' pages for a new prompt sharing their prefix.
    """

    def __init__(self, n_pages: int = 256, page_tokens: int = 16,
                 prefix_sharing: bool = True):
        if n_pages < 1 or page_tokens < 1:
            raise ValueError("n_pages and page_tokens must be >= 1")
        self.n_pages = int(n_pages)
        self.page_tokens = int(page_tokens)
        self.prefix_sharing = bool(prefix_sharing)
        self._lock = threading.Lock()
        # sid -> _Entry; insertion order = LRU order
        self._table: "OrderedDict[str, _Entry]" = OrderedDict()
        # exact token-prefix tuple -> shared _Page
        self._shared: Dict[tuple, _Page] = {}
        # (n_leaves, pageable idx tuple, per-pageable extent, dtypes) —
        # pinned by the first paged put; one pool serves one model
        self._layout = None
        self.evictions = 0          # sessions dropped to free pages
        self.evicted_pages = 0      # pages actually freed by those drops
        self.page_hits = 0          # sealed pages deduped against peers
        self.prefix_matches = 0     # match_prefix adoptions
        self.truncations = 0        # speculative-reject rollbacks
        self.truncated_pages = 0    # pages freed by those rollbacks

    # ------------------------------------------------------------ accounting
    def pages_for(self, tokens: int) -> int:
        return max(1, -(-int(tokens) // self.page_tokens))

    def _physical_locked(self) -> int:
        """Distinct pages actually held: each shared page once, plus
        every session's private tail / dense charge."""
        used = len(self._shared)
        for ent in self._table.values():
            if ent.paged:
                used += 1 if ent.tail is not None else 0
            else:
                used += self.pages_for(ent.tokens)
        return used

    def _logical_locked(self) -> int:
        """Page charge as if nothing were shared — the numerator of the
        dedup ratio."""
        return sum(self.pages_for(ent.tokens)
                   for ent in self._table.values())

    @property
    def pages_used(self) -> int:
        with self._lock:
            return self._physical_locked()

    @property
    def occupancy(self) -> float:
        return self.pages_used / self.n_pages

    @property
    def sessions(self) -> List[str]:
        with self._lock:
            return list(self._table)

    def describe(self) -> dict:
        with self._lock:
            used = self._physical_locked()
            logical = self._logical_locked()
            shared = sum(1 for p in self._shared.values() if p.ref >= 2)
            return {"n_pages": self.n_pages, "page_tokens": self.page_tokens,
                    "pages_used": used, "occupancy": used / self.n_pages,
                    "sessions": len(self._table),
                    "evictions": self.evictions,
                    "prefix_sharing": self.prefix_sharing,
                    "shared_pages": shared,
                    "store_pages": len(self._shared),
                    "logical_pages": logical,
                    "dedup_ratio": (round(logical / used, 4) if used
                                    else None),
                    "page_hits": self.page_hits,
                    "prefix_matches": self.prefix_matches,
                    "truncations": self.truncations,
                    "truncated_pages": self.truncated_pages}

    # ------------------------------------------------------------- internals
    def _pageable_layout(self, tokens: int, leaves) -> Optional[tuple]:
        """Detect the pageable leaves: ``[1, extent, ...]`` arrays whose
        token axis covers this session. Returns the layout tuple, or
        ``None`` when nothing is pageable (dense fallback)."""
        idx, extents, dtypes = [], [], []
        for i, leaf in enumerate(leaves):
            shape = getattr(leaf, "shape", None)
            if (shape is not None and getattr(leaf, "ndim", 0) >= 3
                    and shape[0] == 1 and shape[1] >= tokens):
                idx.append(i)
                extents.append(int(shape[1]))
                dtypes.append(leaf.dtype)
        if not idx:
            return None
        return (len(list(leaves)), tuple(idx), tuple(extents),
                tuple(dtypes))

    def _release_locked(self, ent: _Entry) -> int:
        """Drop a session's holdings: decrement its chain refs (freeing
        pages at zero), drop its tail/dense charge. Returns pages freed."""
        freed = 0
        if not ent.paged:
            return self.pages_for(ent.tokens)
        for key in ent.chain:
            page = self._shared.get(key)
            if page is None:
                continue
            page.ref -= 1
            if page.ref <= 0:
                del self._shared[key]
                freed += 1
        if ent.tail is not None:
            freed += 1
        ent.chain, ent.tail, ent.others = [], None, []
        return freed

    def _evict_locked(self, keep_sid: str) -> None:
        """LRU-release other sessions until the pool fits. A victim all
        of whose pages are shared frees nothing by itself — survivors
        keep those pages — so the sweep continues to the next victim."""
        while self._physical_locked() > self.n_pages:
            victim = next((s for s in self._table if s != keep_sid), None)
            if victim is None:
                break   # only keep_sid remains; its own charge fits
            ent = self._table.pop(victim)
            self.evictions += 1
            self.evicted_pages += self._release_locked(ent)

    # ----------------------------------------------------------------- store
    def put(self, sid: str, tokens: int, leaves, ids=None) -> None:
        """Store/refresh ``sid``'s cache leaves and charge it for
        ``tokens`` decoded tokens, evicting LRU peers if the pool is
        full. With ``ids`` (the session's full token history, one id per
        token) and pageable leaves, sealed full pages are deduplicated
        against the shared store by exact prefix key. Raises
        ``CachePoolFullError`` when the session alone exceeds pool
        capacity."""
        need = self.pages_for(tokens)
        if need > self.n_pages:
            raise CachePoolFullError(
                f"session '{sid}' needs {need} pages "
                f"({tokens} tokens @ {self.page_tokens}/page) but the "
                f"pool holds {self.n_pages}")
        tokens = int(tokens)
        layout = None
        if self.prefix_sharing and ids is not None and len(ids) == tokens:
            layout = self._pageable_layout(tokens, leaves)
        with self._lock:
            ent = self._table.pop(sid, None)
            if layout is None:
                # legacy dense path (accounting-only, leaves verbatim)
                if ent is not None:
                    self._release_locked(ent)
                ent = _Entry()
                ent.tokens, ent.dense = tokens, leaves
                self._table[sid] = ent
                self._evict_locked(sid)
                return
            if self._layout is None:
                self._layout = layout
            if ent is None or not ent.paged:
                if ent is not None:
                    self._release_locked(ent)
                ent = _Entry()
            pt = self.page_tokens
            idst = tuple(int(i) for i in ids)
            n_full = tokens // pt
            # a re-prefill with a DIFFERENT history (sid reuse) must not
            # extend the stale chain — release and rebuild
            if ent.chain and (len(ent.chain) > n_full or ent.chain[-1]
                              != idst[:len(ent.chain) * pt]):
                self.evicted_pages += self._release_locked(ent)
            # the old tail is superseded by this put's fresh slices
            ent.tail = None
            pageable = layout[1]
            for p in range(len(ent.chain), n_full):
                key = idst[:(p + 1) * pt]
                page = self._shared.get(key)
                if page is not None:
                    page.ref += 1
                    self.page_hits += 1
                else:
                    page = _Page([np.ascontiguousarray(
                        leaves[i][:, p * pt:(p + 1) * pt])
                        for i in pageable])
                    self._shared[key] = page
                ent.chain.append(key)
            rem = tokens - n_full * pt
            if rem or not ent.chain:
                # always hold >= the admission floor of one page
                ent.tail = [np.ascontiguousarray(
                    leaves[i][:, n_full * pt:tokens]) for i in pageable]
            ent.others = [(i, leaves[i]) for i in range(layout[0])
                          if i not in pageable]
            ent.tokens = tokens
            ent.dense = None
            self._table[sid] = ent
            self._evict_locked(sid)

    def get(self, sid: str):
        """Cache leaves for ``sid`` (LRU-touched), or ``None`` if the
        session was evicted (caller re-prefills from token history).
        Paged sessions are reassembled to full-extent arrays; positions
        beyond the token frontier are zeros, which the fixed-extent
        attention never reads before overwriting."""
        with self._lock:
            ent = self._table.pop(sid, None)
            if ent is None:
                return None
            self._table[sid] = ent   # move to MRU end
            if not ent.paged:
                return ent.dense
            n_leaves, pageable, extents, dtypes = self._layout
            leaves: List[object] = [None] * n_leaves
            pt = self.page_tokens
            for j, i in enumerate(pageable):
                parts = [self._shared[key].slices[j] for key in ent.chain]
                if ent.tail is not None:
                    parts.append(ent.tail[j])
                row = parts[0].shape[2:]
                arr = np.zeros((1, extents[j]) + tuple(row), dtypes[j])
                if ent.tokens:
                    arr[:, :ent.tokens] = np.concatenate(parts, axis=1) \
                        if len(parts) > 1 else parts[0]
                leaves[i] = arr
            for i, leaf in ent.others:
                leaves[i] = leaf
            return leaves

    def match_prefix(self, sid: str, ids, align_tokens: Optional[int] = None
                     ) -> Tuple[int, Optional[dict]]:
        """Adopt the longest resident page chain matching a prefix of
        ``ids`` for a NEW session ``sid``: takes a reference on each
        matched page and installs the session's chain, so the caller can
        skip prefill compute for the covered tokens. Returns
        ``(n_tokens_covered, {leaf idx: [1, n, ...] partial})`` — or
        ``(0, None)`` when nothing matches. Always leaves at least one
        prompt token uncovered (the caller still needs logits for the
        last prompt token), and caps coverage at a multiple of
        ``align_tokens`` so the caller's segment ladder stays on its
        warmed rungs."""
        if not self.prefix_sharing:
            return 0, None
        with self._lock:
            if self._layout is None:
                return 0, None
            pt = self.page_tokens
            idst = tuple(int(i) for i in ids)
            limit = (len(idst) - 1) // pt
            if align_tokens:
                step = max(1, int(align_tokens) // pt)
                limit -= limit % step
            chain = []
            for p in range(limit):
                page = self._shared.get(idst[:(p + 1) * pt])
                if page is None:
                    break
                chain.append(idst[:(p + 1) * pt])
            if align_tokens:
                step = max(1, int(align_tokens) // pt)
                chain = chain[:len(chain) - (len(chain) % step)]
            if not chain:
                return 0, None
            # take the new references BEFORE releasing any old entry for
            # this sid: a live session re-prefilling over its own sealed
            # pages (repeat wire-op generate, speculative resync) would
            # otherwise free the very pages the chain just matched
            for key in chain:
                self._shared[key].ref += 1
            old = self._table.pop(sid, None)
            if old is not None:
                self._release_locked(old)
            ent = _Entry()
            ent.chain = list(chain)
            ent.tokens = len(chain) * pt
            self._table[sid] = ent
            self.prefix_matches += 1
            _, pageable, _, _ = self._layout
            partial = {}
            for j, i in enumerate(pageable):
                parts = [self._shared[key].slices[j] for key in chain]
                partial[i] = (np.concatenate(parts, axis=1)
                              if len(parts) > 1 else parts[0])
            return ent.tokens, partial

    def truncate(self, sid: str, to_tokens: int, others=None) -> bool:
        """Roll session ``sid`` back to its first ``to_tokens`` tokens —
        the speculative-decode reject path (serving/decode.py): positions
        fed past the accept point must leave the store. Drops the private
        partial tail past the accept point, decrements references on (and
        frees, at refcount zero) every sealed page wholly beyond it, and
        re-slices the boundary page's prefix into a fresh private tail
        when the accept point lands mid-page. COW-safe by construction:
        shared pages are immutable and only ever de-referenced here, so a
        page another session still holds survives untouched. ``others``
        (optional ``{leaf idx: replacement leaf}``) overwrites the
        non-pageable leaves — the pool treats them as opaque, so the
        caller owns their semantics (the decode engine moves its position
        carries back to the new frontier). Returns ``False`` when the
        session is absent, stored dense (opaque — the caller re-prefills
        from history instead), or ``to_tokens`` is not a shrink within
        the admission floor of one token."""
        to_tokens = int(to_tokens)
        with self._lock:
            ent = self._table.get(sid)
            if (ent is None or not ent.paged or to_tokens < 1
                    or to_tokens > ent.tokens):
                return False
            if to_tokens < ent.tokens:
                pt = self.page_tokens
                n_full = to_tokens // pt
                rem = to_tokens - n_full * pt
                tail = None
                if rem:
                    if len(ent.chain) > n_full:
                        boundary = self._shared[ent.chain[n_full]]
                        tail = [np.ascontiguousarray(s[:, :rem])
                                for s in boundary.slices]
                    elif ent.tail is not None:
                        tail = [np.ascontiguousarray(t[:, :rem])
                                for t in ent.tail]
                    else:       # nothing backs the boundary tokens
                        return False
                freed = 1 if ent.tail is not None else 0
                for key in ent.chain[n_full:]:
                    page = self._shared.get(key)
                    if page is None:
                        continue
                    page.ref -= 1
                    if page.ref <= 0:
                        del self._shared[key]
                        freed += 1
                if tail is not None:
                    freed -= 1   # the rebuilt tail still charges a page
                ent.chain = ent.chain[:n_full]
                ent.tail = tail
                ent.tokens = to_tokens
                self.truncations += 1
                self.truncated_pages += max(0, freed)
            if others:
                merged = dict(ent.others)
                merged.update({int(i): v for i, v in others.items()})
                ent.others = sorted(merged.items())
            return True

    def drop(self, sid: str) -> bool:
        """Voluntary release (session closed) — decrements this
        session's page references and frees whatever nobody else still
        holds, without counting as an eviction."""
        with self._lock:
            ent = self._table.pop(sid, None)
            if ent is None:
                return False
            self._release_locked(ent)
            return True
