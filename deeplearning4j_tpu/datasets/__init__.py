"""Host-side data pipeline (parity: deeplearning4j-nn/.../datasets/iterator
+ deeplearning4j-core dataset fetchers, SURVEY.md §2.5)."""

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    DataSetIterator,
    ListDataSetIterator,
    ArrayDataSetIterator,
    AsyncDataSetIterator,
    DevicePrefetchIterator,
    MultipleEpochsIterator,
    SamplingDataSetIterator,
    ReconstructionDataSetIterator,
)
from deeplearning4j_tpu.datasets.fetchers import (
    CifarDataSetIterator,
    CurvesDataSetIterator,
    IrisDataSetIterator,
    LFWDataSetIterator,
    MnistDataSetIterator,
)
