"""DataSetIterator infrastructure.

Parity: the reference's iterator API + async prefetch wrappers
(deeplearning4j-nn/.../datasets/iterator/: AsyncDataSetIterator — a
background prefetch thread with a queue of 2, auto-wrapped at
MultiLayerNetwork.java:951; MultipleEpochsIterator; adapters). The async
wrapper here overlaps host-side batch preparation with device compute — the
TPU equivalent of the reference's host I/O boundary.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet


def default_prefetch_depth() -> int:
    """Async prefetch queue depth (reference default 2; override with
    DL4J_TPU_PREFETCH_DEPTH for slow input pipelines)."""
    return max(1, int(os.environ.get("DL4J_TPU_PREFETCH_DEPTH", "2")))


class DataSetIterator:
    """Iterator protocol: iterate DataSets; ``reset()`` restarts."""

    def __iter__(self):
        raise NotImplementedError

    def reset(self):
        pass

    @property
    def batch_size(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Iterates a pre-built list of DataSet minibatches
    (ListDataSetIterator parity)."""

    def __init__(self, datasets: List[DataSet]):
        self._datasets = list(datasets)

    def __iter__(self):
        return iter(self._datasets)

    def __len__(self):
        return len(self._datasets)

    @property
    def batch_size(self):
        return self._datasets[0].num_examples if self._datasets else None


class ArrayDataSetIterator(DataSetIterator):
    """Slices (features, labels) arrays into minibatches, optionally
    reshuffling each epoch (the canonical in-memory path; parity with
    the reference's INDArrayDataSetIterator)."""

    def __init__(self, features, labels, batch_size: int, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = False):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels) if labels is not None else None
        self._batch = int(batch_size)
        self._shuffle = shuffle
        self._seed = int(seed)
        self._epoch = 0
        self._drop_last = drop_last

    def __iter__(self):
        n = self.features.shape[0]
        idx = np.arange(n)
        if self._shuffle:
            # fresh-but-deterministic order each epoch (seed + epoch, the
            # SamplingDataSetIterator scheme) so reset() makes replay after
            # a rollback bit-identical instead of consuming a shared
            # mutating RNG
            np.random.default_rng(self._seed + self._epoch).shuffle(idx)
        self._epoch += 1
        stop = (n // self._batch) * self._batch if self._drop_last else n
        for start in range(0, stop, self._batch):
            sel = idx[start:start + self._batch]
            yield DataSet(
                self.features[sel],
                None if self.labels is None else self.labels[sel],
            )

    def __len__(self):
        n = self.features.shape[0]
        return n // self._batch if self._drop_last else -(-n // self._batch)

    def reset(self):
        """Restart the stream: replay yields the epoch-0 order again (the
        DataSetIterator contract — previously a no-op while the RNG kept
        mutating, so post-rollback replays saw different orders)."""
        self._epoch = 0

    @property
    def batch_size(self):
        return self._batch


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch wrapper (AsyncDataSetIterator.java parity:
    blocking queue, default depth 2 — configurable per instance or via
    DL4J_TPU_PREFETCH_DEPTH).

    The consumer's ``finally`` drains the queue and JOINS the producer
    thread, so abandoning the generator early (break, exception, a chaos
    relaunch tearing down the fit loop) never leaks a prefetch thread
    blocked on a full queue."""

    _SENTINEL = object()
    THREAD_NAME = "dl4j-async-prefetch"

    def __init__(self, base: DataSetIterator,
                 queue_size: Optional[int] = None):
        self.base = base
        self.queue_size = (default_prefetch_depth() if queue_size is None
                           else max(1, int(queue_size)))

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self.queue_size)
        stop = threading.Event()
        error: list = []

        def put(item) -> bool:
            # Bounded put that gives up when the consumer abandoned the
            # generator (e.g. an exception in the training loop) — otherwise
            # the producer would block forever on a full queue.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for ds in self.base:
                    if not put(ds):
                        return
            except BaseException as e:  # surfaced on the consumer side
                error.append(e)
            finally:
                put(self._SENTINEL)

        t = threading.Thread(target=producer, daemon=True,
                             name=self.THREAD_NAME)
        t.start()
        try:
            while True:
                try:
                    # Timed get: a producer that dies without delivering
                    # its sentinel (killed interpreter thread, bug) must
                    # surface as an error, not hang the fit loop forever.
                    item = q.get(timeout=1.0)
                except queue.Empty:
                    if not t.is_alive() and q.empty():
                        if error:
                            raise error[0]
                        raise RuntimeError(
                            "async prefetch producer died without "
                            "delivering its end-of-data sentinel")
                    continue
                if item is self._SENTINEL:
                    if error:
                        raise error[0]
                    return
                yield item
        finally:
            stop.set()
            # Drain so a producer blocked on put() observes stop quickly,
            # then join: no thread may outlive its consumer.
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5.0)

    def reset(self):
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size


class DevicePrefetchIterator(DataSetIterator):
    """Double-buffered host→device transfer: issues ``jax.device_put`` for
    batch N+1 before yielding batch N, so the transfer rides under the
    device compute of step N. ``device_put`` is asynchronous (it returns
    a future-backed array immediately), so no extra thread is needed —
    layering this on :class:`AsyncDataSetIterator` gives host prep AND
    the PCIe/ICI copy both off the step's critical path. Yielded
    DataSets hold committed device arrays, making the inline
    ``jnp.asarray`` calls in ``fit_batch`` no-ops.

    ``sharding`` (optional): a ``jax.sharding.Sharding`` applied to every
    batch leaf — pass the net's data sharding when meshed so the arrays
    land already distributed."""

    def __init__(self, base: DataSetIterator, sharding=None):
        self.base = base
        self.sharding = sharding

    def _put(self, arr):
        if arr is None:
            return None
        import jax
        if self.sharding is not None:
            return jax.device_put(arr, self.sharding)
        return jax.device_put(arr)

    def _to_device(self, ds):
        if isinstance(ds, MultiDataSet):
            return MultiDataSet(
                [self._put(f) for f in ds.features],
                [self._put(l) for l in ds.labels],
                (None if ds.features_masks is None
                 else [self._put(m) for m in ds.features_masks]),
                (None if ds.labels_masks is None
                 else [self._put(m) for m in ds.labels_masks]),
            )
        return DataSet(
            self._put(ds.features), self._put(ds.labels),
            self._put(ds.features_mask), self._put(ds.labels_mask))

    def __iter__(self):
        it = iter(self.base)
        try:
            pending = self._to_device(next(it))
        except StopIteration:
            return
        for ds in it:
            nxt = self._to_device(ds)  # in flight while batch N computes
            yield pending
            pending = nxt
        yield pending

    def reset(self):
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size


class MultipleEpochsIterator(DataSetIterator):
    """Replays a base iterator for N epochs (MultipleEpochsIterator parity)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self.epochs = epochs
        self.base = base
        self._epoch = 0

    def __iter__(self):
        # no base.reset() between epochs: bases with seed+epoch shuffle
        # (ArrayDataSetIterator, SamplingDataSetIterator) advance their
        # epoch counter naturally, so each replayed epoch sees a distinct
        # deterministic order; reset() rewinds everything to epoch 0
        while self._epoch < self.epochs:
            self._epoch += 1
            yield from self.base

    def reset(self):
        self._epoch = 0
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size


class IteratorDataSetIterator(DataSetIterator):
    """Adapts a plain python iterable of DataSets (IteratorDataSetIterator
    parity)."""

    def __init__(self, iterable_factory):
        # factory so reset() can re-create the underlying iterable
        self._factory = iterable_factory

    def __iter__(self):
        return iter(self._factory())


class NativeDataSetIterator(DataSetIterator):
    """DataSetIterator over the C++ prefetch loader
    (datasets/native_io.py): shuffling, batch assembly and the depth-2
    prefetch ring run in native worker threads — the DataVec-tier
    substitution for the reference's off-JVM ingestion. Fallback is the
    caller's job (use ArrayDataSetIterator when native_io.available() is
    False)."""

    def __init__(self, features, labels, batch_size: int,
                 shuffle: bool = True, seed: int = 0, depth: int = 2,
                 drop_last: bool = True):
        from deeplearning4j_tpu.datasets.native_io import NativeBatchLoader
        self._loader = NativeBatchLoader(
            features, labels, batch_size, shuffle=shuffle, seed=seed,
            depth=depth, drop_last=drop_last)
        self._batch_size = self._loader.batch_size

    def __iter__(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        for x, y in self._loader:
            yield DataSet(x, y)

    def __len__(self):
        return self._loader.batches_per_epoch

    def reset(self):
        # restart the native stream (fresh epoch position + empty
        # prefetch ring) — the DataSetIterator contract; a mid-epoch
        # abandoned generator must not shift subsequent epochs
        self._loader.reset()

    @property
    def batch_size(self):
        return self._batch_size

    def close(self):
        self._loader.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SamplingDataSetIterator(DataSetIterator):
    """Batches sampled WITH replacement from a source DataSet
    (SamplingDataSetIterator.java parity: bootstrap-style batches for a
    fixed number of iterations per epoch)."""

    def __init__(self, dataset, batch_size: int, total_batches: int,
                 seed: int = 0):
        self._x = np.asarray(dataset.features)
        self._y = (None if dataset.labels is None
                   else np.asarray(dataset.labels))
        self._batch_size = int(batch_size)
        self.total_batches = int(total_batches)
        self._seed = seed
        self._epoch = 0

    def __iter__(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        # fresh-but-deterministic draws each epoch
        rng = np.random.default_rng(self._seed + self._epoch)
        self._epoch += 1
        n = len(self._x)
        for _ in range(self.total_batches):
            idx = rng.integers(0, n, self._batch_size)
            yield DataSet(self._x[idx],
                          None if self._y is None else self._y[idx])

    def reset(self):
        """Restart the stream: replay yields the epoch-0 draws again (the
        DataSetIterator contract)."""
        self._epoch = 0

    def __len__(self):
        return self.total_batches

    @property
    def batch_size(self):
        return self._batch_size


class ReconstructionDataSetIterator(DataSetIterator):
    """Wraps an iterator, replacing labels with the features —
    autoencoder reconstruction targets (ReconstructionDataSetIterator
    .java parity)."""

    def __init__(self, base: DataSetIterator):
        self.base = base

    def __iter__(self):
        # the features mask applies to both sides of reconstruction:
        # masked sequence autoencoders must not score padded steps
        for ds in self.base:
            yield DataSet(ds.features, ds.features,
                          ds.features_mask, ds.features_mask)

    def reset(self):
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size
