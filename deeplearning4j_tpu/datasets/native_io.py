"""ctypes binding for the native data-loading runtime (native/dataloader.cpp).

The reference's ingestion is native-grade code outside the Python/JVM hot
path (external DataVec + AsyncDataSetIterator's background thread —
SURVEY.md §2.5); here the IDX parsing, batch assembly, shuffling and
prefetch ring run in C++ worker threads behind a C API. The binding:

- ``available()``     -> bool (lib present or buildable)
- ``read_idx(path)``  -> np.ndarray (float32; u8 payloads normalized /255)
- ``NativeBatchLoader(x, y, batch_size, ...)`` -> iterator of
  (features, labels) with C++-side prefetch (depth-2 ring, the
  AsyncDataSetIterator default)

The library is built on demand with ``make -C native`` (g++ baked into
the image); every consumer falls back to the pure-Python path when the
toolchain or lib is unavailable, so nothing hard-depends on it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libdl4jtpu_io.so")


def _build() -> bool:
    try:
        proc = subprocess.run(["make", "-C", _NATIVE_DIR],
                              capture_output=True, timeout=120)
        return proc.returncode == 0 and os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.dl4j_idx_read.restype = ctypes.c_int
        lib.dl4j_idx_read.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float))]
        lib.dl4j_idx_free.argtypes = [ctypes.POINTER(ctypes.c_float)]
        lib.dl4j_loader_open.restype = ctypes.c_void_p
        lib.dl4j_loader_open.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32]
        lib.dl4j_loader_next.restype = ctypes.c_int64
        lib.dl4j_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float)]
        lib.dl4j_loader_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not None


def read_idx(path: str, normalize: bool = True) -> np.ndarray:
    """Parse an (uncompressed) IDX file natively. Raises on failure —
    callers fall back to the Python parser for .gz or when the lib is
    missing."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    dims = (ctypes.c_int64 * 8)()
    ndim = ctypes.c_int32()
    data = ctypes.POINTER(ctypes.c_float)()
    rc = lib.dl4j_idx_read(path.encode(), 1 if normalize else 0, dims,
                           ctypes.byref(ndim), ctypes.byref(data))
    if rc != 0:
        raise RuntimeError(f"dl4j_idx_read({path}) failed with code {rc}")
    shape = tuple(int(dims[i]) for i in range(ndim.value))
    n = int(np.prod(shape)) if shape else 0
    try:
        out = np.ctypeslib.as_array(data, shape=(n,)).copy().reshape(shape)
    finally:
        lib.dl4j_idx_free(data)
    return out


class NativeBatchLoader:
    """C++-prefetched minibatch iterator over in-memory arrays.

    Features flatten to [n, feat] for transport and are reshaped back per
    batch; labels must be one-hot [n, classes]. ``depth`` is the prefetch
    ring size (AsyncDataSetIterator's queue of 2 by default)."""

    def __init__(self, features, labels, batch_size: int,
                 shuffle: bool = True, seed: int = 0, depth: int = 2,
                 drop_last: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError("native IO library unavailable")
        self._lib = lib
        x = np.ascontiguousarray(np.asarray(features, np.float32))
        y = np.ascontiguousarray(np.asarray(labels, np.float32))
        if y.ndim != 2 or x.shape[0] != y.shape[0]:
            raise ValueError("labels must be one-hot [n, classes] aligned "
                             "with features")
        self._feat_shape = x.shape[1:]
        self.batch_size = min(batch_size, x.shape[0])
        self._feat = int(np.prod(self._feat_shape)) if self._feat_shape else 1
        self._classes = y.shape[1]
        self._n = x.shape[0]
        self.batches_per_epoch = (
            self._n // self.batch_size if drop_last
            else -(-self._n // self.batch_size))
        self._open_args = (x.reshape(self._n, -1), y, 1 if shuffle else 0,
                           seed, depth, 1 if drop_last else 0)
        self._handle = None
        self._reopen()
        self._xbuf = np.empty((self.batch_size, self._feat), np.float32)
        self._ybuf = np.empty((self.batch_size, self._classes), np.float32)

    def _reopen(self):
        """(Re)start the native stream — reset() semantics: a fresh
        epoch position and an empty prefetch ring."""
        if self._handle:
            self._lib.dl4j_loader_close(self._handle)
            self._handle = None
        xf, y, shuffle, seed, depth, drop_last = self._open_args
        self._handle = self._lib.dl4j_loader_open(
            xf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._n, self._feat, self._classes, self.batch_size,
            shuffle, seed, depth, drop_last)
        if not self._handle:
            raise RuntimeError("dl4j_loader_open failed")

    def reset(self):
        self._reopen()

    def next_batch(self):
        if self._handle is None:
            raise RuntimeError("native loader is closed")
        n = self._lib.dl4j_loader_next(
            self._handle,
            self._xbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._ybuf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if n < 0:
            raise RuntimeError("native loader stopped")
        x = self._xbuf[:n].reshape((n,) + self._feat_shape).copy()
        y = self._ybuf[:n].copy()
        return x, y

    def __iter__(self):
        for _ in range(self.batches_per_epoch):
            yield self.next_batch()

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.dl4j_loader_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
