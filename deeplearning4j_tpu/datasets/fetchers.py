"""Built-in dataset fetchers + iterators: MNIST, Iris, CIFAR-10.

Parity: deeplearning4j-core datasets/fetchers/MnistDataFetcher.java
(downloads + parses the IDX binary via datasets/mnist/MnistManager.java)
and datasets/iterator/impl/{Mnist,Iris,Cifar}DataSetIterator.java.

This environment has no network egress, so fetchers resolve data as:
1. an explicit ``path`` argument,
2. the standard cache dirs (~/.deeplearning4j_tpu/<name>, ~/.cache/<name>,
   $DL4J_TPU_DATA_DIR/<name>) holding the usual raw files
   (train-images-idx3-ubyte etc. for MNIST, cifar-10 binary batches),
3. a clearly-flagged deterministic SYNTHETIC fallback with the same shapes
   and class structure (template-per-class + noise), so training pipelines
   and benchmarks run anywhere. ``DataSetDescriptor.synthetic`` reports
   which path was taken.
"""

from __future__ import annotations

import gzip
import os
import struct
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ArrayDataSetIterator


@dataclass
class DataSetDescriptor:
    name: str
    synthetic: bool
    num_examples: int


def _search_dirs(name: str):
    dirs = []
    env = os.environ.get("DL4J_TPU_DATA_DIR")
    if env:
        dirs.append(os.path.join(env, name))
    home = os.path.expanduser("~")
    dirs.append(os.path.join(home, ".deeplearning4j_tpu", name))
    dirs.append(os.path.join(home, ".cache", name))
    return dirs


def _find_file(name: str, filenames):
    for d in _search_dirs(name):
        for fn in filenames:
            p = os.path.join(d, fn)
            if os.path.exists(p):
                return p
    return None


def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (MnistManager parity), gzip-transparent. Plain
    files go through the native C++ parser when the library is available
    (native/dataloader.cpp — the DataVec-tier runtime); .gz and
    lib-missing fall back to this Python path."""
    if not path.endswith(".gz"):
        try:
            from deeplearning4j_tpu.datasets import native_io
            if native_io.available():
                # native reader returns normalized f32; callers here
                # expect raw uint8 semantics, so request unnormalized
                return native_io.read_idx(path, normalize=False).astype(
                    np.uint8)
        except Exception:
            pass
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _synthetic_images(classes, h, w, c, n, seed, split_seed=0):
    """Per-class template + noise images in [0, 1] — separable, MNIST-like
    statistics; deterministic in ``seed``. Templates depend ONLY on
    ``seed`` so train/test splits (different ``split_seed``) share the
    same class structure — otherwise a model trained on the synthetic
    train split scores chance accuracy on the test split."""
    templates = np.random.default_rng(seed).random(
        (classes, h, w, c)).astype(np.float32)
    rng = np.random.default_rng(seed * 7919 + split_seed + 1)
    labels = rng.integers(0, classes, n)
    x = templates[labels] + 0.35 * rng.standard_normal(
        (n, h, w, c)).astype(np.float32)
    x = np.clip(x, 0.0, 1.0)
    y = np.eye(classes, dtype=np.float32)[labels]
    return x, y


class MnistDataFetcher:
    """28x28x1, 10 classes (MnistDataFetcher.java parity)."""

    TRAIN_IMAGES = ("train-images-idx3-ubyte", "train-images-idx3-ubyte.gz")
    TRAIN_LABELS = ("train-labels-idx1-ubyte", "train-labels-idx1-ubyte.gz")
    TEST_IMAGES = ("t10k-images-idx3-ubyte", "t10k-images-idx3-ubyte.gz")
    TEST_LABELS = ("t10k-labels-idx1-ubyte", "t10k-labels-idx1-ubyte.gz")

    def fetch(self, train: bool = True, num_examples: Optional[int] = None,
              path: Optional[str] = None, seed: int = 0
              ) -> Tuple[DataSet, DataSetDescriptor]:
        img_names = self.TRAIN_IMAGES if train else self.TEST_IMAGES
        lbl_names = self.TRAIN_LABELS if train else self.TEST_LABELS
        if path is not None:
            img_p = os.path.join(path, img_names[0])
            if not os.path.exists(img_p):
                img_p = os.path.join(path, img_names[1])
            lbl_p = os.path.join(path, lbl_names[0])
            if not os.path.exists(lbl_p):
                lbl_p = os.path.join(path, lbl_names[1])
        else:
            img_p = _find_file("mnist", img_names)
            lbl_p = _find_file("mnist", lbl_names)
        if img_p and lbl_p and os.path.exists(img_p) and os.path.exists(lbl_p):
            imgs = _read_idx(img_p).astype(np.float32) / 255.0
            labels = _read_idx(lbl_p)
            x = imgs[..., None]
            y = np.eye(10, dtype=np.float32)[labels]
            if num_examples:
                x, y = x[:num_examples], y[:num_examples]
            return DataSet(x, y), DataSetDescriptor("mnist", False, len(x))
        n = num_examples or (6000 if train else 1000)
        x, y = _synthetic_images(10, 28, 28, 1, n, seed,
                                 split_seed=0 if train else 1)
        return DataSet(x, y), DataSetDescriptor("mnist(synthetic)", True, n)


class CifarDataFetcher:
    """32x32x3, 10 classes (CifarDataSetIterator parity). Reads the binary
    batch format (data_batch_*.bin) when cached."""

    def fetch(self, train: bool = True, num_examples: Optional[int] = None,
              path: Optional[str] = None, seed: int = 0
              ) -> Tuple[DataSet, DataSetDescriptor]:
        names = ([f"data_batch_{i}.bin" for i in range(1, 6)]
                 if train else ["test_batch.bin"])
        dirs = [path] if path else _search_dirs("cifar-10-batches-bin")
        xs, ys = [], []
        for d in dirs:
            if d is None or not os.path.isdir(d):
                continue
            for fn in names:
                p = os.path.join(d, fn)
                if not os.path.exists(p):
                    continue
                raw = np.fromfile(p, dtype=np.uint8).reshape(-1, 3073)
                ys.append(raw[:, 0])
                xs.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                          .transpose(0, 2, 3, 1))
            if xs:
                break
        if xs:
            x = (np.concatenate(xs).astype(np.float32) / 255.0)
            y = np.eye(10, dtype=np.float32)[np.concatenate(ys)]
            if num_examples:
                x, y = x[:num_examples], y[:num_examples]
            return DataSet(x, y), DataSetDescriptor("cifar10", False, len(x))
        n = num_examples or (5000 if train else 1000)
        x, y = _synthetic_images(10, 32, 32, 3, n, seed,
                                 split_seed=0 if train else 1)
        return DataSet(x, y), DataSetDescriptor("cifar10(synthetic)", True, n)


class IrisDataFetcher:
    """150 examples, 4 features, 3 classes (IrisDataFetcher.java parity).
    Reads iris.data CSV when cached; synthetic 3-Gaussian fallback with
    iris-like class means otherwise."""

    def fetch(self, path: Optional[str] = None, seed: int = 0
              ) -> Tuple[DataSet, DataSetDescriptor]:
        p = path or _find_file("iris", ("iris.data", "iris.csv"))
        if p and os.path.exists(p):
            rows, labels = [], []
            label_map = {}
            with open(p) as f:
                for line in f:
                    parts = line.strip().split(",")
                    if len(parts) < 5:
                        continue
                    rows.append([float(v) for v in parts[:4]])
                    lbl = parts[4]
                    label_map.setdefault(lbl, len(label_map))
                    labels.append(label_map[lbl])
            x = np.asarray(rows, np.float32)
            y = np.eye(3, dtype=np.float32)[np.asarray(labels)]
            return DataSet(x, y), DataSetDescriptor("iris", False, len(x))
        rng = np.random.default_rng(seed)
        means = np.array([[5.0, 3.4, 1.5, 0.2],
                          [5.9, 2.8, 4.3, 1.3],
                          [6.6, 3.0, 5.6, 2.0]], np.float32)
        stds = np.array([[0.35, 0.38, 0.17, 0.10],
                         [0.51, 0.31, 0.47, 0.20],
                         [0.64, 0.32, 0.55, 0.27]], np.float32)
        labels = np.repeat(np.arange(3), 50)
        x = (means[labels]
             + stds[labels] * rng.standard_normal((150, 4))).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[labels]
        perm = rng.permutation(150)
        return (DataSet(x[perm], y[perm]),
                DataSetDescriptor("iris(synthetic)", True, 150))


# ---------------------------------------------------------------------------
# Iterators (datasets/iterator/impl parity)
# ---------------------------------------------------------------------------

class MnistDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 train: bool = True, flatten: bool = False,
                 shuffle: bool = True, seed: int = 123,
                 path: Optional[str] = None):
        ds, self.descriptor = MnistDataFetcher().fetch(
            train=train, num_examples=num_examples, path=path, seed=seed)
        x = ds.features
        if flatten:
            x = x.reshape(x.shape[0], -1)
        super().__init__(x, ds.labels, batch_size=batch_size,
                         shuffle=shuffle, seed=seed)


class CifarDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 train: bool = True, shuffle: bool = True, seed: int = 123,
                 path: Optional[str] = None):
        ds, self.descriptor = CifarDataFetcher().fetch(
            train=train, num_examples=num_examples, path=path, seed=seed)
        super().__init__(ds.features, ds.labels, batch_size=batch_size,
                         shuffle=shuffle, seed=seed)


class IrisDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int = 150, num_examples: int = 150,
                 seed: int = 123, path: Optional[str] = None):
        ds, self.descriptor = IrisDataFetcher().fetch(path=path, seed=seed)
        super().__init__(ds.features[:num_examples], ds.labels[:num_examples],
                         batch_size=batch_size, shuffle=False, seed=seed)

class LFWDataFetcher:
    """Labeled Faces in the Wild (LFWDataSetIterator.java /
    datasets/fetchers/LFWDataFetcher.java parity). Reads the standard
    extracted layout ``lfw/<person_name>/<person_name>_NNNN.jpg`` from a
    ``path`` or the cache dirs; persons with fewer than
    ``min_images_per_person`` images are dropped (the reference's
    subset-by-label behavior). No-egress synthetic fallback: per-identity
    face templates."""

    def fetch(self, num_examples: Optional[int] = None,
              image_size: Tuple[int, int] = (64, 64),
              min_images_per_person: int = 2, num_labels: int = 10,
              path: Optional[str] = None, seed: int = 0
              ) -> Tuple[DataSet, DataSetDescriptor]:
        h, w = image_size
        root = path
        if root is None:
            for d in _search_dirs("lfw"):
                if os.path.isdir(d):
                    root = d
                    break
        if root and os.path.isdir(root):
            people = []
            for person in sorted(os.listdir(root)):
                pdir = os.path.join(root, person)
                if not os.path.isdir(pdir):
                    continue
                imgs = sorted(fn for fn in os.listdir(pdir)
                              if fn.lower().endswith((".jpg", ".jpeg",
                                                      ".png")))
                if len(imgs) >= min_images_per_person:
                    people.append((person, [os.path.join(pdir, fn)
                                            for fn in imgs]))
            # most-photographed first, capped at num_labels (the
            # reference's useSubset semantics)
            people.sort(key=lambda p: (-len(p[1]), p[0]))
            people = people[:num_labels]
            if people:
                from PIL import Image
                xs, ys = [], []
                for label, (_, paths) in enumerate(people):
                    for p in paths:
                        img = Image.open(p).convert("RGB").resize((w, h))
                        xs.append(np.asarray(img, np.float32) / 255.0)
                        ys.append(label)
                x = np.stack(xs)
                y = np.eye(len(people), dtype=np.float32)[np.asarray(ys)]
                if num_examples:
                    x, y = x[:num_examples], y[:num_examples]
                return (DataSet(x, y),
                        DataSetDescriptor("lfw", False, len(x)))
        n = num_examples or 400
        x, y = _synthetic_images(num_labels, h, w, 3, n, seed)
        return DataSet(x, y), DataSetDescriptor("lfw(synthetic)", True, n)


def _render_curve(rng, size: int = 28) -> np.ndarray:
    """Rasterize one random cubic Bezier stroke into a [size, size] float
    image — the 'curves' dataset's generative family (the reference's
    CurvesDataFetcher serves precomputed images of exactly such random
    curves for the deep-autoencoder examples)."""
    pts = rng.uniform(0.1, 0.9, (4, 2))
    t = np.linspace(0.0, 1.0, 160)[:, None]
    b = ((1 - t) ** 3 * pts[0] + 3 * (1 - t) ** 2 * t * pts[1]
         + 3 * (1 - t) * t ** 2 * pts[2] + t ** 3 * pts[3])
    img = np.zeros((size, size), np.float32)
    ij = np.clip((b * size).astype(int), 0, size - 1)
    img[ij[:, 1], ij[:, 0]] = 1.0
    # 1-pixel blur to soften the stroke (matches the dataset's antialiased
    # look and gives the autoencoder a non-binary target)
    blurred = img.copy()
    for dy, dx in ((0, 1), (0, -1), (1, 0), (-1, 0)):
        blurred += 0.35 * np.roll(np.roll(img, dy, 0), dx, 1)
    return np.clip(blurred, 0.0, 1.0)


class CurvesDataFetcher:
    """The 'curves' autoencoder dataset: 28x28 images of random cubic
    curves (datasets/fetchers/CurvesDataFetcher.java parity — the
    reference downloads precomputed curve images; here they load from a
    cached ``curves.npz`` (key ``x``) or are generated deterministically,
    which is faithful to the dataset's own synthetic construction).
    Features == labels (autoencoder reconstruction target)."""

    def fetch(self, num_examples: Optional[int] = None,
              path: Optional[str] = None, seed: int = 0
              ) -> Tuple[DataSet, DataSetDescriptor]:
        p = path or _find_file("curves", ("curves.npz",))
        if p and os.path.exists(p):
            x = np.load(p)["x"].astype(np.float32)
            if num_examples:
                x = x[:num_examples]
            x = x.reshape(len(x), -1)
            return (DataSet(x, x.copy()),
                    DataSetDescriptor("curves", False, len(x)))
        n = num_examples or 2000
        rng = np.random.default_rng(seed)
        x = np.stack([_render_curve(rng) for _ in range(n)])
        x = x.reshape(n, -1)
        return (DataSet(x, x.copy()),
                DataSetDescriptor("curves(synthetic)", True, n))


class LFWDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 image_size: Tuple[int, int] = (64, 64),
                 min_images_per_person: int = 2, num_labels: int = 10,
                 shuffle: bool = True, seed: int = 123,
                 path: Optional[str] = None):
        ds, self.descriptor = LFWDataFetcher().fetch(
            num_examples=num_examples, image_size=image_size,
            min_images_per_person=min_images_per_person,
            num_labels=num_labels, path=path, seed=seed)
        super().__init__(ds.features, ds.labels, batch_size=batch_size,
                         shuffle=shuffle, seed=seed)


class CurvesDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 seed: int = 123, path: Optional[str] = None):
        ds, self.descriptor = CurvesDataFetcher().fetch(
            num_examples=num_examples, path=path, seed=seed)
        super().__init__(ds.features, ds.labels, batch_size=batch_size,
                         shuffle=False, seed=seed)
