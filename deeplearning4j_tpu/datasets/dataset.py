"""DataSet: one (features, labels) minibatch, with optional masks.

Parity: ND4J's DataSet as consumed by the reference
(`org.nd4j.linalg.dataset.DataSet`, used via DataSetIterator 23x in
deeplearning4j-nn). Masks follow the reference's time-series semantics:
features_mask/labels_mask are [batch, time] 0/1 arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np


@dataclass
class DataSet:
    features: np.ndarray
    labels: Optional[np.ndarray] = None
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    @property
    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, num_train: int):
        a = DataSet(
            self.features[:num_train],
            None if self.labels is None else self.labels[:num_train],
            None if self.features_mask is None else self.features_mask[:num_train],
            None if self.labels_mask is None else self.labels_mask[:num_train],
        )
        b = DataSet(
            self.features[num_train:],
            None if self.labels is None else self.labels[num_train:],
            None if self.features_mask is None else self.features_mask[num_train:],
            None if self.labels_mask is None else self.labels_mask[num_train:],
        )
        return a, b

    def shuffle(self, seed: int = 0):
        perm = np.random.default_rng(seed).permutation(self.num_examples)
        return DataSet(
            self.features[perm],
            None if self.labels is None else self.labels[perm],
            None if self.features_mask is None else self.features_mask[perm],
            None if self.labels_mask is None else self.labels_mask[perm],
        )

    @staticmethod
    def merge(datasets):
        def cat(xs):
            if any(x is None for x in xs):
                return None
            return np.concatenate(xs, axis=0)
        return DataSet(
            cat([d.features for d in datasets]),
            cat([d.labels for d in datasets]),
            cat([d.features_mask for d in datasets]),
            cat([d.labels_mask for d in datasets]),
        )


@dataclass
class MultiDataSet:
    """Multi-input/multi-output minibatch (org.nd4j MultiDataSet parity, as
    consumed by ComputationGraph — nn/graph/ComputationGraph.java fit paths).
    All fields are tuples/lists of arrays (or None masks)."""

    features: list
    labels: list
    features_masks: Optional[list] = None
    labels_masks: Optional[list] = None

    def __post_init__(self):
        self.features = list(self.features)
        self.labels = list(self.labels)
        if self.features_masks is None:
            self.features_masks = [None] * len(self.features)
        if self.labels_masks is None:
            self.labels_masks = [None] * len(self.labels)

    @property
    def num_examples(self) -> int:
        return int(self.features[0].shape[0])

    @staticmethod
    def from_dataset(ds: DataSet) -> "MultiDataSet":
        return MultiDataSet([ds.features], [ds.labels],
                            [ds.features_mask], [ds.labels_mask])
