"""Record readers — the DataVec bridge.

Parity: deeplearning4j-core datasets/datavec/{RecordReaderDataSetIterator,
SequenceRecordReaderDataSetIterator}.java over DataVec's CSV readers. The
reference delegates parsing to the external DataVec project; here a compact
CSV/array record reader feeds the same iterator API.
"""

from __future__ import annotations

import csv
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator


class CSVRecordReader:
    """Reads numeric CSV rows (DataVec CSVRecordReader parity)."""

    def __init__(self, path: str, skip_lines: int = 0, delimiter: str = ","):
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def iter_records(self):
        """Stream rows one at a time without materializing the file —
        the datapipe CSVSource path (resume state stays one cursor)."""
        with open(self.path, newline="") as f:
            reader = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield [float(v) for v in row]

    def records(self) -> List[List[float]]:
        return list(self.iter_records())


class CollectionRecordReader:
    """In-memory records (CollectionRecordReader parity)."""

    def __init__(self, records: Sequence[Sequence[float]]):
        self._records = [list(r) for r in records]

    def records(self):
        return self._records


class RecordReaderDataSetIterator(DataSetIterator):
    """records -> (features, one-hot labels) minibatches
    (RecordReaderDataSetIterator.java parity): ``label_index`` names the
    label column; ``num_classes`` one-hot encodes it; regression mode keeps
    the raw value(s)."""

    def __init__(self, record_reader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        rows = np.asarray(record_reader.records(), dtype=np.float32)
        if label_index is None:
            self.features, self.labels = rows, None
        elif regression:
            to = label_index_to if label_index_to is not None else label_index
            cols = list(range(label_index, to + 1))
            self.labels = rows[:, cols]
            keep = [i for i in range(rows.shape[1]) if i not in cols]
            self.features = rows[:, keep]
        else:
            labels_raw = rows[:, label_index].astype(np.int64)
            if num_classes is None:
                num_classes = int(labels_raw.max()) + 1
            self.labels = np.eye(num_classes, dtype=np.float32)[labels_raw]
            keep = [i for i in range(rows.shape[1]) if i != label_index]
            self.features = rows[:, keep]
        self._batch = batch_size

    def __iter__(self):
        n = self.features.shape[0]
        for s in range(0, n, self._batch):
            yield DataSet(
                self.features[s:s + self._batch],
                None if self.labels is None else self.labels[s:s + self._batch])

    def reset(self):
        pass


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Per-sequence records -> padded+masked [b, t, f] batches
    (SequenceRecordReaderDataSetIterator.java parity with ALIGN_END=False:
    sequences pad at the tail and carry masks)."""

    def __init__(self, sequences, labels, batch_size: int,
                 num_classes: Optional[int] = None):
        """sequences: list of [t_i, f] arrays; labels: list of int class ids
        (one per sequence) or [t_i, out] per-step arrays."""
        self.sequences = [np.asarray(s, np.float32) for s in sequences]
        self.labels = labels
        self.num_classes = num_classes
        self._batch = batch_size

    def __iter__(self):
        n = len(self.sequences)
        for s in range(0, n, self._batch):
            seqs = self.sequences[s:s + self._batch]
            labs = self.labels[s:s + self._batch]
            t_max = max(x.shape[0] for x in seqs)
            f = seqs[0].shape[1]
            b = len(seqs)
            x = np.zeros((b, t_max, f), np.float32)
            fmask = np.zeros((b, t_max), np.float32)
            for i, sq in enumerate(seqs):
                x[i, :sq.shape[0]] = sq
                fmask[i, :sq.shape[0]] = 1.0
            if np.isscalar(labs[0]) or np.ndim(labs[0]) == 0:
                nc = self.num_classes or int(max(labs)) + 1
                y = np.eye(nc, dtype=np.float32)[np.asarray(labs, np.int64)]
                lmask = None
            else:
                out = np.asarray(labs[0]).shape[-1]
                y = np.zeros((b, t_max, out), np.float32)
                lmask = np.zeros((b, t_max), np.float32)
                for i, l in enumerate(labs):
                    l = np.asarray(l, np.float32)
                    y[i, :l.shape[0]] = l
                    lmask[i, :l.shape[0]] = 1.0
            yield DataSet(x, y, fmask, lmask)

    def reset(self):
        pass
