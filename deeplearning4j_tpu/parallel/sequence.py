"""Sequence (context) parallelism for recurrent models.

The reference has NO sequence-length mechanism beyond truncated BPTT and
masking (SURVEY.md §5.7 — 2017, pre-attention). This framework treats the
sequence dimension as a first-class shardable axis, the way ring attention
treats context for transformers: the TIME axis is sharded over a mesh
axis, and the recurrent carry travels the device ring with
``jax.lax.ppermute`` — a WAVEFRONT schedule.

What this buys (and what it does not):
- Activation/residual memory for the sequence is split D ways: sequences
  D× longer than one device's HBM can be trained (the long-context
  enabler). The input projection x @ Wx (the FLOPs-heavy part at large
  f) and every per-timestep layer around the LSTM run fully parallel on
  their local time chunks.
- The recurrent chain itself is inherently sequential, so the cell scans
  execute one device at a time (each under ``lax.cond``, so off-turn
  devices idle rather than recompute); wall-clock for the scan matches a
  single device. This is the correct physics for an RNN — parallelism in
  TIME is what attention buys and the reference predates.

Built on ``shard_map`` so XLA emits the ICI ppermute collectives; works
on any mesh axis (virtual CPU devices in tests, ICI ring on hardware).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_sequence(mesh: Mesh, seq_axis: str, x, time_dim: int = 1):
    """Place [b, T, ...] with the TIME axis sharded over ``seq_axis``."""
    spec = [None] * np.ndim(x)
    spec[time_dim] = seq_axis
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(*spec)))


def sequence_parallel_lstm(mesh: Mesh, seq_axis: str, params, x, h0, c0,
                           *, mask=None, gate_act: str = "sigmoid",
                           cell_act: str = "tanh"):
    """Graves-LSTM forward over a time-sharded sequence.

    ``params``: the GravesLSTM param dict {Wx, Wh, b, p} (replicated);
    ``x``: [b, T, f] with T sharded over ``seq_axis`` (see
    ``shard_sequence``); ``h0``/``c0``: [b, n] replicated initial carry;
    ``mask``: optional [b, T] per-timestep mask, time-sharded like ``x``
    — masked steps carry (h, c) through unchanged and emit zero output
    (the reference-parity masking semantics, MaskedReductionUtil /
    GravesLSTM masking), including across chunk boundaries: a chunk whose
    steps are all masked hands its carry down the ring untouched.
    Returns (y [b, T, n] time-sharded, hT, cT replicated).

    Schedule: D wavefront steps; at step s the device holding chunk s
    runs its local cell scan (through the ``lstm_sequence`` registry op —
    the Pallas kernel on TPU), then the carry ppermutes one hop along the
    ring.
    """
    from deeplearning4j_tpu.ops import registry as ops

    n = params["Wh"].shape[0]
    d = mesh.shape[seq_axis]
    if x.shape[1] % d != 0:
        raise ValueError(
            f"sequence length {x.shape[1]} is not divisible by the "
            f"'{seq_axis}' mesh axis ({d} devices) — pad the time axis")
    lstm_seq = ops.get("lstm_sequence")
    has_mask = mask is not None

    def local(params, x_local, h0, c0, m_local):
        idx = jax.lax.axis_index(seq_axis)
        cd = x_local.dtype
        p_cd = {k: v.astype(cd) for k, v in params.items()}
        # input projection: fully parallel over the local time chunk
        xz = jnp.einsum("btf,fg->btg", x_local, p_cd["Wx"]) + p_cd["b"]
        xz_t = jnp.moveaxis(xz, 1, 0)                     # [t_local, b, 4n]
        m_t = (jnp.moveaxis(m_local.astype(cd), 1, 0)     # [t_local, b]
               if has_mask else None)

        def turn(carry):
            h, c = carry
            ys, hT, cT = lstm_seq(xz_t, h, c, p_cd["Wh"], p_cd["p"], m_t,
                                  gate_act=gate_act, cell_act=cell_act)
            return ys, (hT, cT)

        def wait(carry):
            return jnp.zeros(xz_t.shape[:2] + (n,), cd), carry

        y0 = jnp.zeros(xz_t.shape[:2] + (n,), cd)

        def body(carry, s):
            ring, y_acc, fin = carry
            ys, new_carry = jax.lax.cond(idx == s, turn, wait, ring)
            # accumulate my own turn's output in a single [t_local, b, n]
            # buffer — stacking all d steps would materialize the FULL
            # sequence's output on every device and defeat the memory
            # scaling this module exists for
            y_acc = y_acc + ys
            # the final (hT, cT) is whatever the LAST wavefront step's
            # owner computed
            fin = jax.lax.cond(s == d - 1, lambda _: new_carry,
                               lambda f: f, fin)
            # hand the carry one hop down the ring
            passed = jax.lax.ppermute(
                new_carry, seq_axis,
                perm=[(i, (i + 1) % d) for i in range(d)])
            return (passed, y_acc, fin), None

        carry0 = (h0.astype(cd), c0.astype(cd))
        (_, y_local_t, (h_fin, c_fin)), _ = jax.lax.scan(
            body, (carry0, y0, carry0), jnp.arange(d))
        y_local = jnp.moveaxis(y_local_t, 0, 1)  # [b, t_local, n]
        # the true final carry lives on device d-1; indicator-mask + psum
        # broadcasts it (a one-to-all "send" is not a valid ppermute
        # permutation)
        is_last = (idx == d - 1).astype(cd)
        hT = jax.lax.psum(h_fin * is_last, seq_axis)
        cT = jax.lax.psum(c_fin * is_last, seq_axis)
        return y_local, hT, cT

    if not has_mask:
        # shard_map needs a concrete operand per spec — feed a scalar
        # placeholder that the traced body never touches
        mask = jnp.zeros((), x.dtype)
    from deeplearning4j_tpu.parallel.mesh import compat_shard_map
    fn = compat_shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(None, seq_axis, None), P(), P(),
                  P(None, seq_axis) if has_mask else P()),
        out_specs=(P(None, seq_axis, None), P(), P()))
    return fn(params, x, h0, c0, mask)
