"""Parallel training (parity: deeplearning4j-scaleout — ParallelWrapper,
Spark ParameterAveragingTrainingMaster, Aeron parameter server; SURVEY.md
§2.8/§5.8).

TPU-native design: all data movement is expressed as shardings over a
``jax.sharding.Mesh``; XLA emits the collectives (all-reduce over ICI within
a slice, DCN across slices). There is no parameter server and no driver in
the training path — gradient averaging is a ``psum`` fused into the train
step. The reference's ParameterAveraging *semantics* (average params every k
local steps) is provided as ``ParameterAveragingTrainer`` for
single-machine-equivalence tests.
"""

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.data_parallel import (
    apply_mesh,
    shard_step,
    shard_batch,
    ParallelWrapper,
)
