"""Pipeline (stage) parallelism over a ``pipe`` mesh axis.

The reference's scaleout tier replicates the whole model on every worker
(SURVEY.md §2.8); a pipeline axis is the TPU-native way to train models
DEEPER than one device's HBM: each device holds ONE stage's parameters
(the stage dim of a stacked param tree is sharded over ``pipe``), and
microbatches stream through the device ring in a GPipe wavefront —
``lax.ppermute`` hands each stage's activation to the next stage every
tick, so after the S-1-tick fill the ring computes S microbatches
concurrently. Built on ``shard_map`` like parallel/sequence.py, and
fully differentiable: reverse-mode AD through the scan + ppermute yields
the backward pipeline (cotangents ride the ring in reverse), so one
``jax.grad`` of a loss on the pipeline output trains all stages.

Scope: homogeneous repeated stages (stacked params with a leading stage
dim — the transformer-block/repeated-MLP regime where pipeline
parallelism is used in practice). Heterogeneous stems/heads run outside
the pipelined trunk, dp/tp-style.

Memory: each device stores its own stage's params + per-tick
activations; the bubble is the standard GPipe (S-1)/(M+S-1) fraction —
use n_micro >= 4*stages to amortize.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(per_stage_params):
    """[{param: array}, ...] (one per stage, identical structure) ->
    stacked pytree with a leading stage dim (shard THIS over 'pipe')."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def shard_stages(mesh: Mesh, pipe_axis: str, stacked_params):
    """Place stacked stage params with the stage dim over ``pipe_axis``
    (each device holds exactly its stage's slice)."""
    def put(leaf):
        spec = P(pipe_axis, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, stacked_params)


def split_microbatches(x, n_micro: int):
    """[B, ...] -> [n_micro, B/n_micro, ...] (the GPipe microbatch dim)."""
    b = x.shape[0]
    if b % n_micro != 0:
        raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
    return x.reshape(n_micro, b // n_micro, *x.shape[1:])


def pipeline_forward(mesh: Mesh, pipe_axis: str, stage_params, x_micro,
                     stage_fn):
    """GPipe forward: ``stage_params`` stacked with leading stage dim
    sharded over ``pipe_axis`` (see ``shard_stages``); ``x_micro``
    ``[M, mb, F]`` microbatched input (replicated); ``stage_fn(params,
    x) -> y`` one stage's computation with matching in/out feature shape.
    Returns ``[M, mb, F]`` outputs (replicated). Differentiable.
    """
    n_stages = mesh.shape[pipe_axis]
    stage_dims = {leaf.shape[0]
                  for leaf in jax.tree_util.tree_leaves(stage_params)}
    if stage_dims != {n_stages}:
        # a multiple would shard 2+ stages per device and per_device
        # would silently apply only the first — hard error instead
        raise ValueError(
            f"stacked stage dim(s) {sorted(stage_dims)} must equal the "
            f"'{pipe_axis}' mesh axis size ({n_stages}): one stage per "
            f"device")

    def per_device(p_local, x_all):
        s = jax.lax.axis_index(pipe_axis)
        p = jax.tree_util.tree_map(lambda a: a[0], p_local)
        m = x_all.shape[0]
        ticks = m + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        recv0 = jnp.zeros_like(x_all[0])
        out0 = jnp.zeros_like(x_all)

        def tick(carry, t):
            recv, outbuf = carry
            # stage 0 injects microbatch t (clamped; invalid ticks
            # compute garbage that is never collected)
            inj = x_all[jnp.clip(t, 0, m - 1)]
            inp = jnp.where(s == 0, inj, recv)
            y = stage_fn(p, inp)
            out_idx = t - (n_stages - 1)
            valid = (s == n_stages - 1) & (out_idx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outbuf, y, jnp.clip(out_idx, 0, m - 1), 0)
            outbuf = jnp.where(valid, updated, outbuf)
            send = jax.lax.ppermute(y, pipe_axis, perm)
            return (send, outbuf), None

        (_, outbuf), _ = jax.lax.scan(tick, (recv0, out0),
                                      jnp.arange(ticks))
        # only the last stage wrote real outputs (others kept zeros):
        # psum broadcasts the result to every device
        return jax.lax.psum(outbuf, pipe_axis)

    spec_p = jax.tree_util.tree_map(
        lambda a: P(pipe_axis, *([None] * (a.ndim - 1))), stage_params)
    from deeplearning4j_tpu.parallel.mesh import compat_shard_map
    return compat_shard_map(
        per_device, mesh=mesh, in_specs=(spec_p, P()),
        out_specs=P())(stage_params, x_micro)


def pipeline_train_step(mesh: Mesh, pipe_axis: str, stage_fn, loss_fn,
                        lr: float = 0.1):
    """A jittable SGD step over a pipelined trunk: ``loss_fn(y, labels)``
    is applied to the pipeline output (mean over microbatches folded in
    by the caller's loss). Returns ``step(stage_params, x_micro,
    labels_micro) -> (new_params, loss)``. The backward pipeline falls
    out of reverse-mode AD through the forward schedule."""

    def objective(params, x_micro, labels_micro):
        y = pipeline_forward(mesh, pipe_axis, params, x_micro, stage_fn)
        return loss_fn(y, labels_micro)

    def step(params, x_micro, labels_micro):
        loss, grads = jax.value_and_grad(objective)(params, x_micro,
                                                    labels_micro)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g.astype(p.dtype), params, grads)
        return new_params, loss

    return step
