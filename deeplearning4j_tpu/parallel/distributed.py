"""Multi-host (multi-process) distributed training — the DP-2 tier.

Replaces the reference's Spark parameter-averaging scaleout
(dl4j-spark/.../paramavg/ParameterAveragingTrainingMaster.java:358
executeTraining: broadcast params -> workers fit local minibatches ->
RDD.aggregate sums -> divide -> rebroadcast, §3.4) with the TPU-native
single-controller model (SURVEY.md §5.8): every process calls
``initialize()`` (jax.distributed), the device mesh spans ALL processes'
devices, and the SAME jitted train step runs SPMD everywhere — XLA lowers
the gradient all-reduce onto ICI within a host and DCN across hosts. There
is no driver, no broadcast step, and no parameter copy per round: the
"averaging" is the gradient psum inside the compiled step, every step.

Data feeding: each process supplies its LOCAL slice of the global batch;
``parallel.data_parallel.shard_batch`` assembles the process-local arrays
into one global sharded Array
(jax.make_array_from_process_local_data — the RDD-partition analogue) —
the meshed networks route through it automatically.

The exact-equivalence contract (TestCompareParameterAveragingSparkVs
SingleMachine.java analogue) is pinned by
tests/test_multihost.py: 2 spawned processes x 4 virtual CPU devices
training on disjoint batch halves must produce params bit-identical to
each other AND matching a single-process run on the full batch.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import warnings

import jax
import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

#: default for DL4J_TPU_COLLECTIVE_TIMEOUT_S — how long a consensus
#: round waits for every peer before declaring one lost
DEFAULT_COLLECTIVE_TIMEOUT_S = 60.0


class CollectiveTimeoutError(RuntimeError):
    """A cross-process consensus call did not complete within the
    collective timeout (``DL4J_TPU_COLLECTIVE_TIMEOUT_S``)."""


class PeerLostError(CollectiveTimeoutError):
    """A consensus round timed out waiting for specific peer processes
    — they are presumed dead (crashed, SIGKILLed, or hung past the
    collective timeout). The supervisor turns this into a
    ``peer_lost`` exit; the fleet launcher relaunches on it."""

    def __init__(self, msg: str, *, lost_ranks=(), elapsed_s=None,
                 round_name: str = ""):
        super().__init__(msg)
        self.lost_ranks = list(lost_ranks)
        self.elapsed_s = elapsed_s
        self.round_name = round_name


def collective_timeout_s() -> float:
    """The consensus/barrier deadline: env ``DL4J_TPU_COLLECTIVE_TIMEOUT_S``
    (seconds), else :data:`DEFAULT_COLLECTIVE_TIMEOUT_S`."""
    raw = os.environ.get("DL4J_TPU_COLLECTIVE_TIMEOUT_S")
    if raw:
        try:
            return max(0.1, float(raw))
        except ValueError:
            logger.warning("ignoring malformed "
                           "DL4J_TPU_COLLECTIVE_TIMEOUT_S=%r", raw)
    return DEFAULT_COLLECTIVE_TIMEOUT_S


def _client():
    """The jax.distributed coordination-service client (the KV store /
    barrier endpoint every process holds once ``initialize`` ran), or
    None outside a multi-process runtime."""
    try:
        from jax._src import distributed as _jdist
        return _jdist.global_state.client
    except Exception:
        return None


def _runtime_up() -> bool:
    """True once this process is attached to a jax.distributed runtime
    (client on workers; coordinator-owning process 0 also has one)."""
    try:
        from jax._src import distributed as _jdist
        state = _jdist.global_state
        return state.client is not None or state.service is not None
    except Exception:
        return False


def consensus_available() -> bool:
    """True when the consensus layer can actually allgather: more than
    one process AND a live coordination-service client to do it over."""
    return jax.process_count() > 1 and _client() is not None


# Round counters: every process makes the SAME sequence of consensus
# calls per name (SPMD discipline — the supervisor's recovery decisions
# are schedule-aligned), so a per-process monotonic counter yields the
# same round number everywhere without any extra coordination.
_round_lock = threading.Lock()
_rounds: dict = {}


def _next_round(name: str) -> int:
    with _round_lock:
        n = _rounds.get(name, 0)
        _rounds[name] = n + 1
        return n


def _reset_rounds() -> None:
    """Tests only: forget round counters (a fresh fake cluster)."""
    with _round_lock:
        _rounds.clear()


def _key_prefix() -> str:
    # incarnation-scoped so a relaunched fleet reusing one coordinator
    # never collides with a previous launch's keys
    return os.environ.get("DL4J_TPU_INCARNATION", "0")


def agree_decision(code: int, *, name: str = "decision",
                   timeout_s: float | None = None) -> list[int]:
    """Allgather one tiny integer recovery code across every process.

    The consensus primitive the multi-process supervisor routes every
    recovery decision through: each process publishes ``code`` to the
    coordination-service KV store and blocking-reads every peer's,
    returning ``[code_0, ..., code_{n-1}]`` (identical on every
    process). Unlike an XLA collective (``process_allgather``), a dead
    peer cannot hang this forever: a read that exceeds the collective
    timeout raises :class:`PeerLostError` naming the missing rank(s).

    Single-process: returns ``[code]`` without touching any runtime."""
    code = int(code)
    count = jax.process_count()
    if count == 1:
        return [code]
    client = _client()
    if client is None:
        raise RuntimeError(
            "agree_decision needs the jax.distributed coordination "
            "service — call parallel.distributed.initialize() first")
    if timeout_s is None:
        timeout_s = collective_timeout_s()
    rank = jax.process_index()
    rnd = _next_round(name)
    base = f"dl4j/agree/{_key_prefix()}/{name}/{rnd}"
    client.key_value_set(f"{base}/{rank}", str(code))
    codes: list = []
    lost: list = []
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    for peer in range(count):
        remaining_ms = max(100, int((deadline - time.monotonic()) * 1000))
        try:
            v = client.blocking_key_value_get(f"{base}/{peer}",
                                              remaining_ms)
        except Exception:
            # jaxlib surfaces the KV deadline as XlaRuntimeError
            # DEADLINE_EXCEEDED; any failure to hear from the peer
            # within budget is treated the same — presumed lost
            lost.append(peer)
            codes.append(None)
        else:
            codes.append(int(v))
    if lost:
        elapsed = time.monotonic() - t0
        raise PeerLostError(
            f"no decision from process(es) {lost} for consensus round "
            f"{name!r}#{rnd} within {timeout_s:.1f}s (waited "
            f"{elapsed:.1f}s) — peer(s) presumed lost",
            lost_ranks=lost, elapsed_s=elapsed, round_name=name)
    if rnd >= 2:
        # GC our own key from two rounds back: every peer reaching round
        # rnd has finished reading round rnd-1, hence rnd-2 long before
        try:
            client.key_value_delete(f"dl4j/agree/{_key_prefix()}/{name}/"
                                    f"{rnd - 2}/{rank}")
        except Exception:
            pass
    return codes


def any_process(flag: bool, *, name: str = "flag",
                timeout_s: float | None = None) -> bool:
    """True iff ``flag`` is truthy on ANY process (the broadcast-OR the
    supervisor uses for preemption: one SIGTERM anywhere stops the whole
    fleet at the same step boundary)."""
    return any(agree_decision(1 if flag else 0, name=name,
                              timeout_s=timeout_s))


def barrier(name: str, *, timeout_s: float | None = None) -> None:
    """Cross-process barrier with a deadline. Uses the coordination
    service's native barrier (timeout-capable — a dead peer raises
    :class:`PeerLostError` instead of hanging forever); falls back to
    ``sync_global_devices`` (an XLA collective, no timeout) when no
    client exists. No-op single-process."""
    if jax.process_count() == 1:
        return
    client = _client()
    if client is None:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
        return
    if timeout_s is None:
        timeout_s = collective_timeout_s()
    rnd = _next_round(f"barrier/{name}")
    barrier_id = f"dl4j/{_key_prefix()}/barrier/{name}/{rnd}"
    t0 = time.monotonic()
    try:
        client.wait_at_barrier(barrier_id, int(timeout_s * 1000))
    except Exception as e:
        elapsed = time.monotonic() - t0
        raise PeerLostError(
            f"barrier {name!r}#{rnd} did not complete within "
            f"{timeout_s:.1f}s ({e}) — peer presumed lost",
            elapsed_s=elapsed, round_name=name) from e


_ALREADY_UP_WARNED = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None):
    """Bring up the multi-process runtime (jax.distributed.initialize).

    Arguments default to the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) so launchers can stay declarative;
    on TPU pods with no args at all, jax autodetects the topology.

    Idempotent: when the runtime is already up (a second call —
    ``jax.distributed.initialize`` itself would raise), warns once and
    returns :func:`process_info` for the existing cluster."""
    global _ALREADY_UP_WARNED
    if _runtime_up():
        if not _ALREADY_UP_WARNED:
            _ALREADY_UP_WARNED = True
            warnings.warn(
                "parallel.distributed.initialize(): the jax.distributed "
                "runtime is already up; returning the existing cluster's "
                "process_info()", RuntimeWarning, stacklevel=2)
        return process_info()
    # The CPU backend refuses cross-process computations unless an
    # explicit collectives implementation is configured; wire up gloo
    # over the coordination service so multi-process CPU fleets (tests,
    # chaos drills, laptops) can actually train. User settings (env or
    # config) win; TPU/GPU backends ignore the flag entirely.
    try:
        from jax._src import xla_bridge as _xb
        if _xb.CPU_COLLECTIVES_IMPLEMENTATION.value == "none":
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
    except Exception:  # pragma: no cover - older jaxlib without gloo
        pass
    kwargs = {}
    if coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        kwargs["coordinator_address"] = (
            coordinator_address or os.environ["JAX_COORDINATOR_ADDRESS"])
    if num_processes is not None or os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = int(
            num_processes if num_processes is not None
            else os.environ["JAX_NUM_PROCESSES"])
    if process_id is not None or os.environ.get("JAX_PROCESS_ID"):
        kwargs["process_id"] = int(
            process_id if process_id is not None
            else os.environ["JAX_PROCESS_ID"])
    jax.distributed.initialize(**kwargs)
    return process_info()


def process_info():
    return {"process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "local_devices": len(jax.local_devices()),
            "global_devices": len(jax.devices())}


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def sync_check(tree) -> bool:
    """Cross-process agreement check: True iff every process holds
    bit-identical leaves (the params-stay-in-sync assertion the Spark
    master enforced structurally by rebroadcasting; here it is a test/
    debug utility because SPMD keeps them in sync by construction)."""
    from jax.experimental import multihost_utils
    leaves = jax.tree_util.tree_leaves(tree)
    ok = True
    for leaf in leaves:
        arr = np.asarray(jax.device_get(leaf))
        gathered = multihost_utils.process_allgather(arr)
        ok = ok and bool(np.all(gathered == gathered[0]))
    return ok


class MultiProcessLocalSGD:
    """DP-3 substitution: the reference's asynchronous Aeron parameter
    server (deeplearning4j-scaleout-parallelwrapper-parameter-server/...
    /ParameterServerParallelWrapper.java:161 spawns ParameterServerNode,
    :208 workers push/pull over UDP).

    Design decision (documented substitution): asynchronous push/pull
    updates do not map onto the TPU SPMD model — there is no server to
    push to, and XLA programs are bulk-synchronous. The TPU-native
    equivalent with the same systems goal (decouple workers from
    lock-step gradient exchange, trade staleness for communication) is
    communication-avoiding LOCAL SGD: each process trains independently
    on its local data for ``averaging_frequency`` steps with NO
    cross-process traffic, then parameters (and optionally updater state)
    are averaged across processes over DCN. averaging_frequency=1
    degenerates to synchronous parameter averaging; larger values give
    the parameter-server-style reduced communication pattern.

    The net must NOT be meshed across processes (each process holds its
    own replica — the PS-worker analogue).
    """

    def __init__(self, net, averaging_frequency: int = 1,
                 average_updaters: bool = True):
        if averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")
        self.net = net
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self._local_steps = 0
        #: surplus local batches the windowed agreement dropped when the
        #: global-minimum count ended an epoch (uneven shards lose data
        #: silently otherwise — also counted into the
        #: dl4j_localsgd_dropped_batches_total metric)
        self.dropped_batches = 0
        # per-phase EventStats (ParameterAveragingTrainingMasterStats
        # parity — parallel/stats.py): fit / average timings per worker
        from deeplearning4j_tpu.parallel.stats import TrainingStatsCollector
        self.stats = TrainingStatsCollector(
            worker_id=f"worker_{jax.process_index()}")

    def _average_tree(self, tree):
        from jax.experimental import multihost_utils

        def avg(leaf):
            gathered = multihost_utils.process_allgather(
                np.asarray(jax.device_get(leaf)))
            return jax.numpy.asarray(
                np.mean(gathered, axis=0, dtype=np.float64).astype(
                    np.asarray(leaf).dtype))

        return jax.tree_util.tree_map(avg, tree)

    def average_now(self):
        """Cross-process parameter (+ updater-state) average — the
        processResults aggregate/divide step
        (ParameterAveragingTrainingMaster.java:851-877), as one DCN
        all-gather + mean instead of a driver round-trip."""
        with self.stats.time_phase("average"):
            self.net.params = self._average_tree(self.net.params)
            if self.average_updaters and self.net.opt_state is not None:
                self.net.opt_state = self._average_tree(self.net.opt_state)
        return self.net

    def fit_batch(self, ds):
        """One local step; averages every ``averaging_frequency`` steps.
        NOTE: the periodic average is a COLLECTIVE — when driving
        fit_batch directly, every process must take the same number of
        steps or the allgather deadlocks. ``fit`` handles uneven local
        iterators itself."""
        with self.stats.time_phase("fit"):
            score = self.net.fit_batch(ds)
            # the step is async-dispatched; pull the score so the timed
            # span covers real device work, not queue submission
            float(score)
        self._local_steps += 1
        if self._local_steps % self.averaging_frequency == 0:
            self.average_now()
        return score

    def _note_dropped(self, n: int):
        """Account surplus batches the agreement dropped: metric +
        one warning per epoch end (data loss must be observable, not
        silent)."""
        self.dropped_batches += n
        try:
            from deeplearning4j_tpu.observability.metrics import \
                get_registry
            get_registry().counter(
                "dl4j_localsgd_dropped_batches_total",
                "Surplus local batches dropped when the global-minimum "
                "count ended a LocalSGD epoch (uneven shards)").inc(n)
        except Exception:
            pass
        logger.warning(
            "MultiProcessLocalSGD.fit: dropping %d surplus local "
            "batch(es) on process %d — a peer ran out of data first "
            "(uneven shards; %d dropped total this trainer)",
            n, jax.process_index(), self.dropped_batches)

    def fit(self, iterator, *, epochs: int = 1, window: int | None = None):
        """Epoch loop over a LOCAL iterator. Processes may hold uneven
        batch counts (dataset not divisible by process count), and the
        agreed step count drives a COLLECTIVE schedule — so the counts
        must reflect what iteration actually yields (a sized iterator
        whose __len__ over-reports would deadlock the averaging allgather
        on one host).

        The agreement is WINDOWED: each round every process pulls up to
        ``window`` batches into a bounded buffer, the available counts are
        allgathered, the global minimum is trained on everywhere, and the
        leftovers carry into the next round. Memory is bounded by
        ``window`` batches (streaming epoch-scale data works), and the
        total step count per epoch equals the global-minimum batch count —
        identical to whole-epoch agreement. ``window`` defaults to
        max(averaging_frequency, 16)."""
        from jax.experimental import multihost_utils
        if window is None:
            window = max(self.averaging_frequency, 16)
        if window < 1:
            raise ValueError("window must be >= 1")
        for _ in range(epochs):
            it = iter(iterator)
            pending: list = []
            exhausted = False
            while True:
                while len(pending) < window and not exhausted:
                    try:
                        pending.append(next(it))
                    except StopIteration:
                        exhausted = True
                counts = multihost_utils.process_allgather(
                    np.asarray(len(pending)))
                n = int(np.min(counts))
                if n == 0:
                    # some process is out of data: epoch over everywhere
                    # (its peers drop their surplus, as the reference's
                    # balanced repartition would have prevented upstream)
                    if pending:
                        self._note_dropped(len(pending))
                    break
                for ds in pending[:n]:
                    self.fit_batch(ds)
                pending = pending[n:]
            if hasattr(iterator, "reset"):
                iterator.reset()
        if self._local_steps % self.averaging_frequency != 0:
            self.average_now()
        return self.net
