"""Multi-host (multi-process) distributed training — the DP-2 tier.

Replaces the reference's Spark parameter-averaging scaleout
(dl4j-spark/.../paramavg/ParameterAveragingTrainingMaster.java:358
executeTraining: broadcast params -> workers fit local minibatches ->
RDD.aggregate sums -> divide -> rebroadcast, §3.4) with the TPU-native
single-controller model (SURVEY.md §5.8): every process calls
``initialize()`` (jax.distributed), the device mesh spans ALL processes'
devices, and the SAME jitted train step runs SPMD everywhere — XLA lowers
the gradient all-reduce onto ICI within a host and DCN across hosts. There
is no driver, no broadcast step, and no parameter copy per round: the
"averaging" is the gradient psum inside the compiled step, every step.

Data feeding: each process supplies its LOCAL slice of the global batch;
``parallel.data_parallel.shard_batch`` assembles the process-local arrays
into one global sharded Array
(jax.make_array_from_process_local_data — the RDD-partition analogue) —
the meshed networks route through it automatically.

The exact-equivalence contract (TestCompareParameterAveragingSparkVs
SingleMachine.java analogue) is pinned by
tests/test_multihost.py: 2 spawned processes x 4 virtual CPU devices
training on disjoint batch halves must produce params bit-identical to
each other AND matching a single-process run on the full batch.
"""

from __future__ import annotations

import os

import jax
import numpy as np


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None):
    """Bring up the multi-process runtime (jax.distributed.initialize).

    Arguments default to the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) so launchers can stay declarative;
    on TPU pods with no args at all, jax autodetects the topology."""
    kwargs = {}
    if coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        kwargs["coordinator_address"] = (
            coordinator_address or os.environ["JAX_COORDINATOR_ADDRESS"])
    if num_processes is not None or os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = int(
            num_processes if num_processes is not None
            else os.environ["JAX_NUM_PROCESSES"])
    if process_id is not None or os.environ.get("JAX_PROCESS_ID"):
        kwargs["process_id"] = int(
            process_id if process_id is not None
            else os.environ["JAX_PROCESS_ID"])
    jax.distributed.initialize(**kwargs)
    return process_info()


def process_info():
    return {"process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "local_devices": len(jax.local_devices()),
            "global_devices": len(jax.devices())}


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def sync_check(tree) -> bool:
    """Cross-process agreement check: True iff every process holds
    bit-identical leaves (the params-stay-in-sync assertion the Spark
    master enforced structurally by rebroadcasting; here it is a test/
    debug utility because SPMD keeps them in sync by construction)."""
    from jax.experimental import multihost_utils
    leaves = jax.tree_util.tree_leaves(tree)
    ok = True
    for leaf in leaves:
        arr = np.asarray(jax.device_get(leaf))
        gathered = multihost_utils.process_allgather(arr)
        ok = ok and bool(np.all(gathered == gathered[0]))
    return ok


class MultiProcessLocalSGD:
    """DP-3 substitution: the reference's asynchronous Aeron parameter
    server (deeplearning4j-scaleout-parallelwrapper-parameter-server/...
    /ParameterServerParallelWrapper.java:161 spawns ParameterServerNode,
    :208 workers push/pull over UDP).

    Design decision (documented substitution): asynchronous push/pull
    updates do not map onto the TPU SPMD model — there is no server to
    push to, and XLA programs are bulk-synchronous. The TPU-native
    equivalent with the same systems goal (decouple workers from
    lock-step gradient exchange, trade staleness for communication) is
    communication-avoiding LOCAL SGD: each process trains independently
    on its local data for ``averaging_frequency`` steps with NO
    cross-process traffic, then parameters (and optionally updater state)
    are averaged across processes over DCN. averaging_frequency=1
    degenerates to synchronous parameter averaging; larger values give
    the parameter-server-style reduced communication pattern.

    The net must NOT be meshed across processes (each process holds its
    own replica — the PS-worker analogue).
    """

    def __init__(self, net, averaging_frequency: int = 1,
                 average_updaters: bool = True):
        if averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")
        self.net = net
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self._local_steps = 0
        # per-phase EventStats (ParameterAveragingTrainingMasterStats
        # parity — parallel/stats.py): fit / average timings per worker
        from deeplearning4j_tpu.parallel.stats import TrainingStatsCollector
        self.stats = TrainingStatsCollector(
            worker_id=f"worker_{jax.process_index()}")

    def _average_tree(self, tree):
        from jax.experimental import multihost_utils

        def avg(leaf):
            gathered = multihost_utils.process_allgather(
                np.asarray(jax.device_get(leaf)))
            return jax.numpy.asarray(
                np.mean(gathered, axis=0, dtype=np.float64).astype(
                    np.asarray(leaf).dtype))

        return jax.tree_util.tree_map(avg, tree)

    def average_now(self):
        """Cross-process parameter (+ updater-state) average — the
        processResults aggregate/divide step
        (ParameterAveragingTrainingMaster.java:851-877), as one DCN
        all-gather + mean instead of a driver round-trip."""
        with self.stats.time_phase("average"):
            self.net.params = self._average_tree(self.net.params)
            if self.average_updaters and self.net.opt_state is not None:
                self.net.opt_state = self._average_tree(self.net.opt_state)
        return self.net

    def fit_batch(self, ds):
        """One local step; averages every ``averaging_frequency`` steps.
        NOTE: the periodic average is a COLLECTIVE — when driving
        fit_batch directly, every process must take the same number of
        steps or the allgather deadlocks. ``fit`` handles uneven local
        iterators itself."""
        with self.stats.time_phase("fit"):
            score = self.net.fit_batch(ds)
            # the step is async-dispatched; pull the score so the timed
            # span covers real device work, not queue submission
            float(score)
        self._local_steps += 1
        if self._local_steps % self.averaging_frequency == 0:
            self.average_now()
        return score

    def fit(self, iterator, *, epochs: int = 1, window: int | None = None):
        """Epoch loop over a LOCAL iterator. Processes may hold uneven
        batch counts (dataset not divisible by process count), and the
        agreed step count drives a COLLECTIVE schedule — so the counts
        must reflect what iteration actually yields (a sized iterator
        whose __len__ over-reports would deadlock the averaging allgather
        on one host).

        The agreement is WINDOWED: each round every process pulls up to
        ``window`` batches into a bounded buffer, the available counts are
        allgathered, the global minimum is trained on everywhere, and the
        leftovers carry into the next round. Memory is bounded by
        ``window`` batches (streaming epoch-scale data works), and the
        total step count per epoch equals the global-minimum batch count —
        identical to whole-epoch agreement. ``window`` defaults to
        max(averaging_frequency, 16)."""
        from jax.experimental import multihost_utils
        if window is None:
            window = max(self.averaging_frequency, 16)
        if window < 1:
            raise ValueError("window must be >= 1")
        for _ in range(epochs):
            it = iter(iterator)
            pending: list = []
            exhausted = False
            while True:
                while len(pending) < window and not exhausted:
                    try:
                        pending.append(next(it))
                    except StopIteration:
                        exhausted = True
                counts = multihost_utils.process_allgather(
                    np.asarray(len(pending)))
                n = int(np.min(counts))
                if n == 0:
                    # some process is out of data: epoch over everywhere
                    # (its peers drop their surplus, as the reference's
                    # balanced repartition would have prevented upstream)
                    break
                for ds in pending[:n]:
                    self.fit_batch(ds)
                pending = pending[n:]
            if hasattr(iterator, "reset"):
                iterator.reset()
        if self._local_steps % self.averaging_frequency != 0:
            self.average_now()
        return self.net
