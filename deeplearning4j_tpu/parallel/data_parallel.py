"""Data-parallel training over a device mesh.

Replaces all three of the reference's data-parallel strategies (SURVEY.md
§2.8): ParallelWrapper (intra-node, Nd4j.averageAndPropagate at
ParallelWrapper.java:218), Spark ParameterAveragingTrainingMaster
(driver-centric broadcast/aggregate, ParameterAveragingTrainingMaster.java:358)
and the Aeron parameter server — with sharded computation: the batch is
sharded over the 'data' mesh axis, params are replicated, and XLA inserts the
gradient all-reduce over ICI as part of the single compiled train step.

``ParallelWrapper`` reproduces the reference's *semantics* (k local steps
between parameter averages) for the fixed-seed equivalence tests
(TestCompareParameterAveragingSparkVsSingleMachine analogue); with
``averaging_frequency=1`` it is mathematically the same as the sharded step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicate(mesh: Mesh, x):
    """Replicate a host value across the (possibly multi-process) mesh.
    In a multi-process runtime plain device_put cannot address remote
    devices; every process holds the identical full value, so the
    process-local-data assembly path produces the replicated global
    Array."""
    repl = NamedSharding(mesh, P())
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(repl, np.asarray(x))
    return jax.device_put(x, repl)


def apply_mesh(net, mesh: Mesh, data_axis: str = "data"):
    """Replicate the net's params/state/opt state across the mesh. Batches
    get sharded in fit_batch; computation follows sharding, so the jitted
    step becomes data-parallel with an ICI (and, across hosts, DCN)
    all-reduce on gradients."""
    put = lambda tree: jax.tree_util.tree_map(
        lambda leaf: replicate(mesh, leaf), tree)
    if net.params is not None:
        net.params = put(net.params)
    if net.state:
        net.state = put(net.state)
    if net.opt_state is not None:
        net.opt_state = put(net.opt_state)
    return net


def shard_batch(mesh: Mesh, data_axis: str, x):
    """Place a host batch sharded over the data axis (leading dim). In a
    multi-process runtime each process passes its LOCAL slice of the
    global batch (the Spark-partition analogue — SURVEY.md §3.4); the
    slices are assembled into one global sharded Array."""
    if x is None:
        return None
    spec = P(data_axis) if np.ndim(x) >= 1 else P()
    sh = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sh, np.asarray(x))
    return jax.device_put(jnp.asarray(x), sh)


def _pad_batch(x, labels, fmask, lmask, multiple: int):
    """Pad a partial batch up to a multiple of the data-axis size. Padded
    examples are masked out via the label mask, so the loss mean (and thus
    gradients) are identical to the unpadded batch."""
    n = x.shape[0]
    target = -(-n // multiple) * multiple
    if target == n:
        return x, labels, fmask, lmask
    pad = target - n

    def pad0(a):
        if a is None:
            return None
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(jnp.asarray(a), widths)

    if lmask is None:
        # per-example mask shaped like the label-mask convention
        lead = labels.shape[:-1] if labels.ndim > 1 else labels.shape
        lmask = jnp.ones(lead, jnp.float32)
    return pad0(x), pad0(labels), pad0(fmask), pad0(lmask)


def shard_step(net, step_fn, mesh: Mesh, data_axis: str = "data"):
    """Jit the train step for mesh execution. Params arrive replicated and
    batches sharded (set by apply_mesh/shard_batch); partial batches are
    zero-padded + mask-excluded so any batch size divides the mesh."""
    n_shards = mesh.shape[data_axis]
    # each process pads its LOCAL slice to its local share of the data axis
    pad_multiple = max(n_shards // jax.process_count(), 1)

    jitted = jax.jit(step_fn, donate_argnums=(0, 1, 2))

    def wrapped(params, state, opt_state, it, x, labels, fmask, lmask, rng):
        x, labels, fmask, lmask = _pad_batch(x, labels, fmask, lmask,
                                             pad_multiple)
        x = shard_batch(mesh, data_axis, x)
        labels = shard_batch(mesh, data_axis, labels)
        fmask = shard_batch(mesh, data_axis, fmask)
        lmask = shard_batch(mesh, data_axis, lmask)
        rng = replicate(mesh, rng)
        return jitted(params, state, opt_state, it, x, labels, fmask, lmask, rng)

    return wrapped


def _mask_lead_shape(label):
    """Label-mask leading shape: [b] for [b, c] labels, [b, t] for
    [b, t, c] sequence labels."""
    return label.shape[:-1] if label.ndim > 1 else label.shape


def shard_step_multi(net, step_fn, mesh: Mesh, data_axis: str = "data"):
    """ComputationGraph variant of shard_step: inputs are a dict and labels/
    masks are lists; every batch-leading tensor is sharded over the data
    axis; partial batches are zero-padded with padded rows excluded via the
    per-output label masks."""
    n_shards = mesh.shape[data_axis]
    # each process pads its LOCAL slice to its local share of the data axis
    pad_multiple = max(n_shards // jax.process_count(), 1)

    jitted = jax.jit(step_fn, donate_argnums=(0, 1, 2))

    def wrapped(params, state, opt_state, it, inputs, labels, fmasks, lmasks,
                rng):
        n = next(iter(inputs.values())).shape[0]
        target = -(-n // pad_multiple) * pad_multiple
        if target != n:
            pad = target - n

            def pad0(a):
                if a is None:
                    return None
                widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
                return jnp.pad(jnp.asarray(a), widths)

            inputs = {k: pad0(v) for k, v in inputs.items()}
            if lmasks is None:
                lmasks = [jnp.ones(_mask_lead_shape(l), jnp.float32)
                          for l in labels]
            else:
                lmasks = [jnp.ones(_mask_lead_shape(l), jnp.float32)
                          if m is None else m
                          for l, m in zip(labels, lmasks)]
            labels = [pad0(l) for l in labels]
            lmasks = [pad0(m) for m in lmasks]
            fmasks = {k: pad0(v) for k, v in fmasks.items()}
        inputs = {k: shard_batch(mesh, data_axis, v) for k, v in inputs.items()}
        labels = [shard_batch(mesh, data_axis, l) for l in labels]
        fmasks = {k: shard_batch(mesh, data_axis, v) for k, v in fmasks.items()}
        if lmasks is not None:
            lmasks = [shard_batch(mesh, data_axis, m) for m in lmasks]
        rng = replicate(mesh, rng)
        return jitted(params, state, opt_state, it, inputs, labels, fmasks,
                      lmasks, rng)

    return wrapped


class ParallelWrapper:
    """Reference-semantics data-parallel trainer: each of N logical workers
    runs ``averaging_frequency`` local steps, then parameters and (optionally)
    updater state are averaged (ParallelWrapper.java:181-218,:239-252).

    Implemented as a vmapped worker dimension + ``pmean``-equivalent
    tree-average; runs on any mesh or a single device. This exists for
    capability/equivalence parity — the sharded step above is the
    performance path.
    """

    def __init__(self, net, workers: int = 2, averaging_frequency: int = 1,
                 average_updaters: bool = True):
        self.net = net
        self.workers = workers
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters

    def fit(self, iterator, epochs: int = 1):
        net = self.net
        if net._train_step is None:
            net._train_step = net._build_train_step()
        step = net._train_step
        for _ in range(epochs):
            batch_iter = iter(iterator)
            done = False
            while not done:
                # Collect workers x averaging_frequency batches, round-robin
                # like the reference's per-worker queues.
                replicas = [
                    (jax.tree_util.tree_map(jnp.copy, net.params),
                     jax.tree_util.tree_map(jnp.copy, net.state),
                     jax.tree_util.tree_map(jnp.copy, net.opt_state))
                    for _ in range(self.workers)
                ]
                scores = []
                stepped = [False] * self.workers
                for _ in range(self.averaging_frequency):
                    for w in range(self.workers):
                        try:
                            ds = next(batch_iter)
                        except StopIteration:
                            done = True
                            break
                        stepped[w] = True
                        p, s, o = replicas[w]
                        net._rng_key, rng = jax.random.split(net._rng_key)
                        it_c = jnp.asarray(net.iteration, jnp.int32)
                        p, s, o, score = step(
                            p, s, o, it_c,
                            jnp.asarray(ds.features), jnp.asarray(ds.labels),
                            None if ds.features_mask is None
                            else jnp.asarray(ds.features_mask),
                            None if ds.labels_mask is None
                            else jnp.asarray(ds.labels_mask),
                            rng)
                        replicas[w] = (p, s, o)
                        scores.append(score)
                    if done:
                        break
                if not any(stepped):
                    break
                # Average params (and updater state) across the workers that
                # actually stepped — the Nd4j.averageAndPropagate equivalent,
                # here a tree-mean (idle tail workers are excluded so the
                # last partial round isn't diluted toward stale params).
                active = [replicas[w] for w in range(self.workers) if stepped[w]]
                def mean_leaf(*xs):
                    # Integer leaves (e.g. Adam's step counter 't') must stay
                    # integral: true-division would silently float them and
                    # retrace the donated jitted step. Max = the furthest
                    # worker's count, exact when workers step evenly.
                    if jnp.issubdtype(xs[0].dtype, jnp.integer):
                        return jnp.max(jnp.stack(xs), axis=0)
                    return sum(xs) / len(xs)
                def tree_mean(trees):
                    return jax.tree_util.tree_map(mean_leaf, *trees)
                net.params = tree_mean([r[0] for r in active])
                net.state = active[0][1]
                if self.average_updaters:
                    net.opt_state = tree_mean([r[2] for r in active])
                else:
                    net.opt_state = active[0][2]
                net.iteration += 1
                if scores:
                    net.score_value = scores[-1]
                for l in net.listeners:
                    l.iteration_done(net, net.iteration, net.epoch)
            iterator.reset()
            net.epoch += 1
        return net
