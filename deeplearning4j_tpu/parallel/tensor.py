"""Tensor (model) parallelism via GSPMD sharding annotations.

The reference's scaleout tier has no model-parallel story (parameter
averaging replicates the full model per worker —
ParameterAveragingTrainingMaster.java); on TPU, model parallelism is a
first-class mesh axis: shard the WEIGHTS over a ``model`` axis, keep the
batch on ``data``, and XLA's SPMD partitioner splits every matmul and
inserts the all-gathers / reduce-scatters over ICI — the
"pick a mesh, annotate shardings, let XLA insert collectives" recipe.
No hand-written collectives, no Megatron-style layer rewrites: the same
jitted train step runs dp, tp, or dp+tp depending only on how the
params are placed.

Default placement rule (override per-parameter with ``rules``): any
float weight with ndim >= 2 whose LAST axis divides the model-axis size
is sharded on that axis (column-parallel everywhere — after each layer
the activations are feature-sharded and XLA re-partitions where the
next op needs them); biases, norms, scalars, and indivisible tensors
replicate. Optimizer-state leaves inherit the sharding of the param
they track (shapes match); everything else replicates.

Caveat: custom Pallas kernels (the fused LSTM) do not auto-partition
under GSPMD — recurrent stacks scale via sequence parallelism
(parallel/sequence.py) instead; dense/conv stacks are the tp surface.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def default_rule(path: str, leaf, model_axis: str, axis_size: int):
    """PartitionSpec for one parameter leaf (see module docstring)."""
    shape = getattr(leaf, "shape", ())
    if (len(shape) >= 2 and shape[-1] % axis_size == 0
            and shape[-1] >= axis_size):
        return P(*([None] * (len(shape) - 1) + [model_axis]))
    return P()


def _split_rules(rules):
    """Normalize the two accepted rule forms into (exact, regex) lookups.

    ``rules`` is either a dict mapping EXACT keystr paths
    (``"['layer_0']['W']"``) to PartitionSpecs — the original tp_rules
    form — or a sequence of ``(pattern, spec)`` pairs where ``pattern``
    is matched with ``re.search`` against the keystr path (the
    match_partition_rules form: ``[(r"layer_\\d+.*W", P(None, "model"))]``,
    first match wins). Dict keys are treated as exact paths, never
    regexes, so existing bracket-heavy keys keep working unescaped."""
    if not rules:
        return {}, []
    if hasattr(rules, "items"):
        return dict(rules), []
    return {}, [(re.compile(pat), spec) for pat, spec in rules]


def match_partition_rules(rules, params, *, on_unmatched: str = "error"):
    """Regex rules -> PartitionSpec pytree (the SNIPPETS.md [1] exemplar
    mechanism). ``rules`` is a sequence of ``(regex, spec)`` pairs
    applied with ``re.search`` against each leaf's keystr path, first
    match wins; scalar/size-1 leaves never partition. ``on_unmatched``:
    ``"error"`` raises naming the unmatched param path, ``"replicate"``
    falls back to ``P()``."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_of(kp, leaf):
        path = jax.tree_util.keystr(kp)
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for pat, spec in compiled:
            if pat.search(path):
                return spec
        if on_unmatched == "replicate":
            return P()
        raise ValueError(f"partition rule not found for param: {path}")

    return jax.tree_util.tree_map_with_path(spec_of, params)


def unmatched_rules(rules, params) -> list:
    """Rule entries that match NO param path — exact dict keys checked
    by equality, regex pairs by ``re.search`` — so callers can validate
    eagerly (a rule that silently no-ops usually means a typo'd layer
    name, and the mis-placement only surfaces as OOM or wrong numerics
    much later). Returns the offending keys/patterns, in rule order."""
    exact, regex = _split_rules(rules)
    paths = [jax.tree_util.keystr(kp) for kp, _ in
             jax.tree_util.tree_flatten_with_path(params)[0]]
    missing = [key for key in exact if key not in paths]
    missing.extend(pat.pattern for pat, _ in regex
                   if not any(pat.search(p) for p in paths))
    return missing


def param_specs(params, mesh: Mesh, model_axis: str = "model",
                rules: Optional[Dict[str, P]] = None,
                rule: Optional[Callable] = None):
    """PartitionSpec pytree for a param tree. ``rules`` maps exact
    keystr paths (e.g. ``"['layer_0']['W']"``) to specs, or is a
    sequence of ``(regex, spec)`` pairs searched against the keystr
    path (first match wins); unmatched leaves go through ``rule``
    (default: last-axis column sharding)."""
    axis_size = mesh.shape[model_axis]
    rule = rule or default_rule
    exact, regex = _split_rules(rules)

    def spec_of(kp, leaf):
        path = jax.tree_util.keystr(kp)
        if path in exact:
            return exact[path]
        for pat, spec in regex:
            if pat.search(path):
                return spec
        return rule(path, leaf, model_axis, axis_size)

    return jax.tree_util.tree_map_with_path(spec_of, params)


def opt_state_specs(opt_state, specs):
    """PartitionSpec tree mirroring a net's opt_state: each layer's
    slots (momentum/velocity/...) whose structure matches the layer's
    param tree take the SAME spec tree — rules overrides included (a
    replicated-by-rule param must not keep model-sharded momentum, or
    sharding propagation re-shards it on the first update). Scalar slots
    (step counters) and non-layer entries (the ``_loss_scale`` dynamic
    loss-scaling state) replicate."""
    ts = jax.tree_util.tree_structure

    def layer_specs(ln, ln_state):
        ln_specs = specs.get(ln) if hasattr(specs, "get") else None
        out = {}
        for slot, sub in ln_state.items():
            if ln_specs is not None and ts(sub) == ts(ln_specs):
                out[slot] = jax.tree_util.tree_map(
                    lambda _, s: s, sub, ln_specs)
            else:
                out[slot] = jax.tree_util.tree_map(lambda leaf: P(), sub)
        return out

    return {ln: layer_specs(ln, st) for ln, st in opt_state.items()}


def apply_tensor_parallel(net, mesh: Mesh, data_axis: str = "data",
                          model_axis: str = "model",
                          rules: Optional[Dict[str, P]] = None):
    """Place a net's params over ``mesh`` with model-parallel sharding
    (and matching optimizer-state placement); batches stay sharded on
    ``data_axis`` by the existing shard_step machinery, so the compiled
    step is dp x tp over the 2-D mesh."""
    from deeplearning4j_tpu.parallel.data_parallel import replicate

    specs = param_specs(net.params, mesh, model_axis, rules)

    def put(leaf, spec):
        sh = NamedSharding(mesh, spec)
        if jax.process_count() > 1:
            # every process holds the identical FULL value; global_shape
            # must say so or the sharded dim gets inflated by the
            # each-host-holds-its-own-shard inference
            arr = np.asarray(leaf)
            return jax.make_array_from_process_local_data(
                sh, arr, global_shape=arr.shape)
        return jax.device_put(leaf, sh)

    net.params = jax.tree_util.tree_map(put, net.params, specs)

    if net.opt_state is not None:
        o_specs = opt_state_specs(net.opt_state, specs)
        net.opt_state = {
            ln: jax.tree_util.tree_map(put, st, o_specs[ln])
            for ln, st in net.opt_state.items()}
    if net.state:
        net.state = jax.tree_util.tree_map(
            lambda leaf: replicate(mesh, leaf), net.state)
    return net
