"""Tensor (model) parallelism via GSPMD sharding annotations.

The reference's scaleout tier has no model-parallel story (parameter
averaging replicates the full model per worker —
ParameterAveragingTrainingMaster.java); on TPU, model parallelism is a
first-class mesh axis: shard the WEIGHTS over a ``model`` axis, keep the
batch on ``data``, and XLA's SPMD partitioner splits every matmul and
inserts the all-gathers / reduce-scatters over ICI — the
"pick a mesh, annotate shardings, let XLA insert collectives" recipe.
No hand-written collectives, no Megatron-style layer rewrites: the same
jitted train step runs dp, tp, or dp+tp depending only on how the
params are placed.

Default placement rule (override per-parameter with ``rules``): any
float weight with ndim >= 2 whose LAST axis divides the model-axis size
is sharded on that axis (column-parallel everywhere — after each layer
the activations are feature-sharded and XLA re-partitions where the
next op needs them); biases, norms, scalars, and indivisible tensors
replicate. Optimizer-state leaves inherit the sharding of the param
they track (shapes match); everything else replicates.

Caveat: custom Pallas kernels (the fused LSTM) do not auto-partition
under GSPMD — recurrent stacks scale via sequence parallelism
(parallel/sequence.py) instead; dense/conv stacks are the tp surface.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def default_rule(path: str, leaf, model_axis: str, axis_size: int):
    """PartitionSpec for one parameter leaf (see module docstring)."""
    shape = getattr(leaf, "shape", ())
    if (len(shape) >= 2 and shape[-1] % axis_size == 0
            and shape[-1] >= axis_size):
        return P(*([None] * (len(shape) - 1) + [model_axis]))
    return P()


def param_specs(params, mesh: Mesh, model_axis: str = "model",
                rules: Optional[Dict[str, P]] = None,
                rule: Optional[Callable] = None):
    """PartitionSpec pytree for a param tree. ``rules`` maps exact
    keystr paths (e.g. ``"['layer_0']['W']"``) to specs; unmatched leaves
    go through ``rule`` (default: last-axis column sharding)."""
    axis_size = mesh.shape[model_axis]
    rule = rule or default_rule
    rules = rules or {}

    def spec_of(kp, leaf):
        path = jax.tree_util.keystr(kp)
        if path in rules:
            return rules[path]
        return rule(path, leaf, model_axis, axis_size)

    return jax.tree_util.tree_map_with_path(spec_of, params)


def apply_tensor_parallel(net, mesh: Mesh, data_axis: str = "data",
                          model_axis: str = "model",
                          rules: Optional[Dict[str, P]] = None):
    """Place a net's params over ``mesh`` with model-parallel sharding
    (and matching optimizer-state placement); batches stay sharded on
    ``data_axis`` by the existing shard_step machinery, so the compiled
    step is dp x tp over the 2-D mesh."""
    from deeplearning4j_tpu.parallel.data_parallel import replicate

    specs = param_specs(net.params, mesh, model_axis, rules)

    def put(leaf, spec):
        sh = NamedSharding(mesh, spec)
        if jax.process_count() > 1:
            # every process holds the identical FULL value; global_shape
            # must say so or the sharded dim gets inflated by the
            # each-host-holds-its-own-shard inference
            arr = np.asarray(leaf)
            return jax.make_array_from_process_local_data(
                sh, arr, global_shape=arr.shape)
        return jax.device_put(leaf, sh)

    net.params = jax.tree_util.tree_map(put, net.params, specs)

    # optimizer state: each layer's slots (momentum/velocity/...) mirror
    # that layer's param tree, so they take the SAME spec tree — rules
    # overrides included (a replicated-by-rule param must not keep
    # model-sharded momentum, or sharding propagation re-shards it on
    # the first update). Scalar slots (step counters) replicate.
    if net.opt_state is not None:
        ts = jax.tree_util.tree_structure

        def place_layer_opt(ln, ln_state):
            ln_specs = specs.get(ln) if hasattr(specs, "get") else None
            out = {}
            for slot, sub in ln_state.items():
                if ln_specs is not None and ts(sub) == ts(ln_specs):
                    out[slot] = jax.tree_util.tree_map(put, sub, ln_specs)
                else:
                    out[slot] = jax.tree_util.tree_map(
                        lambda leaf: put(leaf, P()), sub)
            return out

        net.opt_state = {ln: place_layer_opt(ln, st)
                         for ln, st in net.opt_state.items()}
    if net.state:
        net.state = jax.tree_util.tree_map(
            lambda leaf: replicate(mesh, leaf), net.state)
    return net
