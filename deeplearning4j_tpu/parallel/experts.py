"""Expert (MoE) parallelism over an ``expert`` mesh axis.

The last of the five mesh axes (dp/tp/pp/sp/ep). A mixture-of-experts
feed-forward bank: each token is routed to its top-k experts, expert
weights live stacked with a leading expert dim SHARDED over the
``expert`` axis, and the dispatch/combine einsums against the one-hot
routing tensors are the classic Shazeer formulation — GSPMD partitions
them and inserts the all-to-alls over ICI, exactly as it inserts the
gradient all-reduce for dp. No reference analogue (2017-era DL4J
predates MoE); included because expert parallelism is a first-class
scaling axis on TPU and shapes the framework's mesh design.

Capacity semantics: each expert processes at most ``capacity`` tokens
per batch; overflow tokens are DROPPED from the expert path (standard
GShard behavior) and pass through with zero expert contribution —
training remains differentiable through the router probabilities.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(key, n_experts: int, f_in: int, f_hidden: int,
                    f_out: Optional[int] = None, dtype=jnp.float32):
    """Router + stacked expert FFN params (expert dim leads)."""
    f_out = f_out or f_in
    k_r, k_1, k_2 = jax.random.split(key, 3)
    s1 = (2.0 / (f_in + f_hidden)) ** 0.5
    s2 = (2.0 / (f_hidden + f_out)) ** 0.5
    return {
        "router": jax.random.normal(k_r, (f_in, n_experts), dtype) * 0.02,
        "W1": jax.random.normal(k_1, (n_experts, f_in, f_hidden),
                                dtype) * s1,
        "b1": jnp.zeros((n_experts, f_hidden), dtype),
        "W2": jax.random.normal(k_2, (n_experts, f_hidden, f_out),
                                dtype) * s2,
        "b2": jnp.zeros((n_experts, f_out), dtype),
    }


def shard_experts(mesh: Mesh, expert_axis: str, params):
    """Place MoE params: expert-stacked weights sharded on the expert
    dim, the router replicated."""
    def put(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name == "router":
            spec = P()
        else:
            spec = P(expert_axis, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map_with_path(put, params)


def moe_ffn(params, x, *, capacity: Optional[int] = None, top_k: int = 1):
    """Routed mixture-of-experts FFN on ``x`` [tokens, f_in].

    Pure function of sharded params — under jit on a mesh whose
    ``expert`` axis holds the stacked weights, GSPMD turns the dispatch/
    combine einsums into all-to-alls and runs each expert's FFN on its
    own devices. Returns ([tokens, f_out], aux_loss) where aux_loss is
    the standard load-balancing loss (mean_prob * mean_assignment * E)."""
    n_tokens = x.shape[0]
    n_experts = params["W1"].shape[0]
    if capacity is None:
        capacity = max(2 * top_k * n_tokens // n_experts, 4)

    logits = x @ params["router"].astype(x.dtype)       # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    combine_chunks = []
    masked_probs = probs
    occupancy = jnp.zeros((n_experts,), probs.dtype)  # kept tokens so far
    assign_chunks = []  # pre-capacity routing decisions, per round
    for _ in range(top_k):
        idx = jnp.argmax(masked_probs, axis=-1)          # [T]
        onehot = jax.nn.one_hot(idx, n_experts, dtype=probs.dtype)
        assign_chunks.append(onehot)
        # 1-based position in the chosen expert's queue, CONTINUING after
        # the slots earlier routing rounds already claimed (per-round
        # restarts would collide round-1 and round-2 tokens in one slot)
        pos = (jnp.cumsum(onehot, axis=0) + occupancy[None, :]) * onehot
        keep = (pos <= capacity).astype(probs.dtype) * onehot
        occupancy = occupancy + keep.sum(0)
        gate = (masked_probs * keep).sum(-1, keepdims=True)  # [T, 1]
        pos_oh = jax.nn.one_hot(((pos * keep).sum(-1) - 1).astype(jnp.int32),
                                capacity, dtype=probs.dtype)
        # [T, E, C] dispatch/combine tensors (Shazeer einsum form)
        combine_chunks.append(
            gate[:, :, None] * keep[:, :, None] * pos_oh[:, None, :])
        masked_probs = masked_probs * (1.0 - onehot)
    combine = sum(combine_chunks)                        # [T, E, C]
    dispatch = (combine > 0).astype(x.dtype)

    expert_in = jnp.einsum("tec,tf->ecf", dispatch, x)   # [E, C, f_in]
    h = jax.nn.relu(jnp.einsum("ecf,efh->ech", expert_in,
                               params["W1"].astype(x.dtype))
                    + params["b1"][:, None, :].astype(x.dtype))
    expert_out = (jnp.einsum("ech,eho->eco", h,
                             params["W2"].astype(x.dtype))
                  + params["b2"][:, None, :].astype(x.dtype))
    y = jnp.einsum("tec,eco->to", combine.astype(x.dtype), expert_out)

    # load-balancing auxiliary (GShard/Switch): encourages uniform
    # routing; differentiable through probs. The assignment fraction
    # comes from the router's PRE-capacity one-hot choices, not the
    # post-drop dispatch tensor: under heavy overflow the dropped tokens
    # are concentrated on exactly the overloaded experts, so counting
    # only kept tokens would under-penalize the imbalance the loss
    # exists to correct (Switch §2.2 / GShard semantics).
    assign = sum(assign_chunks).astype(jnp.float32)      # [T, E]
    aux = (probs.mean(0) * assign.mean(0)).sum() * n_experts
    return y, aux
