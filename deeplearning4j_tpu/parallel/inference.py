"""Mesh-parallel inference: the serving-side twin of parallel/tensor.py.

Training already runs dp x tp by annotating shardings and letting
GSPMD insert collectives (parallel/tensor.py). Serving cannot reuse
that recipe unchanged, because its contract is stricter: a coalesced
f32 serving forward must return rows BIT-IDENTICAL to the single-device
``net.output()`` (SERVING.md). Under plain GSPMD the partitioner is
free to shard a matmul's *contraction* dimension — each device then
computes partial sums that an all-reduce combines in a different order
than the single-device dot, and replies drift by last-ulp amounts
(measured: ~1e-8 relative on the 8-device CPU mesh, exactly the
reduction-order noise the bit-identity contract forbids).

So the serving forward is built with ``shard_map`` and explicit
collectives chosen to be arithmetic-free:

- weights shard column-parallel over ``model_axis`` (same default rule
  and per-path override ``rules`` as training's tp — one placement
  vocabulary for both);
- each device computes its full-contraction local matmul (no partial
  sums anywhere), producing feature-sharded activations;
- layer boundaries re-assemble with ``all_gather(tiled=True)`` — a pure
  concatenation, so no floating-point op ever sees a different operand
  order than the single-device walk;
- optionally the batch shards over ``data_axis`` too (dp x tp serving):
  row slicing and the final gather are also exact.

The remaining bit-identity condition is the same one the bucket
ladder's ``min_batch`` floor already manages: XLA's *local* gemm kernel
must block the K loop identically at sharded and unsharded widths. On
XLA:CPU that holds for contraction dims < 256 (pinned by the serve
bench's mesh check); on TPU the MXU K loop is width-independent.

Params are sharded ONCE at server start (``shard_params_for_serving``)
and the returned forward reads ``net.params`` live on every call, so a
net that is still training serves its freshest weights — the same
aliasing contract as the bf16 serving shadow (PRECISION.md).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import compat_shard_map
from deeplearning4j_tpu.parallel.tensor import param_specs

# Activations that normalize ACROSS the feature axis. A column-sharded
# layer applies its activation to the local feature slice BEFORE the
# gather, which is exact for elementwise activations but wrong for these
# (softmax over a 1-column shard is identically 1.0) — such layers serve
# replicated regardless of divisibility.
_CROSS_FEATURE_ACTIVATIONS = frozenset({"softmax", "logsoftmax"})


def _mixes_features(layer) -> bool:
    name = getattr(getattr(layer, "activation_fn", None),
                   "activation_name", None)
    return name in _CROSS_FEATURE_ACTIVATIONS


def serving_param_specs(params, mesh: Mesh, model_axis: str = "model",
                        rules: Optional[Dict[str, P]] = None, layers=None):
    """Training's ``param_specs`` plus two serving-walk corrections.

    Bias co-sharding: GSPMD can keep a bias replicated next to a
    column-sharded weight (it re-shards at the add), but the shard_map
    walk computes with the LOCAL shards directly: a layer whose weight
    is column-parallel produces a feature-sharded local activation, so
    its 1-D params of the same output width must arrive as matching
    column shards.

    Cross-feature replication: layers whose activation mixes across the
    feature axis (softmax heads) must compute full-width, so their
    params stay replicated even when the width divides the axis —
    unless an explicit per-path ``rules`` override claims them."""
    specs = param_specs(params, mesh, model_axis, rules)
    rules = rules or {}
    for layer in layers or ():
        lname = getattr(layer, "name", None)
        if not _mixes_features(layer) or lname not in (
                specs if hasattr(specs, "items") else {}):
            continue
        lspecs = specs[lname]
        if hasattr(lspecs, "items"):
            for k in lspecs:
                if f"['{lname}']['{k}']" not in rules:
                    lspecs[k] = P()
    for lname, lspecs in (specs.items() if hasattr(specs, "items") else ()):
        if not hasattr(lspecs, "items"):
            continue
        widths = {params[lname][k].shape[-1] for k, s in lspecs.items()
                  if isinstance(s, P) and len(s) >= 2
                  and s[-1] == model_axis}
        if not widths:
            continue
        for k, s in lspecs.items():
            leaf = params[lname][k]
            path = f"['{lname}']['{k}']"
            if (path not in rules and isinstance(s, P) and len(s) == 0
                    and getattr(leaf, "ndim", 0) == 1
                    and leaf.shape[0] in widths):
                lspecs[k] = P(model_axis)
    return specs


def shard_params_for_serving(net, mesh: Mesh, model_axis: str = "model",
                             rules: Optional[Dict[str, P]] = None):
    """Place ``net.params`` over ``mesh`` with the serving tp rule
    (overridable per-path via ``rules`` — same keystr convention as
    training). Runs once at server start; returns the spec pytree.
    Cached jitted forwards are dropped — they were compiled for the old
    placement."""
    specs = serving_param_specs(net.params, mesh, model_axis, rules,
                                layers=getattr(net, "layers", None))

    def put(leaf, spec):
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    net.params = jax.tree_util.tree_map(put, net.params, specs)
    if getattr(net, "state", None):
        from deeplearning4j_tpu.parallel.data_parallel import replicate
        net.state = jax.tree_util.tree_map(
            lambda leaf: replicate(mesh, leaf), net.state)
    net._apply_fns = {}
    return specs


def _layer_output_sharded(layer_specs, model_axis: str) -> bool:
    """True when any of the layer's param specs shard their LAST axis on
    ``model_axis`` — column-parallel weights make the layer's output
    feature-sharded, so the walk must all-gather after it."""
    for spec in jax.tree_util.tree_leaves(
            layer_specs, is_leaf=lambda s: isinstance(s, P)):
        if isinstance(spec, P) and len(spec) and spec[-1] == model_axis:
            return True
    return False


def build_tp_output_fn(net, mesh: Mesh, model_axis: str = "model",
                       data_axis: Optional[str] = None,
                       rules: Optional[Dict[str, P]] = None) -> Callable:
    """Shard ``net``'s params over ``mesh`` (once) and return a
    ``forward(feats) -> out`` callable running the tensor-parallel
    serving walk described in the module docstring. ``feats`` is the
    batcher's padded-bucket input list (one array for a layer stack).

    Supports MultiLayerNetwork-style layer stacks with stateless
    inference (Dense/conv/activation heads). Nets with layer state (BN
    running stats) or ComputationGraph DAGs serve replicated instead —
    their stacked-vertex walk is not expressible as a generic
    shard-and-gather chain yet."""
    layers = getattr(net, "layers", None)
    if layers is None or not hasattr(net, "preprocessors"):
        raise TypeError(
            "mesh-parallel serving supports MultiLayerNetwork layer "
            f"stacks; got {type(net).__name__} (serve ComputationGraph "
            "replicated, or per-replica placed)")
    if getattr(net, "state", None):
        raise ValueError(
            "mesh-parallel serving requires stateless inference layers; "
            f"this net carries state for {sorted(net.state)} (running "
            "stats would need the same per-channel sharding as their "
            "params) — serve it replicated instead")
    if model_axis not in mesh.shape:
        raise ValueError(f"mesh has no {model_axis!r} axis: {mesh.shape}")
    if data_axis is not None and data_axis not in mesh.shape:
        raise ValueError(f"mesh has no {data_axis!r} axis: {mesh.shape}")

    specs = shard_params_for_serving(net, mesh, model_axis, rules)
    gather_after = {ly.name: _layer_output_sharded(specs.get(ly.name, {}),
                                                   model_axis)
                    for ly in layers}

    def local_fwd(params, x):
        # the device-local rendering of MultiLayerNetwork._forward's
        # inference walk (train=False, no rng/masks, no remat): params
        # arrive as this device's column shards, activations re-assemble
        # exactly at each sharded layer's boundary
        for i, layer in enumerate(layers):
            if net.preprocessors[i] is not None:
                x = net.preprocessors[i](x)
            x, _ = layer.apply(params.get(layer.name, {}), {}, x,
                               train=False, rng=None, mask=None)
            if gather_after[layer.name]:
                x = jax.lax.all_gather(x, model_axis, axis=x.ndim - 1,
                                       tiled=True)
        return x

    x_spec = P(data_axis) if data_axis is not None else P()
    sharded = compat_shard_map(local_fwd, mesh=mesh,
                               in_specs=(specs, x_spec),
                               out_specs=x_spec)
    jitted = jax.jit(sharded)
    batch_spec = NamedSharding(mesh, x_spec)

    def forward(feats):
        # reads net.params live: a training net serves fresh weights
        x = jax.device_put(np.asarray(feats[0]), batch_spec)
        return jitted(net.params, x)

    return forward
