"""Device-mesh helpers.

The mesh is the TPU-native replacement for the reference's device topology
handling (ParallelWrapper's AffinityManager thread->device pinning,
ParallelWrapper.java:352): axes are logical ('data', 'model', ...) and XLA
maps collectives onto ICI rings.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size}. Default: all local devices on one
    'data' axis (pure data parallelism, the reference's only strategy)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    if axes is None:
        axes = {"data": len(devices)}
    sizes = list(axes.values())
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"Mesh needs {total} devices but only {len(devices)} available")
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))
