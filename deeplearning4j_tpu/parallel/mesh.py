"""Device-mesh helpers.

The mesh is the TPU-native replacement for the reference's device topology
handling (ParallelWrapper's AffinityManager thread->device pinning,
ParallelWrapper.java:352): axes are logical ('data', 'model', ...) and XLA
maps collectives onto ICI rings.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size}. Default: all local devices on one
    'data' axis (pure data parallelism, the reference's only strategy)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    if axes is None:
        axes = {"data": len(devices)}
    sizes = list(axes.values())
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"Mesh needs {total} devices but only {len(devices)} available")
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def compat_shard_map(f, *, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` with per-shard replication checking off, on any
    supported jax: the top-level entry point (and its ``check_vma``
    kwarg) only exists on newer releases — older ones ship it as
    ``jax.experimental.shard_map.shard_map(..., check_rep=...)``."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map
    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)
