"""Per-phase distributed training statistics + timeline export.

Parity: dl4j-spark/.../impl/paramavg/stats/
ParameterAveragingTrainingMasterStats.java — the reference times every
phase of a distributed training round (broadcast / fit / aggregate /
processParams) as ``EventStats`` (BaseEventStats.java: start time +
duration + worker id) and exports them as an HTML timeline
(spark/stats/StatsUtils.java exportStatsAsHtml). Here the phases are the
TPU-native round structure (local ``fit`` window, DCN ``average``,
``checkpoint_barrier``), recorded by the trainers in
parallel/distributed.py and nlp/distributed.py, gathered across
processes, and rendered through the ui/components.py ChartTimeline —
the same component tier the reference's StatsUtils uses.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional, Sequence

#: stable phase -> color mapping for timeline rendering
PHASE_COLORS = {
    "fit": "#1f77b4",
    "average": "#ff7f0e",
    "checkpoint_barrier": "#2ca02c",
    "broadcast": "#9467bd",
    "vocab": "#8c564b",
}
_FALLBACK_COLOR = "#7f7f7f"


@dataclass
class EventStats:
    """One timed phase occurrence (BaseEventStats.java parity: machine/
    worker id + start + duration)."""
    worker_id: str
    phase: str
    start: float          # seconds since the collector's epoch
    duration_ms: float

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "EventStats":
        return EventStats(d["worker_id"], d["phase"], d["start"],
                          d["duration_ms"])


class TrainingStatsCollector:
    """Records EventStats for one worker; merges across workers for
    export (the TrainingMasterStats aggregation surface)."""

    def __init__(self, worker_id: str = "worker_0"):
        self.worker_id = worker_id
        self.events: List[EventStats] = []
        self._epoch = time.perf_counter()
        # phases may now be timed from a background thread (the async
        # checkpoint writer records checkpoint_barrier off the step path)
        self._lock = threading.Lock()

    @contextmanager
    def time_phase(self, phase: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            ev = EventStats(self.worker_id, phase, t0 - self._epoch,
                            (t1 - t0) * 1000.0)
            with self._lock:
                self.events.append(ev)
            # phases land in the unified span timeline too, so a Spark-
            # tier `average` shows up against fit-loop/checkpoint spans
            from deeplearning4j_tpu.observability.trace import get_tracer
            get_tracer().record(phase, t0, t1,
                                {"worker": self.worker_id})

    def _snapshot(self) -> List[EventStats]:
        """Copy under the lock: the async checkpoint writer may still be
        appending while a reader iterates (seen as a mid-iteration
        ``RuntimeError: list changed size`` / missing-tail race before
        this existed). ALL readers go through here."""
        with self._lock:
            return list(self.events)

    # ------------------------------------------------------------ queries
    def phase_totals_ms(self) -> Dict[str, float]:
        """Total wall-clock per phase (the getSummaryStats table)."""
        out: Dict[str, float] = {}
        for e in self._snapshot():
            out[e.phase] = out.get(e.phase, 0.0) + e.duration_ms
        return out

    # ------------------------------------------------------- aggregation
    def gather_across_processes(self) -> List[EventStats]:
        """All-gather every process's events (the RDD collect the Spark
        master does before export). COLLECTIVE — every process must call
        it. Event ``start`` clocks stay per-worker-relative, which is
        what the per-lane timeline renders."""
        import numpy as np
        from jax.experimental import multihost_utils

        payload = json.dumps([e.to_dict() for e in self._snapshot()])
        buf = np.frombuffer(payload.encode(), dtype=np.uint8)
        # ragged gather: pad to the global max length
        n = np.asarray(len(buf))
        lens = multihost_utils.process_allgather(n)  # one collective
        max_n = int(np.max(lens))
        padded = np.zeros(max_n, np.uint8)
        padded[:len(buf)] = buf
        blobs = multihost_utils.process_allgather(padded)
        events: List[EventStats] = []
        for row, ln in zip(blobs, lens):
            events.extend(EventStats.from_dict(d) for d in
                          json.loads(bytes(row[:int(ln)]).decode()))
        return events

    # ------------------------------------------------------------ export
    def post_to(self, storage, session_id: str = "training") -> None:
        """Publish this worker's events through a StatsStorage/router
        (``put_static_info`` — the dashboard's /api/phases reads it)."""
        storage.put_static_info(session_id, self.worker_id, {
            "phase_stats": [e.to_dict() for e in self._snapshot()]})


def timeline_component(events: Sequence[EventStats],
                       title: str = "Training phases"):
    """Per-worker lanes of colored phase bars (StatsUtils.java
    exportStatsAsHtml -> ChartTimeline parity)."""
    from deeplearning4j_tpu.ui.components import ChartTimeline, Style

    by_worker: Dict[str, List[EventStats]] = {}
    for e in events:
        by_worker.setdefault(e.worker_id, []).append(e)
    chart = ChartTimeline(title, Style(
        width=760, height=max(120, 46 + 34 * len(by_worker))),
        xlabel="seconds")
    for worker in sorted(by_worker):
        entries = [(e.start, e.start + e.duration_ms / 1000.0, e.phase,
                    PHASE_COLORS.get(e.phase, _FALLBACK_COLOR))
                   for e in sorted(by_worker[worker], key=lambda e: e.start)]
        chart.add_lane(worker, entries)
    return chart


def summary_table(events: Sequence[EventStats]):
    """Per-worker per-phase totals (the summary-stats table the HTML
    export leads with)."""
    from deeplearning4j_tpu.ui.components import ComponentTable

    phases = sorted({e.phase for e in events})
    by_worker: Dict[str, Dict[str, float]] = {}
    counts: Dict[str, int] = {}
    for e in events:
        row = by_worker.setdefault(e.worker_id, {})
        row[e.phase] = row.get(e.phase, 0.0) + e.duration_ms
        counts[e.worker_id] = counts.get(e.worker_id, 0) + 1
    content = [
        [w, str(counts[w])] + [f"{by_worker[w].get(p, 0.0):.1f}"
                               for p in phases]
        for w in sorted(by_worker)]
    return ComponentTable(["worker", "events"] + [f"{p} (ms)"
                                                  for p in phases],
                          content, title="Per-phase totals")


def export_timeline_html(events: Sequence[EventStats], path: str,
                         title: str = "Distributed training timeline"):
    """StatsUtils.exportStatsAsHTML parity: standalone timeline page."""
    from deeplearning4j_tpu.ui.components import render_components_to_file

    render_components_to_file(
        [summary_table(events), timeline_component(events)], path, title)
