"""AOT precompile manifest: the schema'd receipt that rides next to a
persistent compilation cache.

The cache dir alone is opaque — a directory of hashed executables says
nothing about WHAT was precompiled. The manifest records it: model
fingerprint (params-pytree paths/shapes/dtypes), dtype policy, serving
row shapes + bucket ladder, mesh axes, jax version and backend. At
boot the server validates its own configuration against the manifest
(:func:`validate_serving`); any mismatch means the cached executables
were built for a DIFFERENT program, so the server warns and falls back
to lazy compile instead of trusting a stale artifact — the same
contract a schema-versioned checkpoint gives restore.

The manifest never gates correctness (the cache is keyed by HLO, a
mismatched entry simply misses); it gates *expectations* — a boot that
believes it is warm but compiles everything fresh is a silent perf
regression this file makes loud.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import List, Optional

SCHEMA_VERSION = 1
#: default manifest filename inside a cache dir — the server looks here
#: when ``aot_manifest`` isn't given explicitly
MANIFEST_NAME = "aot_manifest.json"


def model_fingerprint(net) -> str:
    """sha256 (truncated) over the params pytree structure: every leaf's
    path, shape and dtype, plus the net class. Two nets with the same
    fingerprint lower to the same parameter signature — the precondition
    for their cached executables to be interchangeable."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(net.params)
    items = [(jax.tree_util.keystr(path), list(getattr(leaf, "shape", ())),
              str(getattr(leaf, "dtype", "?")))
             for path, leaf in flat]
    blob = json.dumps([type(net).__name__, items], sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _mesh_axes(mesh) -> Optional[dict]:
    if mesh is None:
        return None
    return {str(k): int(v) for k, v in dict(mesh.shape).items()}


def build(net, *, serving: Optional[dict] = None,
          train: Optional[List[dict]] = None) -> dict:
    """Assemble a manifest for *net*. ``serving`` / ``train`` are the
    entry dicts :mod:`compilecache.precompile` returns."""
    import jax
    gc = net.conf.global_conf
    man = {
        "schema_version": SCHEMA_VERSION,
        "created_unix": round(time.time(), 3),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "model": {
            "class": type(net).__name__,
            "num_params": int(net.num_params()),
            "fingerprint": model_fingerprint(net),
            "param_dtype": gc.dtype.param_dtype,
            "compute_dtype": gc.dtype.compute_dtype,
        },
    }
    if serving is not None:
        man["serving"] = serving
    if train:
        man["train"] = train
    return man


def save(manifest: dict, path: str) -> str:
    """Atomic write (tmp + rename); ``path`` may be a cache DIR, in
    which case the manifest lands at ``<dir>/aot_manifest.json``."""
    if os.path.isdir(path):
        path = os.path.join(path, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load(path: str) -> dict:
    if os.path.isdir(path):
        path = os.path.join(path, MANIFEST_NAME)
    with open(path) as f:
        return json.load(f)


def validate_serving(manifest: dict, net, *, row_shapes, ladder,
                     max_batch: int, min_batch: int,
                     compute_dtype: str, mesh=None) -> List[str]:
    """Compare a boot-time serving configuration against the manifest.
    Returns a list of human-readable mismatch strings — empty means the
    precompiled artifacts cover exactly this boot. Every check compares
    something that changes the HLO (and therefore the cache key): jax
    version, backend, model signature, dtypes, shapes, ladder, mesh."""
    import jax
    mm: List[str] = []

    def need(cond, msg):
        if not cond:
            mm.append(msg)

    need(manifest.get("schema_version") == SCHEMA_VERSION,
         f"schema_version {manifest.get('schema_version')!r} != "
         f"{SCHEMA_VERSION}")
    need(manifest.get("jax_version") == jax.__version__,
         f"jax_version {manifest.get('jax_version')!r} != "
         f"{jax.__version__!r}")
    need(manifest.get("backend") == jax.default_backend(),
         f"backend {manifest.get('backend')!r} != "
         f"{jax.default_backend()!r}")
    model = manifest.get("model") or {}
    need(model.get("class") == type(net).__name__,
         f"model class {model.get('class')!r} != {type(net).__name__!r}")
    fp = model_fingerprint(net)
    need(model.get("fingerprint") == fp,
         f"model fingerprint {model.get('fingerprint')!r} != {fp!r}")
    serving = manifest.get("serving")
    if serving is None:
        mm.append("manifest has no 'serving' entry")
        return mm
    want_shapes = [list(s) for s in row_shapes]
    need(serving.get("row_shapes") == want_shapes,
         f"row_shapes {serving.get('row_shapes')!r} != {want_shapes!r}")
    need(serving.get("ladder") == list(ladder),
         f"ladder {serving.get('ladder')!r} != {list(ladder)!r}")
    need(serving.get("max_batch") == int(max_batch),
         f"max_batch {serving.get('max_batch')!r} != {int(max_batch)}")
    need(serving.get("min_batch") == int(min_batch),
         f"min_batch {serving.get('min_batch')!r} != {int(min_batch)}")
    need(serving.get("compute_dtype") == compute_dtype,
         f"serving compute_dtype {serving.get('compute_dtype')!r} != "
         f"{compute_dtype!r}")
    need(serving.get("mesh_axes") == _mesh_axes(mesh),
         f"mesh_axes {serving.get('mesh_axes')!r} != {_mesh_axes(mesh)!r}")
    return mm
