"""Cold-start & compile-time engine (ROADMAP item 4, TVM grounding:
compilation artifacts and schedule choices are managed, measured state
— not boot-time side effects).

Three layers:

- :mod:`cache` — the persistent XLA compilation cache as a first-class
  knob: ``configure(dir)`` / the ``DL4J_TPU_COMPILE_CACHE`` env var wire
  ``jax_compilation_cache_dir`` through ``ModelServer``/``serve()``/
  ``fit``/``resilient_fit``; hit/miss traffic lands in
  ``dl4j_xla_cache_hits_total`` / ``_misses_total`` and on RunReport.
  The dir may be a SHARED mount (NFS/GCS-style): ``configure`` stamps
  it with an atomically-published marker and is concurrent-configure
  safe across processes, so a whole fleet warm-boots from one host's
  compiles (SERVING.md "Cross-host federation").
- :mod:`manifest` + :mod:`precompile` — AOT ``lower().compile()`` of
  the serving bucket ladder and both nets' train steps at BUILD time
  (scripts/precompile.py), persisting executables into the cache dir
  with a schema'd JSON manifest the server validates at boot; a
  mismatch warns and falls back to lazy compile.
- :mod:`autotune` — replay a ``serve_bench --out`` traffic trace
  offline and search the (bucket ladder, linger window) space for the
  config minimizing p99 x padding waste; the server loads the winning
  config via ``tuning_report=``.
"""

from deeplearning4j_tpu.compilecache.cache import (ENV_VAR, META_NAME,
                                                   atomic_publish, cache_dir,
                                                   configure, deactivate,
                                                   ensure_configured,
                                                   shared_meta)

__all__ = ["ENV_VAR", "META_NAME", "cache_dir", "configure", "deactivate",
           "ensure_configured", "atomic_publish", "shared_meta"]
