"""Traffic-trace-driven autotuning of the serving schedule knobs.

The bucket ladder cap (``max_batch``) and linger window
(``batch_window_ms``) are schedule choices: bigger buckets amortize
dispatch but pad more and wait longer to fill; a longer linger raises
coalescing at the direct cost of tail latency. The right point depends
on the traffic SHAPE — so, per the TVM stance (PAPERS.md: measured
search over schedules, not a hand model), we replay a recorded
``serve_bench --out`` trace offline against a simulator of the
dispatcher and search the grid.

The simulator replays the micro-batcher's exact dispatch semantics
(serving/batcher.py): one device thread; the oldest pending ticket
starts a batch; compatible arrivals coalesce until the bucket fills or
the linger window closes (the window is waited out even when the queue
goes empty — that IS the linger cost at low concurrency); the batch
pads to the next power-of-two bucket; service time comes from a
per-bucket model fitted to the trace's own measured device times
(``device_ms_by_bucket``), linear in the bucket via weighted least
squares — measured, not assumed.

Objective: ``p99_ms * (1 + padding_waste_fraction)`` — the issue's
"p99 x padding waste" made non-degenerate (a raw product is 0 whenever
waste is 0, which would declare any zero-waste config perfect no
matter its latency; the ``1 +`` keeps p99 in charge and prices waste
as a multiplicative penalty on it).

The winning config ships as a tuning report the server loads via
``ModelServer(tuning_report=...)``; the default config is always a
grid point, so the tuned objective is <= the default's BY CONSTRUCTION
on the replayed trace.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional, Sequence, Tuple

from deeplearning4j_tpu.serving.batcher import next_bucket

SCHEMA_VERSION = 1

#: default search grids: ladder caps (powers of two) and linger windows
#: (ms). 0.0 window = launch as soon as the device is free.
DEFAULT_MAX_BATCH_GRID = (4, 8, 16, 32, 64, 128)
DEFAULT_WINDOW_GRID_MS = (0.0, 0.5, 1.0, 2.0, 4.0, 8.0)


# ------------------------------------------------------------- trace intake
def extract_trace(results: dict) -> dict:
    """Pull the replayable trace out of a ``serve_bench --out`` file:
    arrivals, the measured per-bucket device times, and the config the
    bench ran with (the 'default' the search must beat)."""
    trace = results.get("trace")
    if not trace or not trace.get("arrivals"):
        raise ValueError(
            "no 'trace' in results — rerun scripts/serve_bench.py "
            "(its --out report embeds the arrival trace)")
    metrics = results.get("metrics") or {}
    return {
        "arrivals": [(float(t), int(r)) for t, r in trace["arrivals"]],
        "concurrency": trace.get("concurrency"),
        "device_ms_by_bucket": {
            int(k): float(v) for k, v in
            (metrics.get("device_ms_by_bucket") or {}).items()},
        "bucket_counts": {
            int(k): int(v) for k, v in
            (metrics.get("batch_size_hist") or {}).items()},
        "default": {"max_batch": int(results.get("max_batch", 1024)),
                    "batch_window_ms": float(
                        results.get("batch_window_ms", 2.0))},
    }


# ----------------------------------------------------------- service model
def fit_service_model(device_ms_by_bucket: dict,
                      bucket_counts: Optional[dict] = None
                      ) -> Tuple[float, float]:
    """Fit ``service_ms(bucket) = a + c * bucket`` to the measured
    per-bucket mean device times, weighted by how often each bucket
    executed. The linear form matches the weight-streaming serving
    regime (fixed dispatch + per-row compute); with a single observed
    bucket the split is fixed at 80% dispatch / 20% per-row, the
    conservative end (discourages the search from assuming big buckets
    are nearly free)."""
    pts = sorted(device_ms_by_bucket.items())
    if not pts:
        raise ValueError("empty device_ms_by_bucket — nothing to fit")
    if len(pts) == 1:
        b, ms = pts[0]
        return 0.8 * ms, 0.2 * ms / max(1, b)
    w = [float((bucket_counts or {}).get(b, 1)) for b, _ in pts]
    sw = sum(w)
    mb = sum(wi * b for wi, (b, _) in zip(w, pts)) / sw
    mm = sum(wi * ms for wi, (_, ms) in zip(w, pts)) / sw
    var = sum(wi * (b - mb) ** 2 for wi, (b, _) in zip(w, pts))
    if var <= 0:
        b, ms = pts[0]
        return 0.8 * ms, 0.2 * ms / max(1, b)
    c = sum(wi * (b - mb) * (ms - mm) for wi, (b, ms) in zip(w, pts)) / var
    c = max(c, 0.0)  # per-row cost can't be negative
    a = max(mm - c * mb, 0.0)
    if a == 0.0 and c == 0.0:
        a = mm
    return a, c


# --------------------------------------------------------------- simulator
def simulate(arrivals: Sequence[Tuple[float, int]], *, max_batch: int,
             batch_window_ms: float, min_batch: int, service_ms) -> dict:
    """Replay *arrivals* (sorted ``(t_seconds, rows)``) through the
    dispatcher semantics under one (max_batch, window) config.
    ``service_ms(bucket)`` models the device forward. Returns p99/mean
    latency and the padding waste the config would have produced."""
    evts = sorted((float(t), min(int(r), max_batch)) for t, r in arrivals)
    n = len(evts)
    window_s = batch_window_ms / 1000.0
    lat: List[float] = []
    real = padded = 0
    t_free = 0.0
    i = 0
    while i < n:
        t_start = max(t_free, evts[i][0])
        rows = 0
        j = i
        # everything already queued at t_start that fits
        while j < n and evts[j][0] <= t_start and rows + evts[j][1] <= max_batch:
            rows += evts[j][1]
            j += 1
        launch = t_start
        if window_s > 0 and rows < max_batch:
            # linger: coalesce stragglers until the bucket fills or the
            # window closes; the window is waited out even if no one
            # else arrives (batcher.py _gather_locked cond.wait)
            deadline = t_start + window_s
            launch = deadline
            while j < n and evts[j][0] <= deadline \
                    and rows + evts[j][1] <= max_batch:
                rows += evts[j][1]
                if rows >= max_batch:
                    launch = evts[j][0]  # full bucket launches NOW
                j += 1
        bucket = next_bucket(rows, max_batch, min_batch)
        done = launch + service_ms(bucket) / 1000.0
        for k in range(i, j):
            lat.append(done - evts[k][0])
        real += rows
        padded += bucket - rows
        t_free = done
        i = j
    s = sorted(lat)

    def pct(q):
        return 1000.0 * s[min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))]

    waste = padded / (real + padded) if real + padded else 0.0
    return {
        "p50_ms": round(pct(0.50), 3),
        "p99_ms": round(pct(0.99), 3),
        "mean_ms": round(1000.0 * sum(s) / len(s), 3) if s else 0.0,
        "padding_waste_fraction": round(waste, 4),
    }


def objective(p99_ms: float, waste_fraction: float) -> float:
    """p99 x (1 + padding waste): tail latency priced up by the share
    of device work the schedule burned on filler rows."""
    return p99_ms * (1.0 + waste_fraction)


# ------------------------------------------------------------------ search
def autotune(results: dict, *, min_batch: int = 2,
             max_batch_grid: Optional[Sequence[int]] = None,
             window_grid_ms: Optional[Sequence[float]] = None) -> dict:
    """Grid-search (max_batch, batch_window_ms) over the replayed trace;
    returns the tuning report (schema'd dict) with the tuned config,
    the default config's numbers, and the full scored grid."""
    trace = extract_trace(results)
    arrivals = trace["arrivals"]
    a, c = fit_service_model(trace["device_ms_by_bucket"],
                             trace["bucket_counts"])

    def svc(bucket: int) -> float:
        return a + c * bucket

    default = trace["default"]
    caps = list(max_batch_grid or DEFAULT_MAX_BATCH_GRID)
    windows = list(window_grid_ms or DEFAULT_WINDOW_GRID_MS)
    if default["max_batch"] not in caps:
        caps.append(default["max_batch"])
    if default["batch_window_ms"] not in windows:
        windows.append(default["batch_window_ms"])

    grid = []
    for cap in sorted(set(caps)):
        for win in sorted(set(windows)):
            sim = simulate(arrivals, max_batch=int(cap),
                           batch_window_ms=float(win), min_batch=min_batch,
                           service_ms=svc)
            grid.append({"max_batch": int(cap),
                         "batch_window_ms": float(win), **sim,
                         "objective": round(objective(
                             sim["p99_ms"],
                             sim["padding_waste_fraction"]), 3)})
    # deterministic winner: lowest objective, then smallest knobs
    grid.sort(key=lambda g: (g["objective"], g["max_batch"],
                             g["batch_window_ms"]))
    tuned = grid[0]
    default_row = next(
        g for g in grid
        if g["max_batch"] == default["max_batch"]
        and g["batch_window_ms"] == default["batch_window_ms"])
    return {
        "schema_version": SCHEMA_VERSION,
        "config": "serving_autotune",
        "created_unix": round(time.time(), 3),
        "trace": {"requests": len(arrivals),
                  "span_s": round(arrivals[-1][0] - arrivals[0][0], 3)
                  if len(arrivals) > 1 else 0.0,
                  "concurrency": trace.get("concurrency")},
        "service_model_ms": {"dispatch": round(a, 4),
                             "per_row": round(c, 6),
                             "observed_buckets":
                                 {str(k): v for k, v in sorted(
                                     trace["device_ms_by_bucket"].items())}},
        "default": default_row,
        "tuned": tuned,
        # <= 1.0 by construction (the default is a grid point)
        "objective_ratio": round(
            tuned["objective"] / default_row["objective"], 4)
        if default_row["objective"] else 1.0,
        "grid": grid[:16],
    }


def load_tuned(report) -> dict:
    """The (max_batch, batch_window_ms) a server should boot with, from
    a tuning report dict or a path to one. Raises on a report that
    doesn't carry a tuned config (fail loud — a server silently falling
    back to defaults would defeat the receipt)."""
    if isinstance(report, (str, os.PathLike)):
        with open(report) as f:
            report = json.load(f)
    tuned = report.get("tuned") or {}
    if "max_batch" not in tuned or "batch_window_ms" not in tuned:
        raise ValueError("tuning report has no tuned config "
                         "(expected report['tuned']['max_batch'/"
                         "'batch_window_ms'])")
    return {"max_batch": int(tuned["max_batch"]),
            "batch_window_ms": float(tuned["batch_window_ms"])}
