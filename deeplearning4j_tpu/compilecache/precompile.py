"""Build-time AOT compilation: run every executable a deploy will need
BEFORE the deploy, persisting into the compilation cache.

Two surfaces:

- :func:`precompile_serving` — the serving bucket ladder, through the
  SAME seam the server warms lazily (``ReplicaSet.warm`` over a
  ``ModelServer`` built with ``warmup=False``): identical forward,
  identical shapes, identical HLO, so the cache entries written here
  are byte-for-byte the ones a later boot looks up. Covers replicated,
  bf16-shadow and mesh tensor-parallel forwards because it goes through
  the server's own construction path rather than re-deriving it.
- :func:`precompile_fit` — both nets' jitted train step via explicit
  AOT ``step.lower(*args).compile()`` on zero-filled arrays of the
  training batch shape. Lowering + compiling never executes the step
  (params are untouched; donation only applies at execution), and the
  AOT path routes through the same ``compile_or_get_cached`` as jit, so
  a later ``fit`` of the same shapes boots warm.

Both return manifest entry dicts; ``scripts/precompile.py`` assembles
them into the schema'd artifact (compilecache.manifest) next to the
cache dir.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.compilecache import cache as _cache
from deeplearning4j_tpu.compilecache import manifest as _manifest


def precompile_serving(net, *, cache_dir: str, max_batch: int = 1024,
                       min_batch: Optional[int] = None,
                       input_shapes=None, compute_dtype=None,
                       replicas: int = 1, mesh=None,
                       model_axis: str = "model", data_axis=None,
                       tp_rules=None) -> dict:
    """AOT-compile the serving bucket ladder into *cache_dir* and return
    the manifest ``serving`` entry. Raises ValueError when the row
    shapes can't be inferred and ``input_shapes`` wasn't given."""
    from deeplearning4j_tpu.serving.batcher import bucket_ladder
    from deeplearning4j_tpu.serving.server import ModelServer

    _cache.configure(cache_dir)
    server = ModelServer(net, port=0, max_batch=max_batch, warmup=False,
                         input_shapes=input_shapes,
                         compute_dtype=compute_dtype, replicas=replicas,
                         mesh=mesh, model_axis=model_axis,
                         data_axis=data_axis, tp_rules=tp_rules)
    try:
        shapes = server._infer_row_shapes()
        if shapes is None:
            raise ValueError(
                "cannot infer serving row shapes from the model "
                "configuration — pass input_shapes explicitly")
        mb = server._batcher
        server._fleet.warm(shapes)
        return {
            "row_shapes": [list(s) for s in shapes],
            "ladder": bucket_ladder(mb.min_batch, mb.max_batch),
            "max_batch": int(mb.max_batch),
            "min_batch": int(mb.min_batch),
            "compute_dtype": server.serving_compute_dtype,
            "mesh_axes": _manifest._mesh_axes(mesh),
        }
    finally:
        server._fleet.stop()


def precompile_fit(net, *, cache_dir: str, batch: int = 32,
                   input_shapes=None) -> dict:
    """AOT-compile the net's train step for one training batch shape
    into *cache_dir* (``lower().compile()``, no execution) and return
    the manifest ``train`` entry. Works for MultiLayerNetwork and
    ComputationGraph with feed-forward output heads; ``input_shapes``
    overrides per-input row shapes when inference can't derive them."""
    import jax
    import jax.numpy as jnp

    _cache.configure(cache_dir)
    if net.params is None:
        net.init()
    step = net._build_train_step()
    row_shapes = input_shapes or _infer_row_shapes(net)
    if row_shapes is None:
        raise ValueError(
            "cannot infer training input shapes — pass input_shapes")
    is_graph = hasattr(net.conf, "network_inputs")
    it = jnp.asarray(0, jnp.int32)
    rng = jax.random.PRNGKey(0)
    if is_graph:
        inputs = {name: jnp.zeros((batch,) + tuple(s), jnp.float32)
                  for name, s in zip(net.conf.network_inputs, row_shapes)}
        labels = [jnp.zeros((batch, n), jnp.float32)
                  for n in _output_widths(net)]
        lowered = step.lower(net.params, net.state, net.opt_state, it,
                             inputs, labels, {}, None, rng)
    else:
        x = jnp.zeros((batch,) + tuple(row_shapes[0]), jnp.float32)
        y = jnp.zeros((batch, _output_widths(net)[0]), jnp.float32)
        lowered = step.lower(net.params, net.state, net.opt_state, it,
                             x, y, None, None, rng)
    lowered.compile()
    return {
        "kind": "train_step",
        "net": type(net).__name__,
        "batch": int(batch),
        "row_shapes": [list(s) for s in row_shapes],
    }


def _infer_row_shapes(net) -> Optional[list]:
    """Per-input row shapes via the server's inference (one code path
    for both precompile surfaces — serving and fit must agree on what
    the model eats)."""
    from deeplearning4j_tpu.serving.server import ModelServer
    probe = ModelServer.__new__(ModelServer)
    probe.input_shapes = None
    probe.net = net
    probe._is_graph = hasattr(net, "conf") and hasattr(
        net.conf, "network_inputs")
    return probe._infer_row_shapes()


def _output_widths(net) -> List[int]:
    """n_out of every output head (label widths for the dummy batch)."""
    if hasattr(net.conf, "network_outputs"):
        return [int(net._resolved_confs[name].n_out)
                for name in net.conf.network_outputs]
    return [int(net._resolved_confs[-1].n_out)]
