"""Persistent XLA compilation cache as a first-class runtime knob.

jax has had an on-disk compilation cache for years
(``jax_compilation_cache_dir``), but as shipped it is a config flag
buried behind two more flags that silently disable it for small
programs: entries are skipped below a 1-second compile-time floor and a
minimum serialized size. A CI-sized model compiles in milliseconds, so
the stock defaults cache *nothing* and every boot stays cold. This
module owns the knob:

- :func:`configure` points jax at a cache dir AND zeroes both floors,
  so every executable — tiny CI ladder buckets included — persists.
- The dir resolves from an explicit argument or the
  ``DL4J_TPU_COMPILE_CACHE`` env var; reconfiguration mid-process works
  (jax latches its cache handle on first use; we reset it).
- Hit/miss traffic is observable: jax emits
  ``/jax/compilation_cache/cache_hits`` / ``cache_misses`` monitoring
  events only while a cache is active, and observability.metrics folds
  them into ``dl4j_xla_cache_hits_total`` / ``_misses_total`` plus the
  RunReport ``xla_cache_hits``/``xla_cache_misses`` fields. A warm boot
  of an unchanged server therefore *proves* itself: misses == 0 and the
  run's ``compile_count`` ~ 0 (cache hits skip ``backend_compile``, the
  event the compile counter rides).

The cache key is the HLO module + compile options, so it is shared by
lazy jit, warm-up ladders and AOT ``lower().compile()`` — precompiling
at build time (compilecache.precompile) and serving later from the
same dir hit the identical entries.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

#: env var consulted by :func:`ensure_configured` (fit / resilient_fit /
#: serving all call it) — set it and every run in the process shares one
#: persistent cache without touching call sites
ENV_VAR = "DL4J_TPU_COMPILE_CACHE"

_lock = threading.Lock()
_configured: Optional[str] = None


def cache_dir() -> Optional[str]:
    """The active persistent-cache directory, or None when cold."""
    return _configured


def configure(path: Optional[str] = None) -> Optional[str]:
    """Activate the persistent compilation cache at *path* (or at
    ``$DL4J_TPU_COMPILE_CACHE`` when *path* is None). Idempotent per
    dir; switching dirs mid-process resets jax's latched cache handle
    so the new dir takes effect. Returns the active dir (None when
    neither source names one — the knob stays off, nothing changes).

    Also installs the compile/cache-event listener so hit/miss counters
    are live even before the first ``install_runtime_metrics`` call.
    """
    global _configured
    resolved = path or os.environ.get(ENV_VAR) or None
    if not resolved:
        return _configured
    resolved = os.path.abspath(resolved)
    with _lock:
        if _configured == resolved:
            return _configured
        os.makedirs(resolved, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", resolved)
        # stock floors (1s compile time, min serialized bytes) exist to
        # keep huge fleets from caching trivia; here they would skip
        # every CI-sized program — zero both so the cache is honest at
        # any model size
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        try:
            # jax latches its cache handle on first compile; without a
            # reset, configuring after any jit ran would silently keep
            # the old (or no) cache
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
        from deeplearning4j_tpu.observability.metrics import \
            _ensure_compile_listener
        _ensure_compile_listener()
        _configured = resolved
    return _configured


def deactivate() -> None:
    """Turn the persistent cache back off: unset the dir, restore jax's
    stock floors, and drop the latched cache handle so later compiles
    run cold again. Process-global, like :func:`configure` — meant for
    tear-down (tests, embedding hosts), not the serving hot path."""
    global _configured
    with _lock:
        if _configured is None:
            return
        import jax
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
        _configured = None


def ensure_configured() -> Optional[str]:
    """Env-driven activation: a no-op unless ``DL4J_TPU_COMPILE_CACHE``
    is set (or :func:`configure` already ran). The fit loops, the
    supervisor and the server call this at run start, so exporting one
    env var turns on warm boots across the whole stack."""
    return configure(None)
