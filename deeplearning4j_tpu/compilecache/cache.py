"""Persistent XLA compilation cache as a first-class runtime knob.

jax has had an on-disk compilation cache for years
(``jax_compilation_cache_dir``), but as shipped it is a config flag
buried behind two more flags that silently disable it for small
programs: entries are skipped below a 1-second compile-time floor and a
minimum serialized size. A CI-sized model compiles in milliseconds, so
the stock defaults cache *nothing* and every boot stays cold. This
module owns the knob:

- :func:`configure` points jax at a cache dir AND zeroes both floors,
  so every executable — tiny CI ladder buckets included — persists.
- The dir resolves from an explicit argument or the
  ``DL4J_TPU_COMPILE_CACHE`` env var; reconfiguration mid-process works
  (jax latches its cache handle on first use; we reset it).
- Hit/miss traffic is observable: jax emits
  ``/jax/compilation_cache/cache_hits`` / ``cache_misses`` monitoring
  events only while a cache is active, and observability.metrics folds
  them into ``dl4j_xla_cache_hits_total`` / ``_misses_total`` plus the
  RunReport ``xla_cache_hits``/``xla_cache_misses`` fields. A warm boot
  of an unchanged server therefore *proves* itself: misses == 0 and the
  run's ``compile_count`` ~ 0 (cache hits skip ``backend_compile``, the
  event the compile counter rides).

The cache key is the HLO module + compile options, so it is shared by
lazy jit, warm-up ladders and AOT ``lower().compile()`` — precompiling
at build time (compilecache.precompile) and serving later from the
same dir hit the identical entries.

**Shared-directory backend (cross-host).** The same dir can be a
mounted NFS/GCS-style path shared by a whole serving fleet: host A's
warm-up compiles become host B's cache hits, so only the FIRST host of
a fleet ever pays a fresh compile (measured by
``scripts/crosshost_serve_bench.py``; SERVING.md "Cross-host
federation"). What makes the dir safe to share:

- jax's file-system cache already publishes each entry via its own
  tmp+rename, so a reader never sees a partial executable;
- :func:`configure` stamps the dir with an atomically-published
  ``dl4j_cache_meta.json`` marker (:func:`atomic_publish`: unique tmp
  name per process/thread + ``os.replace``) recording schema and first
  writer — N processes configuring the same dir concurrently race
  benignly: every writer replaces a COMPLETE file, the first valid
  marker is kept, and no ``*.tmp`` turds survive;
- re-configure is idempotent per resolved dir, cross-process included
  (pinned by ``tests/test_crosshost_serving.py``).
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from typing import Optional

#: env var consulted by :func:`ensure_configured` (fit / resilient_fit /
#: serving all call it) — set it and every run in the process shares one
#: persistent cache without touching call sites
ENV_VAR = "DL4J_TPU_COMPILE_CACHE"

#: the shared-dir marker :func:`configure` publishes atomically — its
#: presence (and valid JSON-ness) is the "this dir is a dl4j compile
#: cache" handshake between hosts sharing the mount
META_NAME = "dl4j_cache_meta.json"
META_SCHEMA_VERSION = 1

_lock = threading.Lock()
_configured: Optional[str] = None


def atomic_publish(directory: str, name: str, payload: dict) -> str:
    """Write ``payload`` as JSON to ``directory/name`` via the
    tmp+rename protocol shared dirs require: serialize to a tmp file
    whose name is unique per process/thread (pid + uuid — two hosts on
    one NFS mount never collide), fsync, then ``os.replace`` onto the
    final name. A concurrent reader sees either the old complete file
    or the new complete file, never a torn write; a concurrent writer
    just wins or loses the whole rename. Returns the final path."""
    final = os.path.join(directory, name)
    tmp = os.path.join(
        directory, f".{name}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp")
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
    finally:
        # a crash between write and replace must not leave tmp litter
        # for the next configure to trip over
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return final


def shared_meta(path: Optional[str] = None) -> Optional[dict]:
    """The shared-dir marker of ``path`` (default: the active cache
    dir), or None when the dir is unstamped/unreadable."""
    d = path or _configured
    if not d:
        return None
    try:
        with open(os.path.join(d, META_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _stamp_shared_dir(resolved: str) -> None:
    """Publish the ``dl4j_cache_meta.json`` marker if the dir doesn't
    already carry a valid one. Concurrent-configure safe: losers of the
    publish race overwrite with an equivalent complete marker; an
    existing valid marker is left untouched (idempotent re-configure —
    the first writer's identity stays recorded); a corrupt marker is
    replaced. Never raises — a read-only shared mount still serves
    hits, it just stays unstamped."""
    if shared_meta(resolved) is not None:
        return
    try:
        from deeplearning4j_tpu.observability.distributed import \
            get_identity
        created_by = get_identity().tag
    except Exception:
        created_by = f"pid-{os.getpid()}"
    import time
    try:
        atomic_publish(resolved, META_NAME, {
            "schema": META_SCHEMA_VERSION,
            "created_unix": round(time.time(), 3),
            "created_by": created_by,
        })
    except OSError:
        pass


def cache_dir() -> Optional[str]:
    """The active persistent-cache directory, or None when cold."""
    return _configured


def configure(path: Optional[str] = None) -> Optional[str]:
    """Activate the persistent compilation cache at *path* (or at
    ``$DL4J_TPU_COMPILE_CACHE`` when *path* is None). Idempotent per
    dir; switching dirs mid-process resets jax's latched cache handle
    so the new dir takes effect. Returns the active dir (None when
    neither source names one — the knob stays off, nothing changes).

    Also installs the compile/cache-event listener so hit/miss counters
    are live even before the first ``install_runtime_metrics`` call.
    """
    global _configured
    resolved = path or os.environ.get(ENV_VAR) or None
    if not resolved:
        return _configured
    resolved = os.path.abspath(resolved)
    with _lock:
        if _configured == resolved:
            return _configured
        os.makedirs(resolved, exist_ok=True)
        _stamp_shared_dir(resolved)
        import jax
        jax.config.update("jax_compilation_cache_dir", resolved)
        # stock floors (1s compile time, min serialized bytes) exist to
        # keep huge fleets from caching trivia; here they would skip
        # every CI-sized program — zero both so the cache is honest at
        # any model size
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        try:
            # jax latches its cache handle on first compile; without a
            # reset, configuring after any jit ran would silently keep
            # the old (or no) cache
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
        from deeplearning4j_tpu.observability.metrics import \
            _ensure_compile_listener
        _ensure_compile_listener()
        _configured = resolved
    return _configured


def deactivate() -> None:
    """Turn the persistent cache back off: unset the dir, restore jax's
    stock floors, and drop the latched cache handle so later compiles
    run cold again. Process-global, like :func:`configure` — meant for
    tear-down (tests, embedding hosts), not the serving hot path."""
    global _configured
    with _lock:
        if _configured is None:
            return
        import jax
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
        _configured = None


def ensure_configured() -> Optional[str]:
    """Env-driven activation: a no-op unless ``DL4J_TPU_COMPILE_CACHE``
    is set (or :func:`configure` already ran). The fit loops, the
    supervisor and the server call this at run start, so exporting one
    env var turns on warm boots across the whole stack."""
    return configure(None)
