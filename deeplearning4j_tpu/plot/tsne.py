"""t-SNE, device-accelerated.

Parity: deeplearning4j-core plot/Tsne.java and plot/BarnesHutTsne.java. The
reference uses Barnes-Hut quadtrees to make the O(N^2) gradient tractable
on CPU; on TPU the exact O(N^2) pairwise computation is a pair of [N, N]
matmuls that the MXU eats for typical embedding sizes (N <= ~20k), so the
exact algorithm IS the fast path. ``BarnesHutTsne`` is the same API
(capability parity) running the exact kernel; binary-search perplexity
calibration matches the reference's.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _pairwise_sq_dists(x):
    s = jnp.sum(x * x, axis=1)
    return s[:, None] - 2.0 * x @ x.T + s[None, :]


def _cond_probs_for_perplexity(d2, perplexity, iters=50):
    """Binary-search per-point precision beta so each row of P hits the
    target perplexity (Tsne.java's hBeta search parity), vectorized."""
    n = d2.shape[0]
    log_u = jnp.log(perplexity)

    def entropy_and_p(beta):
        p = jnp.exp(-d2 * beta[:, None])
        p = p * (1.0 - jnp.eye(n))
        sum_p = jnp.maximum(p.sum(axis=1, keepdims=True), 1e-12)
        h = jnp.log(sum_p[:, 0]) + beta * (d2 * p).sum(axis=1) / sum_p[:, 0]
        return h, p / sum_p

    def body(_, carry):
        beta, lo, hi = carry
        h, _ = entropy_and_p(beta)
        too_high = h > log_u          # entropy too high -> increase beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2,
                         jnp.where(jnp.isinf(lo), beta / 2, (lo + hi) / 2))
        return beta, lo, hi

    beta0 = jnp.ones((n,))
    lo0 = jnp.full((n,), -jnp.inf)
    hi0 = jnp.full((n,), jnp.inf)
    beta, _, _ = jax.lax.fori_loop(0, iters, body, (beta0, lo0, hi0))
    _, p = entropy_and_p(beta)
    return p


@partial(jax.jit, static_argnums=())
def _tsne_grad(y, P):
    n = y.shape[0]
    d2 = _pairwise_sq_dists(y)
    num = 1.0 / (1.0 + d2)
    num = num * (1.0 - jnp.eye(n))
    Q = jnp.maximum(num / jnp.maximum(num.sum(), 1e-12), 1e-12)
    PQ = (P - Q) * num
    grad = 4.0 * ((jnp.diag(PQ.sum(axis=1)) - PQ) @ y)
    kl = jnp.sum(P * jnp.log(jnp.maximum(P, 1e-12) / Q))
    return grad, kl


class Tsne:
    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, max_iter: int = 500,
                 early_exaggeration: float = 12.0, momentum: float = 0.8,
                 seed: int = 0):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.early_exaggeration = early_exaggeration
        self.momentum = momentum
        self.seed = seed
        self.kl = None

    def fit_transform(self, x) -> np.ndarray:
        x = jnp.asarray(x, jnp.float32)
        n = x.shape[0]
        d2 = _pairwise_sq_dists(x)
        P = _cond_probs_for_perplexity(
            d2, min(self.perplexity, max((n - 1) / 3.0, 2.0)))
        P = (P + P.T) / (2.0 * n)
        P = jnp.maximum(P, 1e-12)

        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(rng.standard_normal((n, self.n_components)) * 1e-2,
                        jnp.float32)
        v = jnp.zeros_like(y)
        exag_until = min(250, self.max_iter // 2)
        for it in range(self.max_iter):
            p_eff = P * self.early_exaggeration if it < exag_until else P
            grad, kl = _tsne_grad(y, p_eff)
            mom = 0.5 if it < exag_until else self.momentum
            v = mom * v - self.learning_rate * grad
            y = y + v
            y = y - y.mean(axis=0)
        self.kl = float(kl)
        return np.asarray(y)


class BarnesHutTsne(Tsne):
    """Reference-name alias (BarnesHutTsne.java parity): same API; on TPU
    the exact pairwise kernel is the fast path, so no quadtree is needed."""

    def __init__(self, *args, theta: float = 0.5, **kw):
        super().__init__(*args, **kw)
        self.theta = theta  # accepted for API parity; exact kernel ignores it
