"""t-SNE, device-accelerated.

Parity: deeplearning4j-core plot/Tsne.java and plot/BarnesHutTsne.java. The
reference uses Barnes-Hut quadtrees to make the O(N^2) gradient tractable
on CPU; on TPU the exact O(N^2) pairwise computation is a pair of [N, N]
matmuls that the MXU eats for typical embedding sizes (N <= ~20k), so the
exact algorithm IS the fast path there. ``BarnesHutTsne`` is the REAL
Barnes-Hut algorithm (sparse kNN similarities + SPTree repulsion with
accuracy knob theta — clustering/sptree.py) for the reference's large-N
CPU regime; binary-search perplexity calibration matches the reference's.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _pairwise_sq_dists(x):
    s = jnp.sum(x * x, axis=1)
    return s[:, None] - 2.0 * x @ x.T + s[None, :]


def _cond_probs_for_perplexity(d2, perplexity, iters=50):
    """Binary-search per-point precision beta so each row of P hits the
    target perplexity (Tsne.java's hBeta search parity), vectorized."""
    n = d2.shape[0]
    log_u = jnp.log(perplexity)

    def entropy_and_p(beta):
        p = jnp.exp(-d2 * beta[:, None])
        p = p * (1.0 - jnp.eye(n))
        sum_p = jnp.maximum(p.sum(axis=1, keepdims=True), 1e-12)
        h = jnp.log(sum_p[:, 0]) + beta * (d2 * p).sum(axis=1) / sum_p[:, 0]
        return h, p / sum_p

    def body(_, carry):
        beta, lo, hi = carry
        h, _ = entropy_and_p(beta)
        too_high = h > log_u          # entropy too high -> increase beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2,
                         jnp.where(jnp.isinf(lo), beta / 2, (lo + hi) / 2))
        return beta, lo, hi

    beta0 = jnp.ones((n,))
    lo0 = jnp.full((n,), -jnp.inf)
    hi0 = jnp.full((n,), jnp.inf)
    beta, _, _ = jax.lax.fori_loop(0, iters, body, (beta0, lo0, hi0))
    _, p = entropy_and_p(beta)
    return p


@partial(jax.jit, static_argnums=())
def _tsne_grad(y, P):
    n = y.shape[0]
    d2 = _pairwise_sq_dists(y)
    num = 1.0 / (1.0 + d2)
    num = num * (1.0 - jnp.eye(n))
    Q = jnp.maximum(num / jnp.maximum(num.sum(), 1e-12), 1e-12)
    PQ = (P - Q) * num
    grad = 4.0 * ((jnp.diag(PQ.sum(axis=1)) - PQ) @ y)
    kl = jnp.sum(P * jnp.log(jnp.maximum(P, 1e-12) / Q))
    return grad, kl


class Tsne:
    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, max_iter: int = 500,
                 early_exaggeration: float = 12.0, momentum: float = 0.8,
                 seed: int = 0):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.early_exaggeration = early_exaggeration
        self.momentum = momentum
        self.seed = seed
        self.kl = None

    def fit_transform(self, x) -> np.ndarray:
        x = jnp.asarray(x, jnp.float32)
        n = x.shape[0]
        d2 = _pairwise_sq_dists(x)
        P = _cond_probs_for_perplexity(
            d2, min(self.perplexity, max((n - 1) / 3.0, 2.0)))
        P = (P + P.T) / (2.0 * n)
        P = jnp.maximum(P, 1e-12)

        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(rng.standard_normal((n, self.n_components)) * 1e-2,
                        jnp.float32)
        v = jnp.zeros_like(y)
        exag_until = min(250, self.max_iter // 2)
        for it in range(self.max_iter):
            p_eff = P * self.early_exaggeration if it < exag_until else P
            grad, kl = _tsne_grad(y, p_eff)
            mom = 0.5 if it < exag_until else self.momentum
            v = mom * v - self.learning_rate * grad
            y = y + v
            y = y - y.mean(axis=0)
        self.kl = float(kl)
        return np.asarray(y)


class BarnesHutTsne(Tsne):
    """Barnes-Hut t-SNE (BarnesHutTsne.java parity): sparse kNN input
    similarities + an SPTree (clustering/sptree.py) approximating the
    repulsive forces with accuracy knob ``theta``. ``theta=0`` falls back
    to the exact device kernel (which on TPU is also the FAST path for
    N up to ~20k — the tree pays off in the reference's large-N CPU
    regime)."""

    def __init__(self, *args, theta: float = 0.5, **kw):
        super().__init__(*args, **kw)
        self.theta = theta

    def fit_transform(self, x) -> np.ndarray:
        if self.theta <= 0.0:
            return super().fit_transform(x)
        from deeplearning4j_tpu.clustering.sptree import SPTree

        x = np.asarray(x, np.float64)
        n = x.shape[0]
        perp = min(self.perplexity, max((n - 1) / 3.0, 2.0))
        k = min(n - 1, max(int(3 * perp), 3))

        # sparse input similarities over the kNN graph (the reference
        # builds these with a VPTree). Distances are computed in ROW
        # BLOCKS so memory stays O(block * N), not O(N^2) — the whole
        # point of this path is the large-N regime
        nbr = np.empty((n, k), np.int64)
        d2 = np.empty((n, k), np.float64)
        sq = np.sum(x * x, axis=1)
        block = max(1, min(n, int(2 ** 22 // max(n, 1)) or 1))
        for s0 in range(0, n, block):
            s1 = min(s0 + block, n)
            db = (sq[s0:s1, None] - 2.0 * x[s0:s1] @ x.T + sq[None, :])
            db[np.arange(s1 - s0), np.arange(s0, s1)] = np.inf
            nb = np.argpartition(db, k, axis=1)[:, :k]
            nbr[s0:s1] = nb
            d2[s0:s1] = np.take_along_axis(db, nb, axis=1)
        p_cond = self._knn_cond_probs(d2, perp)                  # [n, k]

        # symmetrize the sparse matrix: P = (P + P^T) / (2n)
        rows = np.repeat(np.arange(n), k)
        cols = nbr.reshape(-1)
        vals = p_cond.reshape(-1)
        sym = {}
        for r, c, v in zip(rows, cols, vals):
            sym[(r, c)] = sym.get((r, c), 0.0) + v
            sym[(c, r)] = sym.get((c, r), 0.0) + v
        keys = np.asarray(list(sym.keys()), np.int64)
        pv = np.asarray(list(sym.values()), np.float64) / (2.0 * n)
        ri, ci = keys[:, 0], keys[:, 1]

        rng = np.random.default_rng(self.seed)
        y = rng.standard_normal((n, self.n_components)) * 1e-2
        v = np.zeros_like(y)
        gains = np.ones_like(y)  # adaptive per-dim gains (the reference's
        # Tsne gradient machinery; stabilizes the sparse path without the
        # exact kernel's implicit damping)
        exag_until = min(250, self.max_iter // 2)
        for it in range(self.max_iter):
            exag = self.early_exaggeration if it < exag_until else 1.0
            # attractive: sum_j p_ij q_ij (y_i - y_j) over the sparse graph
            diff = y[ri] - y[ci]
            q_num = 1.0 / (1.0 + np.sum(diff * diff, axis=1))
            w = (exag * pv * q_num)[:, None] * diff
            attr = np.zeros_like(y)
            np.add.at(attr, ri, w)
            # repulsive via the SPTree
            tree = SPTree(y)
            rep = np.zeros_like(y)
            z = 0.0
            for i in range(n):
                neg, zi = tree.non_edge_forces(y[i], i, self.theta)
                rep[i] = neg
                z += zi
            grad = 4.0 * (attr - rep / max(z, 1e-12))
            mom = 0.5 if it < exag_until else self.momentum
            gains = np.where(np.sign(grad) != np.sign(v),
                             gains + 0.2, gains * 0.8)
            gains = np.maximum(gains, 0.01)
            v = mom * v - self.learning_rate * (gains * grad)
            y = y + v
            y = y - y.mean(axis=0)
        # sparse KL over the kNN support (Q normalized by the tree's Z):
        # the base-class contract is a float kl after fit
        q = np.maximum(q_num / max(z, 1e-12), 1e-12)
        self.kl = float(np.sum(pv * np.log(np.maximum(pv, 1e-12) / q)))
        return np.asarray(y, np.float32)

    @staticmethod
    def _knn_cond_probs(d2, perplexity, iters=50):
        """Per-row beta binary search over the kNN distances only
        (BarnesHutTsne.java's sparse hBeta analogue)."""
        n, k = d2.shape
        log_u = np.log(perplexity)
        beta = np.ones(n)
        lo = np.full(n, -np.inf)
        hi = np.full(n, np.inf)
        for _ in range(iters):
            p = np.exp(-d2 * beta[:, None])
            s = np.maximum(p.sum(axis=1), 1e-12)
            h = np.log(s) + beta * (d2 * p).sum(axis=1) / s
            too_high = h > log_u
            lo = np.where(too_high, beta, lo)
            hi = np.where(too_high, hi, beta)
            beta = np.where(np.isinf(hi), beta * 2,
                            np.where(np.isinf(lo), beta / 2, (lo + hi) / 2))
        p = np.exp(-d2 * beta[:, None])
        return p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)
