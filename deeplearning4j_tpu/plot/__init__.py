"""Dimensionality-reduction / plotting utilities (parity:
deeplearning4j-core plot/ — Tsne.java, BarnesHutTsne.java)."""

from deeplearning4j_tpu.plot.tsne import Tsne, BarnesHutTsne
