"""datapipe — the checkpointable sharded input-pipeline subsystem.

The DataVec tier of this stack (see ``DATA.md``): composable record
pipelines — sources → map/filter/normalize → windowed shuffle →
deterministic shard → (bucket-)batch → prefetch — presented to the
trainers as an ordinary ``DataSetIterator``, with O(1) checkpointable
state (``Pipeline.state_dict()``) that the resilience supervisor threads
through its checkpoints so ``resilient_fit`` resumes mid-epoch
bit-identically from any shuffled/streaming source.

Typical use::

    from deeplearning4j_tpu import datapipe

    pipe = (datapipe.from_csv("train.csv", label_index=0, num_classes=10)
            .shuffle(window=4096, seed=7)
            .shard()                       # process-aware for multihost
            .normalize()
            .batch(128, drop_last=True)
            .prefetch(2))
    net.resilient_fit(pipe, checkpoint_dir="ckpts", epochs=5)
"""

from __future__ import annotations

from deeplearning4j_tpu.datapipe.core import (Pipeline, PipelineStats, Stage,
                                              decode_record, decode_state_value,
                                              encode_record, encode_state_value)
from deeplearning4j_tpu.datapipe.prefetch import PrefetchStage
from deeplearning4j_tpu.datapipe.sources import (ArraySource, CSVSource,
                                                 LineSource, RecordSource)
from deeplearning4j_tpu.datapipe.stages import (BatchStage, BucketBatchStage,
                                                FilterStage, MapStage,
                                                NormalizeStage,
                                                NormalizerStats, ShardStage,
                                                ShuffleStage)
from deeplearning4j_tpu.datapipe.tokens import (CharTokenizer, TokenizeStage,
                                                WindowStage)

__all__ = [
    "Pipeline", "PipelineStats", "Stage",
    "ArraySource", "CSVSource", "LineSource", "RecordSource",
    "MapStage", "FilterStage", "NormalizeStage", "NormalizerStats",
    "ShuffleStage", "ShardStage", "BatchStage", "BucketBatchStage",
    "PrefetchStage",
    "CharTokenizer", "TokenizeStage", "WindowStage",
    "from_arrays", "from_csv", "from_lines", "from_records", "from_text",
    "encode_record", "decode_record",
    "encode_state_value", "decode_state_value",
]


def from_arrays(features, labels=None, *, name: str = "datapipe") -> Pipeline:
    """Pipeline over in-memory arrays: records are ``(features[i],
    labels[i])`` rows."""
    return Pipeline(ArraySource(features, labels), name=name)


def from_csv(path: str, *, skip_lines: int = 0, delimiter: str = ",",
             label_index=None, num_classes=None,
             name: str = "datapipe") -> Pipeline:
    """Streaming pipeline over a numeric CSV file (DataVec reader
    conventions — see ``datasets/records.py``)."""
    return Pipeline(CSVSource(path, skip_lines=skip_lines,
                              delimiter=delimiter, label_index=label_index,
                              num_classes=num_classes), name=name)


def from_lines(path: str, *, parse=None, skip_lines: int = 0,
               name: str = "datapipe") -> Pipeline:
    """Streaming pipeline over a text file, one record per line."""
    return Pipeline(LineSource(path, parse=parse, skip_lines=skip_lines),
                    name=name)


def from_records(record_reader, *, name: str = "datapipe") -> Pipeline:
    """Pipeline over any ``records.py``-style reader (``.records()``) or
    a plain sequence of record tuples."""
    return Pipeline(RecordSource(record_reader), name=name)


def from_text(texts, *, name: str = "datapipe") -> Pipeline:
    """Pipeline over text documents (a single string or a sequence of
    strings), one ``(text,)`` record per document — the head of the
    ``tokenize → window → bucket_batch`` language-model pipeline::

        tok = datapipe.CharTokenizer.fit(corpus)
        pipe = (datapipe.from_text(corpus)
                .tokenize(tok)
                .window(64, vocab_size=tok.vocab_size)
                .bucket_batch(8))
    """
    if isinstance(texts, str):
        texts = [texts]
    return Pipeline(RecordSource([(t,) for t in texts]), name=name)
