"""Elastic datapipe resharding: remap a checkpointed shard cursor onto a
different fleet size with no record dropped or doubled.

A pipeline checkpoint (``Pipeline.state_dict()``) bakes the fleet size
into its shard stage: ``(n_old, i_old, k)`` where ``k`` is the number of
upstream records the shard stage has scanned this epoch. Resuming that
state on a fleet of a different size would replay the wrong residue
class — :meth:`ShardStage._load_state` refuses it. This module rewrites
the state for the new fleet.

The coverage rule
-----------------

Shard ``i`` of ``n`` owns upstream positions ``j`` with
``j % n == i``. From the checkpointed cursor:

- ``r = ceil((k - i_old) / n_old)`` — records the old shard has
  *emitted* this epoch (its owned positions below ``k``);
- ``b`` — records sitting unconsumed in buffers *downstream* of the
  shard stage (partial batch buffers, in-flight map records), which the
  remap discards;
- ``d = r - b`` — records this shard actually delivered to training;
- ``G = d * n_old`` — the **global low-water mark**: assuming the fleet
  ran in lockstep (every shard at the same consumed depth ``d``, which
  is exactly what supervisor checkpoints at batch boundaries give),
  every upstream position ``< G`` was consumed by exactly one old
  shard, and no position ``>= G`` was consumed by anyone.

The remapped state starts the new shard ``(n_new, i_new)`` at
``k = G`` with the source cursor rewound to ``G``. The new fleet's
shards then cover exactly the positions ``>= G`` in their (new) residue
classes: disjoint and covering by the same modulo argument as a fresh
epoch, so **no record is dropped or doubled** — records that were
buffered-but-unconsumed at the crash are re-read under the new cut.

Constraints (violations raise, naming the stage):

- exactly one shard stage in the chain;
- no shuffle stage anywhere across the shard boundary — a shuffle
  window holds an unbounded sample of positions whose membership cannot
  be re-cut for a different modulus without dropping or doubling;
- no filter between source and shard (a filtered stream breaks the
  source-position ↔ shard-scan-count equality the rewind relies on);
- the source must expose a ``pos`` cursor (all built-in sources do).

An identity remap (same ``(n, i)``) returns the state untouched,
buffers included — resuming on the same fleet stays bit-exact.
"""

from __future__ import annotations

import copy

__all__ = ["remap_state", "remap_for", "shard_position",
           "low_water_mark"]

# stages that may sit downstream of the shard: state key holding their
# buffered-record payload (cleared by the remap, counted into b)
_DOWNSTREAM_BUFFERS = {"batch": "buf", "map": "inflight"}
# stages safe on either side with no positional state of their own
_STATELESS = {"filter", "normalize"}


def _chain(state: dict) -> list:
    """Stage state dicts tail-first (downstream → source)."""
    out, node = [], state["stage"]
    while node is not None:
        out.append(node)
        node = node.get("upstream")
    return out


def shard_position(state: dict):
    """The checkpoint's shard cursor as ``(n, i, k)``, or None when the
    pipeline has no shard stage (single-host run)."""
    for node in _chain(state):
        if node.get("kind") == "shard":
            if "n" not in node:
                return None
            return (int(node["n"]), int(node["i"]), int(node["k"]))
    return None


def low_water_mark(state: dict):
    """The global record index ``G`` at which an elastic remap of this
    checkpointed state would re-cut the stream (see the coverage rule in
    the module docstring: ``G = (r - b) * n_old`` — every upstream
    position ``< G`` was consumed by exactly one old shard, nothing
    ``>= G`` by anyone). None when the pipeline has no shard stage.

    This is the tiling oracle chaos drivers assert against: a resumed
    fleet of ANY size must consume exactly the positions ``[G, N)``."""
    pos = shard_position(state)
    if pos is None:
        return None
    n_old, i_old, k_old = pos
    chain = _chain(state)
    shard = next(n for n in chain if n.get("kind") == "shard")
    b = sum(_buffered_count(n) for n in chain[:chain.index(shard)])
    r = max(0, -(-(k_old - i_old) // n_old))   # ceil over ints
    return max(0, (r - b)) * n_old


def _buffered_count(node: dict) -> int:
    kind = node.get("kind")
    if kind == "bucket_batch":
        return sum(len(v) for v in node.get("bufs", {}).values())
    key = _DOWNSTREAM_BUFFERS.get(kind)
    return len(node.get(key, ())) if key else 0


def _clear_buffers(node: dict):
    kind = node.get("kind")
    if kind == "bucket_batch":
        node["bufs"] = {}
    key = _DOWNSTREAM_BUFFERS.get(kind)
    if key and key in node:
        node[key] = []


def remap_state(state: dict, num_shards: int, index: int) -> dict:
    """A new ``Pipeline.state_dict()`` for shard ``index`` of
    ``num_shards``, derived from a checkpoint saved under any other
    fleet size (see the module docstring for the coverage rule). The
    input dict is not mutated."""
    num_shards, index = int(num_shards), int(index)
    if not 0 <= index < num_shards:
        raise ValueError(f"shard index {index} out of range "
                         f"[0, {num_shards})")
    state = copy.deepcopy(state)
    chain = _chain(state)

    shard_nodes = [n for n in chain if n.get("kind") == "shard"]
    if len(shard_nodes) != 1:
        raise ValueError(
            f"elastic remap needs exactly one shard stage in the "
            f"pipeline, found {len(shard_nodes)}")
    shard = shard_nodes[0]
    if "n" not in shard:
        raise ValueError(
            "shard state predates the elastic format (no (n, i) "
            "recorded) — it cannot be safely remapped; resume on the "
            "original fleet size once to refresh the checkpoint")
    n_old, i_old, k_old = (int(shard["n"]), int(shard["i"]),
                           int(shard["k"]))
    if (n_old, i_old) == (num_shards, index):
        return state                      # identity: buffers kept, bit-exact

    at = chain.index(shard)
    downstream, upstream = chain[:at], chain[at + 1:]

    for node in chain:
        if node.get("kind") == "shuffle":
            raise ValueError(
                "elastic remap cannot re-cut a stream through a shuffle "
                "stage: its window holds records whose shard membership "
                "changes with the modulus. Re-shard without shuffle, or "
                "accept an epoch-boundary resume")

    # b: records the old shard emitted that training never consumed —
    # discarded here, re-read by the new cut
    b = 0
    for node in downstream:
        kind = node.get("kind")
        if kind in _DOWNSTREAM_BUFFERS or kind == "bucket_batch":
            b += _buffered_count(node)
            _clear_buffers(node)
        elif kind not in _STATELESS and _buffered_count(node):
            raise ValueError(f"elastic remap does not know how to drain "
                             f"stage kind {kind!r} downstream of shard")

    # upstream of the shard: only 1:1 stages, ending at a pos-cursor
    # source; anything the rewind cannot reason about raises
    if not upstream:
        raise ValueError("shard stage has no upstream source")
    for node in upstream[:-1]:
        kind = node.get("kind")
        if kind == "map":
            node["inflight"] = []         # re-read under the new cut
        elif kind not in _STATELESS:
            raise ValueError(
                f"elastic remap requires 1:1 stages between source and "
                f"shard, found {kind!r}")
    source = upstream[-1]
    if "pos" not in source:
        raise ValueError(
            f"source stage {source.get('kind')!r} has no 'pos' cursor — "
            "elastic remap cannot rewind it")

    r = max(0, -(-(k_old - i_old) // n_old))   # ceil over ints
    if b > r:
        raise ValueError(
            f"inconsistent checkpoint: {b} records buffered downstream "
            f"but the shard only emitted {r}")
    low_water = (r - b) * n_old

    shard["n"], shard["i"], shard["k"] = num_shards, index, low_water
    source["pos"] = low_water
    return state


def remap_for(pipeline, state: dict) -> dict:
    """``remap_state`` with ``(num_shards, index)`` taken from the live
    pipeline's own shard stage — the relaunch-side entry point: build
    the pipeline for the NEW fleet, then load the OLD checkpoint through
    this."""
    from deeplearning4j_tpu.datapipe.stages import ShardStage

    shards = [s for s in pipeline.tail.chain()
              if isinstance(s, ShardStage)]
    if len(shards) != 1:
        raise ValueError(
            f"elastic remap needs exactly one shard stage in the "
            f"pipeline, found {len(shards)}")
    return remap_state(state, shards[0].num_shards, shards[0].index)
