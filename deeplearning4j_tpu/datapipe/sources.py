"""Pipeline sources: in-memory arrays, line/CSV files, record readers.

Every source keeps its read position in ``self._pos`` (an instance
attribute mutated between yields), so ``state_dict()`` at any point is a
single integer — O(1) in the dataset. File sources restore by reopening
the file and skipping ``pos`` records: O(pos) restore work, O(1) state.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu.datapipe.core import Stage

__all__ = ["ArraySource", "CSVSource", "LineSource", "RecordSource"]


class ArraySource(Stage):
    """Records from in-memory arrays: yields ``(features[i], labels[i])``
    (or ``(features[i],)`` when unlabeled)."""

    name = "array_source"

    def __init__(self, features, labels=None):
        super().__init__()
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)
        if self.labels is not None and \
                self.labels.shape[0] != self.features.shape[0]:
            raise ValueError("features/labels row mismatch: "
                             f"{self.features.shape[0]} vs "
                             f"{self.labels.shape[0]}")
        self._pos = 0

    def __len__(self):
        return self.features.shape[0]

    def __iter__(self):
        while self._pos < self.features.shape[0]:
            i = self._pos
            rec = (self.features[i],) if self.labels is None \
                else (self.features[i], self.labels[i])
            self._pos = i + 1
            self.records_out += 1
            yield rec

    def on_epoch(self, epoch: int):
        super().on_epoch(epoch)
        self._pos = 0

    def _state(self):
        return {"pos": self._pos}

    def _load_state(self, state):
        self._pos = int(state["pos"])


class LineSource(Stage):
    """Records from a text file, one per line: yields ``(parse(line),)``
    (default parse: the stripped line as a numpy unicode scalar). The
    streaming-source archetype: only the line cursor is state."""

    name = "line_source"

    def __init__(self, path: str, parse: Optional[Callable] = None,
                 skip_lines: int = 0):
        super().__init__()
        self.path = path
        self.parse = parse
        self.skip_lines = skip_lines
        self._pos = 0            # records emitted this epoch

    def _lines(self):
        with open(self.path) as f:
            for i, line in enumerate(f):
                if i < self.skip_lines:
                    continue
                line = line.rstrip("\n")
                if line:
                    yield line

    def __iter__(self):
        for i, line in enumerate(self._lines()):
            if i < self._pos:    # skip already-emitted records on resume
                continue
            rec = (np.str_(line),) if self.parse is None \
                else (self.parse(line),)
            self._pos = i + 1
            self.records_out += 1
            yield rec

    def on_epoch(self, epoch: int):
        super().on_epoch(epoch)
        self._pos = 0

    def _state(self):
        return {"pos": self._pos}

    def _load_state(self, state):
        self._pos = int(state["pos"])


class CSVSource(Stage):
    """Streaming numeric-CSV records via the DataVec-parity reader
    conventions (``datasets/records.py``): ``label_index`` splits the
    label column out (one-hot when ``num_classes``), yielding
    ``(features, label)``; without it, ``(row,)``. Rows stream from disk
    — the file is never materialized, and resume state is one cursor."""

    name = "csv_source"

    def __init__(self, path: str, skip_lines: int = 0, delimiter: str = ",",
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None):
        super().__init__()
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.label_index = label_index
        self.num_classes = num_classes
        self._pos = 0

    def _rows(self):
        from deeplearning4j_tpu.datasets.records import CSVRecordReader
        reader = CSVRecordReader(self.path, skip_lines=self.skip_lines,
                                 delimiter=self.delimiter)
        for row in reader.iter_records():
            yield np.asarray(row, np.float32)

    def _to_record(self, row: np.ndarray):
        li = self.label_index
        if li is None:
            return (row,)
        feat = np.delete(row, li)
        if self.num_classes is not None:
            y = np.zeros(self.num_classes, np.float32)
            y[int(row[li])] = 1.0
        else:
            y = row[li:li + 1]
        return (feat, y)

    def __iter__(self):
        for i, row in enumerate(self._rows()):
            if i < self._pos:
                continue
            rec = self._to_record(row)
            self._pos = i + 1
            self.records_out += 1
            yield rec

    def on_epoch(self, epoch: int):
        super().on_epoch(epoch)
        self._pos = 0

    def _state(self):
        return {"pos": self._pos}

    def _load_state(self, state):
        self._pos = int(state["pos"])


class RecordSource(Stage):
    """Records from any ``records.py``-style reader (an object with a
    ``.records()`` list method) or a plain sequence of records. Rows
    load once on first iteration; only the cursor is checkpoint state,
    so restores stay O(1) in payload."""

    name = "record_source"

    def __init__(self, record_reader):
        super().__init__()
        self._reader = record_reader
        self._rows = None
        self._pos = 0

    def _materialize(self):
        if self._rows is None:
            rows = self._reader.records() \
                if hasattr(self._reader, "records") else self._reader
            self._rows = [tuple(r) if isinstance(r, tuple)
                          else (np.asarray(r, np.float32),) for r in rows]
        return self._rows

    def __len__(self):
        return len(self._materialize())

    def __iter__(self):
        rows = self._materialize()
        while self._pos < len(rows):
            rec = rows[self._pos]
            self._pos += 1
            self.records_out += 1
            yield rec

    def on_epoch(self, epoch: int):
        super().on_epoch(epoch)
        self._pos = 0

    def _state(self):
        return {"pos": self._pos}

    def _load_state(self, state):
        self._pos = int(state["pos"])
