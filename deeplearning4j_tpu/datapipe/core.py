"""Checkpointable record-pipeline core — the DataVec tier of this stack.

The reference delegates all ingestion to the external DataVec project
(SURVEY.md §0: the RecordReaderDataSetIterator bridge); the TensorFlow
system paper (arXiv:1605.08695 §4.2) makes the input pipeline a
first-class runtime subsystem because a starved accelerator is the most
expensive way to idle. This package is that subsystem: composable
sources → transforms → shuffle → shard → batch → prefetch, with one
capability the ad-hoc iterators in ``datasets/iterator.py`` cannot
offer: **O(1) checkpointable pipeline state**.

``Pipeline.state_dict()`` captures, per stage, everything needed to
resume the record stream exactly where it stopped — epoch counter,
source position, shuffle RNG + window contents, partial batch buffers,
prefetched-but-unconsumed batches — in a JSON-serializable dict whose
size is bounded by the configured window/buffer sizes, never by the
dataset. The resilience supervisor threads this state through its
checkpoints (``meta.json``), so ``resilient_fit`` over a shuffled or
streaming source resumes mid-epoch bit-identically: no record is
replayed, none is skipped (previously it checkpointed model/optimizer
state only, silently breaking the PR 2 bit-identity guarantee for any
non-materialized source).

Stage protocol (``Stage``): ``__iter__`` yields the *remainder of the
current epoch* from the stage's instance state — all iteration state
lives in instance attributes mutated between yields, never in generator
locals, which is what makes mid-stream ``state_dict()`` consistent.
``on_epoch(e)`` advances to epoch ``e`` (position 0, per-epoch RNGs
re-derived from ``seed + e``); ``reset()`` rewinds to epoch 0.

Records are tuples of numpy arrays / scalars / None — usually
``(features,)`` or ``(features, label)``; the batch stage collates them
into :class:`~deeplearning4j_tpu.datasets.dataset.DataSet` minibatches.
"""

from __future__ import annotations

import base64
import io
import threading
import time
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.observability.trace import get_tracer

__all__ = ["Stage", "Pipeline", "PipelineStats", "encode_state_value",
           "decode_state_value", "encode_record", "decode_record"]

_END = object()

STATE_VERSION = 1


# ---------------------------------------------------------------------------
# state serialization: everything in a state_dict must survive json.dump
# (checkpoint state lands inside the checkpoint's meta.json)
# ---------------------------------------------------------------------------

def _encode_array(a: np.ndarray) -> dict:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    return {"__nd__": base64.b64encode(buf.getvalue()).decode("ascii")}


def _decode_array(d: dict) -> np.ndarray:
    return np.load(io.BytesIO(base64.b64decode(d["__nd__"])),
                   allow_pickle=False)


def encode_state_value(v):
    """Recursively encode a state value (numpy arrays -> base64 .npy,
    DataSet/MultiDataSet -> tagged field lists) into JSON-safe types."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, np.ndarray):
        return _encode_array(v)
    if isinstance(v, (np.integer, np.floating)):
        return v.item()
    if isinstance(v, DataSet):
        return {"__ds__": [encode_state_value(x) for x in (
            v.features, v.labels, v.features_mask, v.labels_mask)]}
    if isinstance(v, MultiDataSet):
        return {"__mds__": [[encode_state_value(x) for x in part]
                            for part in (v.features, v.labels,
                                         v.features_masks, v.labels_masks)]}
    if isinstance(v, (list, tuple)):
        return [encode_state_value(x) for x in v]
    if isinstance(v, dict):
        return {str(k): encode_state_value(x) for k, x in v.items()}
    # device arrays and other array-likes round-trip through numpy
    return _encode_array(np.asarray(v))


def decode_state_value(v):
    if isinstance(v, dict):
        if "__nd__" in v:
            return _decode_array(v)
        if "__ds__" in v:
            f, l, fm, lm = [decode_state_value(x) for x in v["__ds__"]]
            return DataSet(f, l, fm, lm)
        if "__mds__" in v:
            f, l, fm, lm = [[decode_state_value(x) for x in part]
                            for part in v["__mds__"]]
            return MultiDataSet(f, l, fm, lm)
        return {k: decode_state_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [decode_state_value(x) for x in v]
    return v


def encode_record(rec) -> list:
    """A record is a tuple of arrays/scalars/None."""
    return [encode_state_value(x) for x in rec]


def decode_record(enc) -> tuple:
    return tuple(decode_state_value(x) for x in enc)


def _rng_state(rng: np.random.Generator) -> dict:
    return encode_state_value(rng.bit_generator.state)


def _restore_rng(state: dict) -> np.random.Generator:
    rng = np.random.default_rng(0)
    rng.bit_generator.state = decode_state_value(state)
    return rng


# ---------------------------------------------------------------------------
# stage base
# ---------------------------------------------------------------------------

class Stage:
    """One pipeline stage. Subclasses set ``name`` and implement
    ``__iter__`` (yield the remainder of the current epoch, keeping ALL
    iteration state in instance attributes), plus ``_state()`` /
    ``_load_state()`` for their own checkpointable fields."""

    name = "stage"

    def __init__(self, upstream: Optional["Stage"] = None):
        self.upstream = upstream
        self.records_out = 0       # lifetime counter (metrics)
        self.seconds = 0.0         # own processing time (see _clock)

    # ------------------------------------------------------------- iteration
    def __iter__(self) -> Iterator:
        raise NotImplementedError

    def on_epoch(self, epoch: int):
        """Advance to the start of ``epoch`` (position 0; per-epoch RNGs
        re-derive from ``seed + epoch``)."""
        if self.upstream is not None:
            self.upstream.on_epoch(epoch)

    def reset(self):
        """Rewind to the start of epoch 0 (the DataSetIterator replay
        contract)."""
        self.on_epoch(0)

    # ------------------------------------------------------------ checkpoint
    def state_dict(self) -> dict:
        s = {"kind": self.name}
        s.update(self._state())
        if self.upstream is not None:
            s["upstream"] = self.upstream.state_dict()
        return s

    def load_state_dict(self, state: dict):
        if state.get("kind") != self.name:
            raise ValueError(
                f"pipeline state mismatch: stage {self.name!r} cannot load "
                f"state saved by {state.get('kind')!r} — the restoring "
                "pipeline must be built with the same stage sequence")
        self._load_state(state)
        if self.upstream is not None:
            if "upstream" not in state:
                raise ValueError(f"stage {self.name!r}: state has no "
                                 "upstream entry")
            self.upstream.load_state_dict(state["upstream"])

    def _state(self) -> dict:
        return {}

    def _load_state(self, state: dict):
        pass

    # --------------------------------------------------------------- helpers
    def chain(self) -> List["Stage"]:
        """Source-first list of stages ending at this one."""
        out = [] if self.upstream is None else self.upstream.chain()
        out.append(self)
        return out

    def _clock(self, t0: float):
        """Accumulate own processing time (call with a perf_counter
        start). Used at batch/fill granularity — never per record on the
        hot path."""
        self.seconds += time.perf_counter() - t0


# ---------------------------------------------------------------------------
# pipeline-level stats (the /metrics surface)
# ---------------------------------------------------------------------------

class PipelineStats:
    """Throughput/stall counters for one pipeline, bridged into the
    observability registry as a render-time collector (the ServingStats/
    ResilienceStats pattern: these counters stay the source of truth)."""

    def __init__(self, pipeline: "Pipeline"):
        self._pipeline = pipeline
        self._lock = threading.Lock()
        self.records_total = 0
        self.batches_total = 0
        self.wait_seconds = 0.0      # consumer time blocked pulling batches
        self.records_per_second = 0.0
        self._window_t0 = None
        self._window_records = 0
        self._active_t0 = None       # first pull of the current run
        self._registry = None
        self._collector = None

    def note_batch(self, n_records: int, wait_s: float):
        with self._lock:
            now = time.perf_counter()
            self.records_total += n_records
            self.batches_total += 1
            self.wait_seconds += wait_s
            if self._active_t0 is None:
                self._active_t0 = now
            if self._window_t0 is None:
                self._window_t0 = now
            self._window_records += n_records
            dt = now - self._window_t0
            if dt >= 0.5:            # recent-rate window
                self.records_per_second = self._window_records / dt
                self._window_t0, self._window_records = now, 0

    def stall_fraction(self) -> float:
        """Fraction of the consumer's wall-clock since the first pull
        spent blocked waiting for data (the accelerator-starvation
        number)."""
        with self._lock:
            if self._active_t0 is None:
                return 0.0
            wall = time.perf_counter() - self._active_t0
            if wall <= 0:
                return 0.0
            return min(1.0, self.wait_seconds / wall)

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "records_total": self.records_total,
                "batches_total": self.batches_total,
                "wait_seconds": self.wait_seconds,
                "records_per_second": self.records_per_second,
            }
        out["stall_fraction"] = self.stall_fraction()
        out["queue_depth"] = self._pipeline.queue_depth()
        return out

    # ------------------------------------------------ registry bridge
    def metric_families(self, labels=None):
        from deeplearning4j_tpu.observability.metrics import MetricFamily
        L = dict(labels or {})
        snap = self.snapshot()
        fams = [
            MetricFamily("dl4j_datapipe_records_total", "counter",
                         "Records emitted by the pipeline").add(
                             snap["records_total"], L),
            MetricFamily("dl4j_datapipe_batches_total", "counter",
                         "Batches emitted by the pipeline").add(
                             snap["batches_total"], L),
            MetricFamily("dl4j_datapipe_records_per_second", "gauge",
                         "Recent pipeline throughput (records/sec)").add(
                             snap["records_per_second"], L),
            MetricFamily("dl4j_datapipe_stall_fraction", "gauge",
                         "Fraction of consumer wall-clock blocked on "
                         "data (0 = never starved)").add(
                             snap["stall_fraction"], L),
            MetricFamily("dl4j_datapipe_queue_depth", "gauge",
                         "Prefetched batches ready for the consumer").add(
                             snap["queue_depth"], L),
        ]
        rec = MetricFamily("dl4j_datapipe_stage_records_total", "counter",
                           "Records emitted per stage")
        sec = MetricFamily("dl4j_datapipe_stage_seconds_total", "counter",
                           "Own processing seconds per stage (batch/fill "
                           "granularity)")
        pad = MetricFamily("dl4j_datapipe_padding_waste_fraction", "gauge",
                           "Padded timestep cells over total cells "
                           "collated by pad-to-bucket stages")
        padc = MetricFamily("dl4j_datapipe_padded_cells_total", "counter",
                            "Filler timestep cells emitted by "
                            "pad-to-bucket stages")
        for i, st in enumerate(self._pipeline.tail.chain()):
            sl = {**L, "stage": f"{i}:{st.name}"}
            rec.add(st.records_out, sl)
            sec.add(round(st.seconds, 6), sl)
            real = getattr(st, "cells_real", None)
            padded = getattr(st, "cells_padded", None)
            if real is not None and padded is not None and real + padded:
                pad.add(round(padded / (real + padded), 4), sl)
                padc.add(padded, sl)
        fams.extend([rec, sec])
        if pad.samples:
            fams.extend([pad, padc])
        return fams

    def attach_to_registry(self, registry=None, *, labels=None):
        from deeplearning4j_tpu.observability.metrics import get_registry
        self.detach_from_registry()
        reg = registry if registry is not None else get_registry()

        def _collect():
            return self.metric_families(labels)

        reg.register_collector(_collect)
        self._registry, self._collector = reg, _collect
        return reg

    def detach_from_registry(self):
        if self._registry is not None:
            self._registry.unregister_collector(self._collector)
            self._registry = self._collector = None


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------

class Pipeline(DataSetIterator):
    """A composed record pipeline, presented as a ``DataSetIterator``.

    ``__iter__`` yields the remainder of the *current* epoch and then
    auto-advances the epoch counter (``auto_epochs = True`` — the fit
    loops know not to ``reset()`` between epochs, so per-epoch shuffle
    orders derive from ``seed + epoch``). ``reset()`` rewinds the whole
    pipeline to epoch 0. ``stream(epochs)`` is the flat multi-epoch
    stream the resilience supervisor consumes.

    Build with the fluent constructors in ``datapipe/__init__``::

        pipe = (datapipe.from_arrays(x, y)
                .shuffle(window=512, seed=7)
                .shard()                    # process-aware by default
                .batch(128, drop_last=True)
                .prefetch(2))
        net.fit(pipe, epochs=3)             # or net.resilient_fit(pipe, ...)

    Checkpointing: ``state_dict()`` / ``load_state_dict()`` cover the
    epoch counter and every stage's position/RNG/window/buffer state; the
    restoring pipeline must be built with the same stage sequence over
    the same data.
    """

    auto_epochs = True

    def __init__(self, tail: Stage, name: str = "datapipe"):
        self.tail = tail
        self.name = name
        self.epoch = 0
        self.stats = PipelineStats(self)

    # ------------------------------------------------------------- builders
    def _extend(self, stage: Stage) -> "Pipeline":
        p = Pipeline(stage, name=self.name)
        p.epoch = self.epoch
        return p

    def map(self, fn, workers: int = 0) -> "Pipeline":
        """Apply ``fn(record) -> record``. ``workers > 0`` runs ``fn`` on
        a thread pool with in-order emission (``fn`` must be
        deterministic: in-flight records are re-run on restore)."""
        from deeplearning4j_tpu.datapipe.stages import MapStage
        return self._extend(MapStage(self.tail, fn, workers=workers))

    def filter(self, pred) -> "Pipeline":
        from deeplearning4j_tpu.datapipe.stages import FilterStage
        return self._extend(FilterStage(self.tail, pred))

    def normalize(self, stats=None, eps: float = 1e-8) -> "Pipeline":
        """Standardize record features with :class:`NormalizerStats`
        (``stats=None`` fits mean/std by streaming the pipeline built so
        far once, then rewinding it)."""
        from deeplearning4j_tpu.datapipe.stages import (NormalizerStats,
                                                        NormalizeStage)
        if stats is None:
            stats = NormalizerStats.fit(self, eps=eps)
        return self._extend(NormalizeStage(self.tail, stats))

    def tokenize(self, tokenizer) -> "Pipeline":
        """Map text records to token-id records with a
        ``tokens.CharTokenizer``-style tokenizer (``.encode(str)``)."""
        from deeplearning4j_tpu.datapipe.tokens import TokenizeStage
        return self._extend(TokenizeStage(self.tail, tokenizer))

    def window(self, size: int, stride: Optional[int] = None,
               vocab_size: Optional[int] = None) -> "Pipeline":
        """Cut token-stream records into next-token training windows of
        up to ``size`` steps (``(x_onehot, y_onehot)`` pairs when
        ``vocab_size`` is given) — feed into ``bucket_batch`` for the
        padded-length ladder."""
        from deeplearning4j_tpu.datapipe.tokens import WindowStage
        return self._extend(WindowStage(self.tail, size, stride=stride,
                                        vocab_size=vocab_size))

    def shuffle(self, window: int = 1024, seed: int = 0) -> "Pipeline":
        """Windowed shuffle with an explicit seeded RNG (per-epoch RNG =
        ``seed + epoch``). Checkpoint state includes the RNG state and
        the window contents — O(window), not O(dataset)."""
        from deeplearning4j_tpu.datapipe.stages import ShuffleStage
        return self._extend(ShuffleStage(self.tail, window=window, seed=seed))

    def shard(self, num_shards: Optional[int] = None,
              index: Optional[int] = None) -> "Pipeline":
        """Deterministic ``record_i -> shard (i % num_shards)`` partition:
        shards are disjoint and their union covers every record, for any
        dataset size. Defaults are mesh/process-aware
        (``jax.process_count()`` / ``jax.process_index()``), so a
        multihost run drops one ``.shard()`` in and each host reads its
        own disjoint slice."""
        from deeplearning4j_tpu.datapipe.stages import ShardStage
        if num_shards is None or index is None:
            import jax
            num_shards = jax.process_count() if num_shards is None \
                else num_shards
            index = jax.process_index() if index is None else index
        return self._extend(ShardStage(self.tail, num_shards, index))

    def batch(self, batch_size: int, drop_last: bool = False) -> "Pipeline":
        from deeplearning4j_tpu.datapipe.stages import BatchStage
        return self._extend(BatchStage(self.tail, batch_size,
                                       drop_last=drop_last))

    def bucket_batch(self, batch_size: int, ladder=None,
                     drop_last: bool = False) -> "Pipeline":
        """Pad-to-bucket batching for variable-length sequence records:
        each ``[t, f]`` record pads to the next bucket length (the
        serving tier's power-of-two ladder idea) and batches only with
        records of the same bucket, bounding the XLA compile cache while
        masks keep the math exact."""
        from deeplearning4j_tpu.datapipe.stages import BucketBatchStage
        return self._extend(BucketBatchStage(self.tail, batch_size,
                                             ladder=ladder,
                                             drop_last=drop_last))

    def prefetch(self, depth: int = 2) -> "Pipeline":
        """Parallel worker prefetch: a background thread pulls batches
        ahead of the consumer (layers under the fit loops' own
        ``AsyncDataSetIterator`` / ``DevicePrefetchIterator`` wrappers).
        Prefetched-but-unconsumed batches are part of the checkpoint
        state, so resume neither replays nor drops them."""
        from deeplearning4j_tpu.datapipe.prefetch import PrefetchStage
        return self._extend(PrefetchStage(self.tail, depth=depth))

    # ------------------------------------------------------------ iteration
    def __iter__(self):
        tracer = get_tracer()
        self.stats.attach_to_registry(labels={"pipeline": self.name})
        stream = iter(self.tail)
        while True:
            t0 = time.perf_counter()
            with tracer.span("data_wait", pipeline=self.name):
                ds = next(stream, _END)
            wait = time.perf_counter() - t0
            if ds is _END:
                break
            ds = self._as_dataset(ds)
            self.stats.note_batch(ds.num_examples, wait)
            yield ds
        self._advance_epoch()

    def stream(self, epochs: int):
        """Flat stream of batches until ``self.epoch`` reaches
        ``epochs`` — continues mid-epoch from restored state, then runs
        the remaining full epochs."""
        while self.epoch < epochs:
            before = self.epoch
            for ds in self:
                yield ds
            if self.epoch == before:    # defensive: __iter__ must advance
                raise RuntimeError("pipeline epoch failed to advance")

    def _advance_epoch(self):
        self.epoch += 1
        self.tail.on_epoch(self.epoch)

    @staticmethod
    def _as_dataset(item):
        if isinstance(item, (DataSet, MultiDataSet)):
            return item
        # a bare record tuple at the tail (no batch stage): 1-record sets
        if isinstance(item, tuple):
            parts = list(item) + [None] * (4 - len(item))
            return DataSet(*[None if p is None else np.asarray(p)[None]
                             for p in parts[:4]])
        raise TypeError(f"pipeline tail yielded {type(item)!r}; add a "
                        ".batch(...) stage or yield DataSet objects")

    # --------------------------------------------------- iterator protocol
    def reset(self):
        """Rewind the WHOLE pipeline to epoch 0 (replay-deterministic:
        per-epoch orders re-derive from ``seed + epoch``)."""
        self.epoch = 0
        self.tail.reset()

    @property
    def batch_size(self):
        for st in reversed(self.tail.chain()):
            b = getattr(st, "batch_size", None)
            if b is not None:
                return b
        return None

    def queue_depth(self) -> int:
        for st in reversed(self.tail.chain()):
            d = getattr(st, "buffered", None)
            if d is not None:
                return d()
        return 0

    # ----------------------------------------------------------- checkpoint
    def state_dict(self) -> dict:
        """O(1)-in-dataset-size resumable state: epoch + per-stage
        position/RNG/window/buffer. JSON-serializable (numpy payloads are
        base64 ``.npy``); lands inside the resilience checkpoint's
        ``meta.json``."""
        return {"version": STATE_VERSION, "name": self.name,
                "epoch": self.epoch, "stage": self.tail.state_dict()}

    def load_state_dict(self, state: dict):
        if state.get("version") != STATE_VERSION:
            raise ValueError(
                f"unsupported pipeline state version {state.get('version')}")
        self.epoch = int(state["epoch"])
        self.tail.load_state_dict(state["stage"])

    def close(self):
        """Stop any prefetch workers and detach metrics collectors."""
        for st in self.tail.chain():
            stop = getattr(st, "stop", None)
            if stop is not None:
                stop()
        self.stats.detach_from_registry()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
