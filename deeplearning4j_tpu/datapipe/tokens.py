"""Token pipeline stages for the transformer workload tier (ROADMAP
item 1): tokenize → window → ``bucket_batch``.

A language-model pipeline is text records in, next-token training pairs
out: ``TokenizeStage`` maps text to int token ids, ``WindowStage`` slices
each token stream into (possibly overlapping) windows and emits
``(x_onehot [t, V], y_onehot [t, V])`` next-token records whose variable
tail lengths are exactly what ``BucketBatchStage``'s padded-length ladder
exists for. Both stages follow the datapipe core contract — iteration
state in instance attributes, O(window) checkpoint state — so a
``resilient_fit`` over a token pipeline resumes mid-epoch bit-identically
like every other source.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datapipe.core import (Stage, decode_state_value,
                                              encode_state_value)

__all__ = ["CharTokenizer", "TokenizeStage", "WindowStage"]


class CharTokenizer:
    """Character-level tokenizer: vocabulary = sorted distinct characters
    of the fitted corpus. Stateless after construction; ``state_dict``
    round-trips through JSON so a pipeline checkpoint can pin the exact
    id mapping it trained with."""

    def __init__(self, vocab: str):
        self.vocab = "".join(sorted(set(vocab)))
        self._stoi = {c: i for i, c in enumerate(self.vocab)}

    @classmethod
    def fit(cls, text: str) -> "CharTokenizer":
        return cls(text)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def encode(self, text: str) -> np.ndarray:
        """Unknown characters map to id 0 (the reference's UNK-to-first
        convention for its word-vector lookup tables)."""
        stoi = self._stoi
        return np.asarray([stoi.get(c, 0) for c in text], np.int32)

    def decode(self, ids) -> str:
        v = self.vocab
        return "".join(v[int(i) % len(v)] for i in np.asarray(ids).ravel())

    def one_hot(self, ids) -> np.ndarray:
        out = np.zeros((len(ids), self.vocab_size), np.float32)
        out[np.arange(len(ids)), np.asarray(ids, np.int64)] = 1.0
        return out

    def state_dict(self) -> dict:
        return {"vocab": self.vocab}

    @classmethod
    def from_state_dict(cls, state: dict) -> "CharTokenizer":
        return cls(state["vocab"])


class TokenizeStage(Stage):
    """Map text records ``(str, ...)`` to token-id records
    ``([t] int32, ...)``. Stateless beyond the upstream cursor (the map
    is deterministic)."""

    name = "tokenize"

    def __init__(self, upstream: Stage, tokenizer: CharTokenizer):
        super().__init__(upstream)
        self.tokenizer = tokenizer

    def __iter__(self):
        for rec in self.upstream:
            ids = self.tokenizer.encode(rec[0])
            self.records_out += 1
            yield (ids,) + tuple(rec[1:])


class WindowStage(Stage):
    """Slice token-stream records into next-token training windows.

    Each upstream record's field 0 is a token-id array; every ``stride``
    tokens a window of ``size + 1`` ids is cut and emitted as
    ``(one_hot(w[:-1]), one_hot(w[1:]))`` — ``[t, V]`` features and
    per-timestep labels, ``t <= size``. The final partial window of each
    document is kept when it holds >= 2 tokens, so real corpora emit the
    variable lengths the bucket ladder pads. With ``vocab_size=None`` the
    raw id windows pass through as ``(w,)`` records.

    Checkpoint state: the in-progress document and the window cursor —
    bounded by the longest document, the same O(window) promise as
    ``ShuffleStage``.
    """

    name = "window"

    def __init__(self, upstream: Stage, size: int,
                 stride: Optional[int] = None,
                 vocab_size: Optional[int] = None):
        super().__init__(upstream)
        if size < 1:
            raise ValueError("window size must be >= 1")
        self.size = int(size)
        self.stride = int(stride or size)
        self.vocab_size = None if vocab_size is None else int(vocab_size)
        self._doc: Optional[np.ndarray] = None
        self._off = 0

    def _emit(self, w: np.ndarray) -> tuple:
        if self.vocab_size is None:
            return (w,)
        v = self.vocab_size
        x = np.zeros((len(w) - 1, v), np.float32)
        x[np.arange(len(w) - 1), w[:-1].astype(np.int64)] = 1.0
        y = np.zeros((len(w) - 1, v), np.float32)
        y[np.arange(len(w) - 1), w[1:].astype(np.int64)] = 1.0
        return (x, y)

    def __iter__(self):
        up = iter(self.upstream)
        while True:
            if self._doc is None:
                rec = next(up, None)
                if rec is None:
                    return
                doc = np.asarray(rec[0], np.int32).ravel()
                if doc.shape[0] < 2:
                    continue
                self._doc, self._off = doc, 0
            doc = self._doc
            while self._off + 1 < doc.shape[0]:
                w = doc[self._off:self._off + self.size + 1]
                # advance BEFORE yielding so a checkpoint taken after the
                # consumer takes this record resumes at the next window
                self._off += self.stride
                self.records_out += 1
                yield self._emit(w)
            self._doc, self._off = None, 0

    def on_epoch(self, epoch: int):
        super().on_epoch(epoch)
        self._doc, self._off = None, 0

    def _state(self):
        return {"doc": encode_state_value(self._doc), "off": self._off}

    def _load_state(self, state):
        doc = decode_state_value(state["doc"])
        self._doc = None if doc is None else np.asarray(doc, np.int32)
        self._off = int(state["off"])
