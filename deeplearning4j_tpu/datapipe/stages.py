"""Transform stages: map/filter, normalization, windowed shuffle,
deterministic shard, batch and pad-to-bucket batch.

Every stage follows the core contract: iteration state lives in instance
attributes (never generator locals), ``on_epoch`` re-derives per-epoch
RNGs from ``seed + epoch``, and ``_state()`` captures exactly what a
resume needs — bounded by window/buffer sizes, never the dataset.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datapipe.core import (Stage, _restore_rng, _rng_state,
                                              decode_record, encode_record)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.observability.trace import get_tracer
from deeplearning4j_tpu.serving.batcher import next_bucket

__all__ = ["MapStage", "FilterStage", "NormalizerStats", "NormalizeStage",
           "ShuffleStage", "ShardStage", "BatchStage", "BucketBatchStage"]


class MapStage(Stage):
    """Apply ``fn(record) -> record``. With ``workers > 0`` the function
    runs on a thread pool with in-order emission; the raw in-flight
    records are checkpoint state and re-submitted on restore, so ``fn``
    must be deterministic (same record in, same record out)."""

    name = "map"

    def __init__(self, upstream: Stage, fn: Callable, workers: int = 0):
        super().__init__(upstream)
        self.fn = fn
        self.workers = int(workers)
        self._inflight: List[tuple] = []   # raw records submitted, unemitted

    def __iter__(self):
        if self.workers <= 0:
            for rec in self.upstream:
                out = self.fn(rec)
                self.records_out += 1
                yield out
            return
        with ThreadPoolExecutor(self.workers,
                                thread_name_prefix="dl4j-pipe-map") as pool:
            # re-submit work that was in flight when the checkpoint hit
            pending = [(raw, pool.submit(self.fn, raw))
                       for raw in self._inflight]
            up = iter(self.upstream)
            exhausted = False
            while pending or not exhausted:
                while not exhausted and len(pending) < 2 * self.workers:
                    raw = next(up, None)
                    if raw is None:
                        exhausted = True
                        break
                    self._inflight.append(raw)
                    pending.append((raw, pool.submit(self.fn, raw)))
                if not pending:
                    break
                raw, fut = pending.pop(0)
                # a wedged map fn (hung I/O in user code) must fail the
                # pipeline, not hang the consumer forever
                out = fut.result(timeout=float(os.environ.get(
                    "DL4J_TPU_PIPE_MAP_TIMEOUT_S", "600")))
                self._inflight.remove(raw)
                self.records_out += 1
                yield out

    def on_epoch(self, epoch: int):
        super().on_epoch(epoch)
        self._inflight = []

    def _state(self):
        return {"inflight": [encode_record(r) for r in self._inflight]}

    def _load_state(self, state):
        self._inflight = [decode_record(r) for r in state["inflight"]]


class FilterStage(Stage):
    """Keep records where ``pred(record)`` is truthy. Stateless: the
    upstream cursor is the only position."""

    name = "filter"

    def __init__(self, upstream: Stage, pred: Callable):
        super().__init__(upstream)
        self.pred = pred

    def __iter__(self):
        for rec in self.upstream:
            if self.pred(rec):
                self.records_out += 1
                yield rec


class NormalizerStats:
    """Per-feature mean/std fitted by streaming (Welford accumulation) —
    the NormalizerStandardize tier. Fit once, then reuse across runs:
    ``stats.state_dict()`` makes the statistics part of the pipeline
    checkpoint, so a resumed run normalizes with bit-identical moments."""

    def __init__(self, mean: np.ndarray, std: np.ndarray):
        self.mean = np.asarray(mean, np.float64)
        self.std = np.asarray(std, np.float64)

    @classmethod
    def fit(cls, pipeline, eps: float = 1e-8) -> "NormalizerStats":
        """Stream the pipeline's records once (field 0 = features),
        then rewind it."""
        count = 0
        mean = m2 = None
        for rec in pipeline.tail:
            x = np.asarray(rec[0], np.float64)
            if mean is None:
                mean, m2 = np.zeros_like(x), np.zeros_like(x)
            count += 1
            delta = x - mean
            mean += delta / count
            m2 += delta * (x - mean)
        if count == 0:
            raise ValueError("cannot fit normalizer statistics on an "
                             "empty pipeline")
        var = m2 / count
        pipeline.reset()
        return cls(mean, np.sqrt(var) + eps)

    def transform(self, x: np.ndarray) -> np.ndarray:
        return ((np.asarray(x, np.float64) - self.mean)
                / self.std).astype(np.float32)

    def state_dict(self):
        from deeplearning4j_tpu.datapipe.core import encode_state_value
        return {"mean": encode_state_value(self.mean),
                "std": encode_state_value(self.std)}

    @classmethod
    def from_state_dict(cls, state):
        from deeplearning4j_tpu.datapipe.core import decode_state_value
        return cls(decode_state_value(state["mean"]),
                   decode_state_value(state["std"]))


class NormalizeStage(Stage):
    """Standardize record features (field 0) with fitted
    :class:`NormalizerStats`. The statistics themselves are checkpoint
    state (a resumed pipeline must not refit on different data)."""

    name = "normalize"

    def __init__(self, upstream: Stage, stats: NormalizerStats):
        super().__init__(upstream)
        self.stats = stats

    def __iter__(self):
        for rec in self.upstream:
            self.records_out += 1
            yield (self.stats.transform(rec[0]),) + tuple(rec[1:])

    def _state(self):
        return {"stats": self.stats.state_dict()}

    def _load_state(self, state):
        self.stats = NormalizerStats.from_state_dict(state["stats"])


class ShuffleStage(Stage):
    """Windowed (reservoir-style) shuffle with an explicit seeded RNG.

    Fills a window of ``window`` records, then on each pull swaps a
    random window slot with the tail, pops it, and refills from
    upstream — uniform within the window, streaming-friendly, and
    exactly resumable: checkpoint state is the RNG bit-generator state
    plus the window contents (O(window), never O(dataset)). The
    per-epoch RNG derives from ``seed + epoch`` so every epoch visits a
    distinct deterministic order and ``reset()`` replays epoch 0
    bit-identically.
    """

    name = "shuffle"

    def __init__(self, upstream: Stage, window: int = 1024, seed: int = 0):
        super().__init__(upstream)
        if window < 1:
            raise ValueError("shuffle window must be >= 1")
        self.window = int(window)
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)
        self._buf: List[tuple] = []

    def _top_up(self, up):
        # the initial fill is the expensive pull — span/clock that one;
        # steady-state single-record refills stay untimed (hot path)
        if not self._buf:
            t0 = time.perf_counter()
            with get_tracer().span("pipe_shuffle_fill", window=self.window):
                while len(self._buf) < self.window:
                    rec = next(up, None)
                    if rec is None:
                        return
                    self._buf.append(rec)
            self._clock(t0)
            return
        while len(self._buf) < self.window:
            rec = next(up, None)
            if rec is None:
                return
            self._buf.append(rec)

    def _pop(self) -> tuple:
        j = int(self._rng.integers(len(self._buf)))
        self._buf[j], self._buf[-1] = self._buf[-1], self._buf[j]
        return self._buf.pop()

    def __iter__(self):
        # resume invariant: the top-up happens BEFORE each pop, so the
        # instance state at every yield boundary (buffer just popped,
        # not yet refilled) replays identically whether this generator
        # resumes or a restored stage starts a fresh one
        up = iter(self.upstream)
        while True:
            if len(self._buf) < self.window:
                self._top_up(up)
            if not self._buf:
                break
            rec = self._pop()
            self.records_out += 1
            yield rec

    def on_epoch(self, epoch: int):
        super().on_epoch(epoch)
        self._rng = np.random.default_rng(self.seed + epoch)
        self._buf = []

    def _state(self):
        return {"rng": _rng_state(self._rng),
                "buf": [encode_record(r) for r in self._buf]}

    def _load_state(self, state):
        self._rng = _restore_rng(state["rng"])
        self._buf = [decode_record(r) for r in state["buf"]]


class ShardStage(Stage):
    """Deterministic modulo shard: record ``k`` (0-based position in the
    upstream stream this epoch) belongs to shard ``k % num_shards``; this
    stage keeps ``k % num_shards == index``. Disjoint and covering by
    construction for ANY dataset size — every k lands in exactly one
    shard — with shard sizes differing by at most one record when
    ``num_shards`` does not divide the dataset. Place BEFORE shuffle for
    fully independent per-host streams, or give every host the same
    shuffle seed and place it after for identical global orders."""

    name = "shard"

    def __init__(self, upstream: Stage, num_shards: int, index: int):
        super().__init__(upstream)
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        if not 0 <= index < num_shards:
            raise ValueError(f"shard index {index} out of range "
                             f"[0, {num_shards})")
        self.num_shards = int(num_shards)
        self.index = int(index)
        self._k = 0              # upstream records seen this epoch

    def __iter__(self):
        for rec in self.upstream:
            mine = self._k % self.num_shards == self.index
            self._k += 1
            if mine:
                self.records_out += 1
                yield rec

    def on_epoch(self, epoch: int):
        super().on_epoch(epoch)
        self._k = 0

    def _state(self):
        # n/i ride along so a checkpoint records WHICH shard of HOW MANY
        # this cursor belongs to — the elastic remap (datapipe/reshard.py)
        # needs them to re-cut the stream for a different fleet size
        return {"k": self._k, "n": self.num_shards, "i": self.index}

    def _load_state(self, state):
        # a cursor saved for shard (i of n) is meaningless under any
        # other (n, i): loading it silently would drop/double records.
        # Cross-fleet resume must go through datapipe.reshard.remap_state
        # which rewrites these fields for the new fleet first.
        if "n" in state and (int(state["n"]) != self.num_shards
                             or int(state["i"]) != self.index):
            raise ValueError(
                f"shard state was saved for shard {state['i']} of "
                f"{state['n']}, but this pipeline shards {self.index} of "
                f"{self.num_shards} — remap it with "
                "deeplearning4j_tpu.datapipe.reshard.remap_state first")
        self._k = int(state["k"])


class BatchStage(Stage):
    """Collate ``batch_size`` records into one :class:`DataSet`
    (``np.stack`` per field; a partial buffer at checkpoint time is
    state). Field order: features, labels, features_mask, labels_mask."""

    name = "batch"

    def __init__(self, upstream: Stage, batch_size: int,
                 drop_last: bool = False):
        super().__init__(upstream)
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)
        self._buf: List[tuple] = []

    @staticmethod
    def _collate(rows: Sequence[tuple]) -> DataSet:
        width = max(len(r) for r in rows)
        fields = []
        for f in range(4):
            if f >= width or all(len(r) <= f or r[f] is None for r in rows):
                fields.append(None)
            else:
                fields.append(np.stack([np.asarray(r[f]) for r in rows]))
        return DataSet(*fields)

    def _emit(self) -> DataSet:
        t0 = time.perf_counter()
        with get_tracer().span("pipe_collate", n=len(self._buf)):
            ds = self._collate(self._buf)
        self._buf = []
        self._clock(t0)
        return ds

    def __iter__(self):
        for rec in self.upstream:
            self._buf.append(rec)
            if len(self._buf) >= self.batch_size:
                self.records_out += self.batch_size
                yield self._emit()
        if self._buf and not self.drop_last:
            self.records_out += len(self._buf)
            yield self._emit()
        self._buf = []

    def on_epoch(self, epoch: int):
        super().on_epoch(epoch)
        self._buf = []

    def _state(self):
        return {"buf": [encode_record(r) for r in self._buf]}

    def _load_state(self, state):
        self._buf = [decode_record(r) for r in state["buf"]]


class BucketBatchStage(Stage):
    """Pad-to-bucket batching for variable-length sequence records.

    Each record's time dimension (``[t, f]`` features, optional per-step
    labels) pads to the next rung of a power-of-two length ladder — the
    serving dispatcher's bucket idea (``serving.batcher.next_bucket``)
    pointed at sequence length instead of batch size — and batches only
    with same-bucket records. The XLA compile cache stays bounded by the
    ladder (log(t_max) shapes, not one per distinct length) while the
    emitted masks keep the padded math exact. Per-bucket partial buffers
    are checkpoint state.
    """

    name = "bucket_batch"

    def __init__(self, upstream: Stage, batch_size: int,
                 ladder: Optional[Sequence[int]] = None,
                 drop_last: bool = False):
        super().__init__(upstream)
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.batch_size = int(batch_size)
        self.ladder = None if ladder is None else sorted(int(x)
                                                         for x in ladder)
        self.drop_last = bool(drop_last)
        self._bufs = {}          # bucket_len -> list of records
        self.cells_real = 0      # feature cells holding real timesteps
        self.cells_padded = 0    # feature cells that are bucket filler

    def _bucket(self, t: int) -> int:
        if self.ladder is None:
            return next_bucket(t, max_batch=1 << 62)
        for rung in self.ladder:
            if t <= rung:
                return rung
        return self.ladder[-1]   # over-ladder sequences truncate to top rung

    def _collate(self, bucket: int, rows: List[tuple]) -> DataSet:
        t0 = time.perf_counter()
        real_steps = 0
        with get_tracer().span("pipe_collate", n=len(rows), bucket=bucket):
            b = len(rows)
            f = np.asarray(rows[0][0]).shape[-1]
            x = np.zeros((b, bucket, f), np.float32)
            fmask = np.zeros((b, bucket), np.float32)
            y = lmask = None
            for i, rec in enumerate(rows):
                s = np.asarray(rec[0], np.float32)[:bucket]
                real_steps += s.shape[0]
                x[i, :s.shape[0]] = s
                fmask[i, :s.shape[0]] = 1.0
                if len(rec) > 1 and rec[1] is not None:
                    l = np.asarray(rec[1], np.float32)
                    if l.ndim >= 2:       # per-step labels pad+mask too
                        if y is None:
                            y = np.zeros((b, bucket, l.shape[-1]), np.float32)
                            lmask = np.zeros((b, bucket), np.float32)
                        l = l[:bucket]
                        y[i, :l.shape[0]] = l
                        lmask[i, :l.shape[0]] = 1.0
                    else:                 # one label per sequence
                        if y is None:
                            y = np.zeros((b,) + l.shape, np.float32)
                        y[i] = l
        # padding-waste accounting in timestep cells: b*bucket cells
        # went to the device, real_steps of them carry data
        padded_steps = b * bucket - real_steps
        self.cells_real += real_steps
        self.cells_padded += padded_steps
        from deeplearning4j_tpu.observability import goodput as _goodput
        _goodput.record_padding("datapipe_bucket_batch", real_steps,
                                padded_steps)
        self._clock(t0)
        return DataSet(x, y, fmask, lmask)

    def __iter__(self):
        for rec in self.upstream:
            t = int(np.asarray(rec[0]).shape[0])
            bucket = self._bucket(t)
            buf = self._bufs.setdefault(bucket, [])
            buf.append(rec)
            if len(buf) >= self.batch_size:
                self._bufs[bucket] = []
                self.records_out += len(buf)
                yield self._collate(bucket, buf)
        if not self.drop_last:
            for bucket in sorted(self._bufs):
                buf = self._bufs[bucket]
                if buf:
                    self._bufs[bucket] = []
                    self.records_out += len(buf)
                    yield self._collate(bucket, buf)
        self._bufs = {}

    def on_epoch(self, epoch: int):
        super().on_epoch(epoch)
        self._bufs = {}

    def _state(self):
        return {"bufs": {str(k): [encode_record(r) for r in v]
                         for k, v in self._bufs.items() if v}}

    def _load_state(self, state):
        self._bufs = {int(k): [decode_record(r) for r in v]
                      for k, v in state["bufs"].items()}
