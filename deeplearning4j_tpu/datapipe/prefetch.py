"""Parallel worker prefetch with checkpoint-consistent state.

A background thread pulls batches from upstream ahead of the consumer
(bounded by ``depth``), overlapping host-side collation/IO with the
device step — this layers UNDER the fit loops' own
``AsyncDataSetIterator`` / ``DevicePrefetchIterator`` wrappers, which
see the pipeline as just another iterator.

The checkpoint subtlety: batches sitting in the prefetch buffer have
already advanced the upstream cursor but have not reached the trainer.
``_state()`` therefore captures (upstream state, buffered batches) as
one consistent pair: the worker's ``next(upstream)`` happens OUTSIDE the
lock (so the consumer never blocks behind a slow pull), guarded by a
``_pulling`` flag set before and cleared — together with the buffer
append — under the lock; ``state_dict()`` waits for any in-flight pull
to land before snapshotting. On restore, buffered batches are emitted
first, then the stream continues from the restored upstream cursor — no
record replayed, none dropped.
"""

from __future__ import annotations

import threading
import time
from typing import List

from deeplearning4j_tpu.analysis.guards import guarded_by
from deeplearning4j_tpu.datapipe.core import (Stage, decode_state_value,
                                              encode_state_value)
from deeplearning4j_tpu.observability.trace import get_tracer

__all__ = ["PrefetchStage"]

_END = object()


# _cond wraps _lock (one underlying lock): either with-block satisfies
# the guard, but registration uses the name the writers take
@guarded_by("_cond", "_buf", "_pulling", "_done", "_stop", "_error",
            "_thread")
class PrefetchStage(Stage):
    name = "prefetch"

    def __init__(self, upstream: Stage, depth: int = 2):
        super().__init__(upstream)
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1")
        self.depth = int(depth)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._buf: List[object] = []     # pulled, not yet consumed
        self._pulling = False
        self._done = False               # upstream exhausted this epoch
        self._stop = False
        self._error = None
        self._thread = None

    # ------------------------------------------------------------ worker
    def _worker(self):
        tracer = get_tracer()
        it = iter(self.upstream)
        while True:
            with self._cond:
                while len(self._buf) >= self.depth and not self._stop:
                    self._cond.wait(0.1)
                if self._stop:
                    return
                self._pulling = True
            item = _END
            err = None
            t0 = time.perf_counter()
            try:
                with tracer.span("pipe_prefetch_pull"):
                    item = next(it, _END)
            except BaseException as e:   # surface in the consumer
                err = e
            self._clock(t0)
            with self._cond:
                self._pulling = False
                if err is not None:
                    self._error = err
                    self._done = True
                elif item is _END:
                    self._done = True
                else:
                    self._buf.append(item)
                self._cond.notify_all()
                if self._done or self._stop:
                    return

    def _ensure_worker(self):
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = False
            self._done = False
            self._error = None
            t = threading.Thread(
                target=self._worker, name="dl4j-pipe-prefetch", daemon=True)
            t.start()
            self._thread = t

    def stop(self):
        """Stop the worker and wait for it (consumer exit / close path)."""
        with self._cond:
            t = self._thread
            self._stop = True
            self._cond.notify_all()
        if t is not None and t.is_alive():
            t.join(timeout=10.0)
        with self._cond:
            self._thread = None

    # --------------------------------------------------------- iteration
    def __iter__(self):
        self._ensure_worker()
        try:
            while True:
                with self._cond:
                    while not self._buf and not self._done:
                        self._cond.wait(0.1)
                    if self._buf:
                        item = self._buf.pop(0)
                        self._cond.notify_all()
                    elif self._error is not None:
                        err, self._error = self._error, None
                        raise err
                    else:
                        break
                self.records_out += 1
                yield item
        finally:
            self.stop()

    def buffered(self) -> int:
        """Batches ready for the consumer (the queue-depth metric)."""
        with self._lock:
            return len(self._buf)

    def on_epoch(self, epoch: int):
        self.stop()
        super().on_epoch(epoch)
        with self._cond:
            self._buf = []
            self._done = False
            self._error = None

    # -------------------------------------------------------- checkpoint
    def state_dict(self) -> dict:
        # snapshot (upstream, buffer) consistently: park the worker by
        # waiting out any in-flight pull, then read both under the lock
        with self._cond:
            deadline = time.monotonic() + 30.0
            while self._pulling:
                if not self._cond.wait(0.5) and time.monotonic() > deadline:
                    raise RuntimeError("prefetch worker stuck in pull "
                                       "during state_dict()")
            s = {"kind": self.name,
                 "buf": [encode_state_value(b) for b in self._buf],
                 "upstream": self.upstream.state_dict()}
        return s

    def load_state_dict(self, state: dict):
        if state.get("kind") != self.name:
            raise ValueError(
                f"pipeline state mismatch: stage {self.name!r} cannot load "
                f"state saved by {state.get('kind')!r}")
        self.stop()
        with self._cond:
            self._buf = [decode_state_value(b) for b in state["buf"]]
            self._done = False
            self._error = None
        self.upstream.load_state_dict(state["upstream"])
