"""SLO-aware traffic engine (SERVING.md §Traffic engine).

One scheduling brain for the four queue disciplines that grew
independently across the serving tier:

- batcher admission        (serving/batcher.py — ticket queue ordering)
- fleet replica routing    (serving/fleet.py — global admission)
- decode session scheduling (serving/decode.py — per-op class threading)
- router host-picking      (serving/router.py — front-door admission)

``core.SchedulingCore`` is that brain: admission classes (strict
priority interactive > batch > best_effort), per-tenant token-bucket
quotas, and deadline-aware shedding that degrades batch traffic first
under the existing derived-Retry-After backpressure. Requests carry
``X-DL4J-Tenant`` / ``X-DL4J-Priority`` / ``X-DL4J-Deadline-Ms``
headers end to end, echoed like the trace id.

``autoscaler.Autoscaler`` closes the loop: it watches the live
federation gauges (queue depth, retry_after_s, SLO burn rate) and
actuates through seams that already exist — ``ReplicaSet``
drain/restart within a host, launcher spawn + router host-add across
hosts — with hysteresis, cooldowns and min/max bounds so it never
flaps.

``loadgen`` is the open-loop, trace-driven arrival generator behind
``scripts/traffic_bench.py`` (seeded diurnal ramps, flash crowds,
heavy-tailed sizes, mixed tenants/classes) — the harness that produces
the budget-gated ``TRAFFIC_r01.json`` receipt.
"""

from deeplearning4j_tpu.scheduling.autoscaler import (  # noqa: F401
    Autoscaler, ReplicaSetActuator, fleet_signals)
from deeplearning4j_tpu.scheduling.core import (  # noqa: F401
    BATCH, BEST_EFFORT, CLASSES, DEADLINE_HEADER, INTERACTIVE, PRIORITY,
    PRIORITY_HEADER, SCHED_HEADERS, SHED_CLASS_HEADER, SchedulingCore,
    ShedError, TENANT_HEADER, TokenBucket, build_sched_headers,
    normalize_class, parse_sched_headers)
from deeplearning4j_tpu.scheduling.loadgen import (  # noqa: F401
    Arrival, OpenLoopRunner, TrafficModel, attainment)

__all__ = [
    "SchedulingCore", "ShedError", "TokenBucket", "normalize_class",
    "parse_sched_headers", "build_sched_headers",
    "CLASSES", "PRIORITY", "INTERACTIVE", "BATCH", "BEST_EFFORT",
    "TENANT_HEADER", "PRIORITY_HEADER", "DEADLINE_HEADER",
    "SHED_CLASS_HEADER", "SCHED_HEADERS",
    "Autoscaler", "ReplicaSetActuator", "fleet_signals",
    "TrafficModel", "OpenLoopRunner", "Arrival", "attainment",
]
