"""Open-loop, trace-driven traffic generation (scripts/traffic_bench.py).

serve_bench's closed loop — N clients, each waiting for its reply
before sending the next request — can never overload a server: the
moment latency grows, the offered rate falls to match. Real traffic
does the opposite. Users arrive on their own clock, latency be damned,
and the interesting serving regimes (flash crowds, diurnal peaks,
retry storms) exist exactly because arrivals do NOT wait for
completions. This module generates that traffic:

- **Seeded arrival trace.** ``TrafficModel.arrivals()`` materializes
  one deterministic list of ``Arrival`` events from a seed — a
  nonhomogeneous Poisson process (thinning against the peak rate)
  whose intensity follows a diurnal sinusoid plus configured flash
  crowds (step multipliers over a window). Same seed ⇒ same trace:
  the receipt is reproducible and A/B runs see identical load.
- **Heavy-tailed sizes.** Request row counts draw from a clipped
  Pareto — most requests are small, a few are large, as every real
  serving mix is.
- **Mixed tenants and classes.** Each arrival carries a tenant and an
  admission class sampled from configured weights, plus the class's
  deadline — the headers traffic_bench puts on the wire
  (X-DL4J-Tenant / X-DL4J-Priority / X-DL4J-Deadline-Ms).
- **Sessions with think time.** A fraction of arrivals are session
  continuations: a user who got a reply thinks, then sends again.
  Think time shifts the *scheduled* arrival, preserving open-loop
  semantics (the follow-up fires at its appointed time whether or not
  the fleet is drowning).

``OpenLoopRunner`` replays the trace against a ``submit_fn`` on a
wall-clock (or injected) timebase: a dispatcher thread releases each
arrival at its offset into a worker pool and NEVER waits for
completions — if the fleet falls behind, requests pile up exactly as
they would at a real front door. Per-arrival outcome records
(latency, status, shed class) feed the attainment-vs-offered-load
curves in the receipt.
"""

from __future__ import annotations

import math
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Arrival", "TrafficModel", "OpenLoopRunner", "attainment"]


@dataclass
class Arrival:
    """One scheduled request: fires at offset ``t`` seconds from the
    run start, no matter what happened to every earlier request."""
    t: float
    tenant: str
    klass: str
    rows: int
    deadline_ms: Optional[float] = None
    session: Optional[str] = None

    def headers(self) -> Dict[str, str]:
        from deeplearning4j_tpu.scheduling.core import (
            DEADLINE_HEADER, PRIORITY_HEADER, TENANT_HEADER)
        h = {TENANT_HEADER: self.tenant, PRIORITY_HEADER: self.klass}
        if self.deadline_ms is not None:
            h[DEADLINE_HEADER] = f"{self.deadline_ms:g}"
        return h


@dataclass
class _Phase:
    """Flash crowd: multiply the base intensity by ``mult`` over
    [start, start+duration)."""
    start: float
    duration: float
    mult: float


class TrafficModel:
    """Deterministic open-loop arrival trace.

    ``class_mix`` / ``tenants`` map name -> weight; ``deadlines_ms``
    maps class -> deadline header value (None omits the header).
    ``base_rps`` is the diurnal *mean*; the sinusoid swings it by
    ``diurnal_amplitude`` over ``diurnal_period_s``; each
    ``flash_crowd`` (start_s, duration_s, multiplier) multiplies the
    instantaneous rate. ``session_fraction`` of arrivals spawn a
    follow-up ``think_s`` later under the same session id (same
    tenant/class — a user, not a new one)."""

    def __init__(self, *, seed: int = 0, duration_s: float,
                 base_rps: float, diurnal_amplitude: float = 0.3,
                 diurnal_period_s: float = 60.0,
                 flash_crowds: Sequence[Tuple[float, float, float]] = (),
                 class_mix: Optional[Dict[str, float]] = None,
                 tenants: Optional[Dict[str, float]] = None,
                 deadlines_ms: Optional[Dict[str, float]] = None,
                 pareto_alpha: float = 1.6, max_rows: int = 8,
                 session_fraction: float = 0.0,
                 think_s: float = 1.0):
        from deeplearning4j_tpu.scheduling.core import BATCH, INTERACTIVE
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.base_rps = float(base_rps)
        self.diurnal_amplitude = float(diurnal_amplitude)
        self.diurnal_period_s = float(diurnal_period_s)
        self.phases = [_Phase(*fc) for fc in flash_crowds]
        self.class_mix = dict(class_mix or {INTERACTIVE: 0.5, BATCH: 0.5})
        self.tenants = dict(tenants or {"default": 1.0})
        self.deadlines_ms = dict(deadlines_ms or {})
        self.pareto_alpha = float(pareto_alpha)
        self.max_rows = int(max_rows)
        self.session_fraction = float(session_fraction)
        self.think_s = float(think_s)

    # ------------------------------------------------------------- intensity
    def rate_at(self, t: float) -> float:
        """Offered requests/sec at offset ``t`` — diurnal sinusoid
        times any active flash-crowd multiplier. Exposed so the bench
        can publish the offered-load curve next to attainment."""
        r = self.base_rps * (1.0 + self.diurnal_amplitude * math.sin(
            2.0 * math.pi * t / self.diurnal_period_s))
        for p in self.phases:
            if p.start <= t < p.start + p.duration:
                r *= p.mult
        return max(r, 0.0)

    def peak_rate(self) -> float:
        base_peak = self.base_rps * (1.0 + abs(self.diurnal_amplitude))
        mult = max((p.mult for p in self.phases), default=1.0)
        return base_peak * max(mult, 1.0)

    # --------------------------------------------------------------- drawing
    def _weighted(self, rng: random.Random, table: Dict[str, float]) -> str:
        names = list(table)
        total = sum(table.values())
        x = rng.random() * total
        for n in names:
            x -= table[n]
            if x <= 0:
                return n
        return names[-1]

    def _rows(self, rng: random.Random) -> int:
        # clipped Pareto: P(X > x) ~ x^-alpha, floor 1, cap max_rows
        x = rng.paretovariate(self.pareto_alpha)
        return max(1, min(self.max_rows, int(x)))

    def arrivals(self) -> List[Arrival]:
        """Materialize the whole trace (sorted by t). Thinning: draw
        candidate times from a homogeneous Poisson at the peak rate,
        keep each with probability rate(t)/peak — the textbook
        nonhomogeneous sampler, deterministic under the seed."""
        rng = random.Random(self.seed)
        peak = self.peak_rate()
        if peak <= 0:
            return []
        out: List[Arrival] = []
        t = 0.0
        n_sessions = 0
        while True:
            t += rng.expovariate(peak)
            if t >= self.duration_s:
                break
            if rng.random() * peak > self.rate_at(t):
                continue
            tenant = self._weighted(rng, self.tenants)
            klass = self._weighted(rng, self.class_mix)
            a = Arrival(t=round(t, 6), tenant=tenant, klass=klass,
                        rows=self._rows(rng),
                        deadline_ms=self.deadlines_ms.get(klass))
            out.append(a)
            if rng.random() < self.session_fraction:
                # a session user: reply -> think -> follow-up, scheduled
                # now (open loop — the follow-up fires on time even if
                # the first request is still queued somewhere)
                n_sessions += 1
                sid = f"s{self.seed}-{n_sessions}"
                a.session = sid
                t2 = t + max(0.05, rng.expovariate(1.0 / self.think_s))
                if t2 < self.duration_s:
                    out.append(Arrival(
                        t=round(t2, 6), tenant=tenant, klass=klass,
                        rows=self._rows(rng),
                        deadline_ms=self.deadlines_ms.get(klass),
                        session=sid))
        out.sort(key=lambda a: a.t)
        return out


@dataclass
class _Outcome:
    arrival: Arrival
    t_sent: float
    latency_ms: Optional[float] = None
    status: Optional[int] = None
    shed_class: Optional[str] = None
    error: Optional[str] = None
    extra: dict = field(default_factory=dict)


class OpenLoopRunner:
    """Replay an arrival trace against ``submit_fn(arrival) -> dict``.

    The dispatcher thread sleeps until each arrival's offset and hands
    it to a worker pool — it never waits for a completion before
    releasing the next arrival, which is the entire point. Workers
    record one outcome row per arrival: ``submit_fn`` returns
    ``{"status": int, "shed_class": str|None, ...}`` (extra keys are
    kept) or raises — an exception records as status None with the
    error string, still one row (offered load is accounted even when
    the fleet drops the connection).

    ``max_workers`` bounds concurrency; when all workers are busy the
    backlog queues HERE, time-stamped at the intended offset, so
    latency accounting still measures from the scheduled arrival (what
    the user experienced) rather than from the delayed send."""

    def __init__(self, submit_fn, arrivals: Sequence[Arrival], *,
                 max_workers: int = 32, clock=time.monotonic,
                 sleep=time.sleep):
        self._submit = submit_fn
        self.arrivals = list(arrivals)
        self.max_workers = int(max_workers)
        self._clock = clock
        self._sleep = sleep
        self.outcomes: List[_Outcome] = []
        self._out_lock = threading.Lock()

    def run(self) -> List[dict]:
        from concurrent.futures import ThreadPoolExecutor
        t0 = self._clock()
        with ThreadPoolExecutor(max_workers=self.max_workers,
                                thread_name_prefix="loadgen") as pool:
            for a in self.arrivals:
                delay = a.t - (self._clock() - t0)
                if delay > 0:
                    self._sleep(delay)
                pool.submit(self._one, a, t0)
        # pool __exit__ joined every worker; rows are complete
        return [self._row(o, t0) for o in
                sorted(self.outcomes, key=lambda o: o.arrival.t)]

    def _one(self, a: Arrival, t0: float):
        o = _Outcome(arrival=a, t_sent=self._clock() - t0)
        try:
            res = self._submit(a) or {}
            o.status = res.get("status")
            o.shed_class = res.get("shed_class")
            o.extra = {k: v for k, v in res.items()
                       if k not in ("status", "shed_class")}
        except Exception as e:
            o.error = f"{type(e).__name__}: {e}"
        # latency from the SCHEDULED arrival: queueing delay inside the
        # harness counts against the fleet, as it does for a real user
        o.latency_ms = max(0.0, (self._clock() - t0 - a.t) * 1000.0)
        with self._out_lock:
            self.outcomes.append(o)

    def _row(self, o: _Outcome, t0: float) -> dict:
        a = o.arrival
        row = {"t": a.t, "tenant": a.tenant, "class": a.klass,
               "rows": a.rows, "deadline_ms": a.deadline_ms,
               "session": a.session, "status": o.status,
               "latency_ms": (None if o.latency_ms is None
                              else round(o.latency_ms, 3)),
               "shed_class": o.shed_class, "error": o.error}
        row.update(o.extra)
        return row


def attainment(rows: Sequence[dict], klass: str,
               slo_ms: Optional[float] = None,
               window: Optional[Tuple[float, float]] = None) -> dict:
    """SLO attainment for one class over (optionally) one time window:
    offered = every arrival of the class, attained = 200 replies whose
    latency met the request's own deadline (falling back to ``slo_ms``
    when the arrival carried none). Sheds and errors count as offered
    but never attained — an open-loop generator's denominator is what
    was ASKED, not what was admitted."""
    sel = [r for r in rows if r["class"] == klass
           and (window is None or window[0] <= r["t"] < window[1])]
    offered = len(sel)
    ok = 0
    lat = []
    for r in sel:
        if r["status"] == 200 and r["latency_ms"] is not None:
            lat.append(r["latency_ms"])
            bound = r.get("deadline_ms") or slo_ms
            if bound is None or r["latency_ms"] <= float(bound):
                ok += 1
    lat.sort()

    def pct(p):
        return round(lat[min(len(lat) - 1,
                             int(p * len(lat)))], 3) if lat else None
    return {"class": klass, "offered": offered, "attained": ok,
            "attainment": round(ok / offered, 4) if offered else None,
            "served": len(lat), "p50_ms": pct(0.50), "p99_ms": pct(0.99)}
