"""Closed-loop autoscaler: the actuator side of the federation plane.

PR 19 finished the sensor half of the "millions of users" story — live
``dl4j_slo_*`` burn-rate gauges, queue-depth and retry-after federation
rows — but host and replica counts stayed frozen constructor
arguments. This module closes the loop. It deliberately owns NO
infrastructure: the signals come in through one callable and the
actuation goes out through two, so the same controller drives

- **replica scaling within a host** — ``ReplicaSetActuator`` wraps the
  existing ``ReplicaSet.drain(i)`` / ``restart(i)`` seams (a drained
  slot restarts warm: the forward's jit cache survives, 0 fresh
  compiles);
- **host scaling across the fleet** — traffic_bench wires ``up`` to a
  launcher-style subprocess spawn (warm off the shared compile cache,
  the ``cross_host_serving`` 0-fresh-compiles contract) followed by
  the router's host-add verb (``POST /api/hosts``), and ``down`` to
  drain + evict.

Control discipline (the "never flaps" contract, pinned by tests with
the injectable clock):

- **Hysteresis**: a single hot sample never scales — ``breach_n``
  consecutive breached observations arm a scale-up, ``clear_n``
  consecutive idle observations arm a scale-down (clear_n >> breach_n:
  growing is cheap and urgent, shrinking is neither).
- **Cooldowns**: after any action, ``up_cooldown_s`` /
  ``down_cooldown_s`` must elapse before the next same-direction
  action — capacity added needs time to absorb the backlog before the
  controller may judge it insufficient.
- **Bounds**: ``min_size``/``max_size`` clamp hard; the controller
  reports ``at_max`` instead of spinning on an unreachable target.

Reaction-time accounting: the first breached observation of an episode
stamps ``breach_started``; the actuation that resolves it stamps
``last_reaction_s = act - breach_started`` — the number
``TRAFFIC_r01.json`` gates (``max_scaleup_reaction_s``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from deeplearning4j_tpu.analysis.guards import guarded_by

__all__ = ["Autoscaler", "ReplicaSetActuator", "fleet_signals"]


def fleet_signals(router) -> dict:
    """The standard signal bundle read off a ``FrontDoorRouter``'s
    federation plane: total pushed queue depth, worst derived
    retry-after, worst SLO burn rate, live host count. This is a plain
    function (not a method) so a bench or operator loop can point the
    autoscaler at any router — including one in another process via
    its ``/api/fleet`` payload shaped the same way."""
    rows = router.federation.health()
    depth = 0
    retry_after = 0.0
    for row in rows:
        if not row.get("live"):
            continue
        depth += int(row.get("queue_depth") or 0)
        ra = row.get("retry_after_s")
        if ra is not None:
            retry_after = max(retry_after, float(ra))
    burn = 0.0
    try:
        router.slo_engine.ingest_fed_rows(rows)
        for windows in router.slo_engine.evaluate().values():
            for w in windows.values():
                b = w.get("burn_rate")
                if b is not None:
                    burn = max(burn, float(b))
    except Exception:
        pass  # a broken SLO source must not blind the depth signals
    live_hosts = sum(1 for h in router.hosts if h.status == "live")
    return {"queue_depth": depth, "retry_after_s": retry_after,
            "burn_rate": burn, "size": live_hosts}


@guarded_by("_lock", "size", "breach_streak", "clear_streak",
            "breach_started", "last_up_at", "last_down_at",
            "scale_ups_total", "scale_downs_total", "breaches_total",
            "last_reaction_s", "last_decision", "_thread", "_stop")
class Autoscaler:
    """Observe → decide → actuate, with hysteresis, cooldowns and
    bounds. ``signals_fn()`` returns a dict with any of
    ``queue_depth`` / ``retry_after_s`` / ``burn_rate`` (and optionally
    ``size`` — authoritative current capacity; otherwise the
    controller's own count is used). ``up()`` / ``down()`` perform one
    unit of scaling and return truthy on success.

    Thresholds are opt-in: only the ones passed non-None participate,
    and a breach is ANY armed threshold exceeded (queues lag burn
    rate, burn rate lags queues — either alone is cause)."""

    def __init__(self, *, signals_fn: Callable[[], dict],
                 up: Callable[[], object],
                 down: Optional[Callable[[], object]] = None,
                 min_size: int = 1, max_size: int = 4,
                 up_queue_depth: Optional[float] = None,
                 up_retry_after_s: Optional[float] = None,
                 up_burn_rate: Optional[float] = None,
                 down_queue_depth: float = 0.0,
                 breach_n: int = 2, clear_n: int = 10,
                 up_cooldown_s: float = 5.0, down_cooldown_s: float = 60.0,
                 interval_s: float = 0.5, clock=time.monotonic):
        if min_size < 0 or max_size < max(1, min_size):
            raise ValueError("need 0 <= min_size <= max_size, max_size >= 1")
        self._signals_fn = signals_fn
        self._up = up
        self._down = down
        self.min_size = int(min_size)
        self.max_size = int(max_size)
        self.up_queue_depth = up_queue_depth
        self.up_retry_after_s = up_retry_after_s
        self.up_burn_rate = up_burn_rate
        self.down_queue_depth = float(down_queue_depth)
        self.breach_n = int(breach_n)
        self.clear_n = int(clear_n)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.interval_s = float(interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self.size = self.min_size          # best-effort if signals lack it
        self.breach_streak = 0
        self.clear_streak = 0
        self.breach_started: Optional[float] = None
        self.last_up_at: Optional[float] = None
        self.last_down_at: Optional[float] = None
        self.scale_ups_total = 0
        self.scale_downs_total = 0
        self.breaches_total = 0
        self.last_reaction_s: Optional[float] = None
        self.last_decision = "idle"
        self._thread = None
        self._stop = threading.Event()

    # -------------------------------------------------------------- decision
    def _breached(self, sig: dict) -> bool:
        if self.up_queue_depth is not None and \
                float(sig.get("queue_depth") or 0) >= self.up_queue_depth:
            return True
        if self.up_retry_after_s is not None and \
                float(sig.get("retry_after_s") or 0) >= self.up_retry_after_s:
            return True
        if self.up_burn_rate is not None and \
                float(sig.get("burn_rate") or 0) >= self.up_burn_rate:
            return True
        return False

    def step(self) -> dict:
        """One observe-decide-actuate cycle; returns the decision
        record (also kept as ``last_decision`` for the gauges). Safe to
        call from a bench loop instead of ``start()``."""
        sig = self._signals_fn() or {}
        now = self._clock()
        breached = self._breached(sig)
        with self._lock:
            if "size" in sig and sig["size"] is not None:
                self.size = int(sig["size"])
            if breached:
                self.breaches_total += 1
                self.breach_streak += 1
                self.clear_streak = 0
                if self.breach_started is None:
                    self.breach_started = now
            else:
                self.breach_streak = 0
                idle = float(sig.get("queue_depth") or 0) \
                    <= self.down_queue_depth
                self.clear_streak = self.clear_streak + 1 if idle else 0
                if self.clear_streak >= self.clear_n:
                    # episode over: the next breach starts a new
                    # reaction-time clock
                    self.breach_started = None
            decision, why = self._decide_locked(now)
            self.last_decision = decision
        acted = None
        if decision == "up":
            acted = self._up()
            with self._lock:
                if acted:
                    self.scale_ups_total += 1
                    self.last_up_at = self._clock()
                    if self.breach_started is not None:
                        self.last_reaction_s = round(
                            self.last_up_at - self.breach_started, 3)
                    self.size += 1
                    self.breach_streak = 0
                else:
                    self.last_decision = "up_failed"
        elif decision == "down" and self._down is not None:
            acted = self._down()
            with self._lock:
                if acted:
                    self.scale_downs_total += 1
                    self.last_down_at = self._clock()
                    self.size -= 1
                    self.clear_streak = 0
        return {"decision": decision, "why": why, "signals": sig,
                "acted": bool(acted)}

    def _decide_locked(self, now: float):
        if self.breach_streak >= self.breach_n:
            if self.size >= self.max_size:
                return "hold", "at_max"
            if self.last_up_at is not None and \
                    now - self.last_up_at < self.up_cooldown_s:
                return "hold", "up_cooldown"
            return "up", "breach"
        if self._down is not None and self.clear_streak >= self.clear_n:
            if self.size <= self.min_size:
                return "hold", "at_min"
            last_act = max(x for x in (self.last_up_at, self.last_down_at,
                                       float("-inf")) if x is not None)
            if last_act != float("-inf") and \
                    now - last_act < self.down_cooldown_s:
                return "hold", "down_cooldown"
            return "down", "idle"
        return "hold", "settling"

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "Autoscaler":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            t = threading.Thread(target=self._loop, daemon=True,
                                 name="autoscaler")
            t.start()
            self._thread = t
        return self

    def stop(self):
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:
                # a failed observation/actuation must not kill the
                # control loop — the next tick re-evaluates
                continue

    # --------------------------------------------------------------- metrics
    def snapshot(self) -> dict:
        with self._lock:
            return {"size": self.size,
                    "min_size": self.min_size, "max_size": self.max_size,
                    "breach_streak": self.breach_streak,
                    "clear_streak": self.clear_streak,
                    "breaches_total": self.breaches_total,
                    "scale_ups_total": self.scale_ups_total,
                    "scale_downs_total": self.scale_downs_total,
                    "last_reaction_s": self.last_reaction_s,
                    "last_decision": self.last_decision}

    def metric_families(self, labels=None):
        """``dl4j_autoscaler_*`` families (OBSERVABILITY.md)."""
        from deeplearning4j_tpu.observability.metrics import MetricFamily
        L = dict(labels or {})
        snap = self.snapshot()
        fams = []

        def fam(name, kind, help, value):
            fams.append(MetricFamily(name, kind, help).add(value, L))

        fam("dl4j_autoscaler_size", "gauge",
            "Capacity units (hosts or replicas) under control",
            snap["size"])
        fam("dl4j_autoscaler_breaches_total", "counter",
            "Observations with any scale-up threshold exceeded",
            snap["breaches_total"])
        fam("dl4j_autoscaler_scale_ups_total", "counter",
            "Successful scale-up actuations", snap["scale_ups_total"])
        fam("dl4j_autoscaler_scale_downs_total", "counter",
            "Successful scale-down actuations", snap["scale_downs_total"])
        fam("dl4j_autoscaler_last_reaction_s", "gauge",
            "Seconds from first breached observation to the actuation "
            "that answered it (the TRAFFIC receipt gate)",
            snap["last_reaction_s"] if snap["last_reaction_s"] is not None
            else -1.0)
        return fams


class ReplicaSetActuator:
    """Within-host actuation through the seams ``ReplicaSet`` already
    has: scale-up restarts the highest drained/dead slot (warm — the
    forward's jit cache survives its old device thread, 0 fresh
    compiles), scale-down drains the highest live slot (its accepted
    queue still finishes). The replica COUNT never changes — slots
    park in ``draining`` instead of being destroyed, which is what
    makes up() free."""

    def __init__(self, replica_set):
        self.rs = replica_set

    def live(self) -> int:
        return sum(1 for r in self.rs.replicas if r.status == "live")

    def up(self) -> bool:
        for r in reversed(self.rs.replicas):
            if r.status != "live":
                self.rs.restart(r.index)
                return True
        return False

    def down(self) -> bool:
        live = [r for r in self.rs.replicas if r.status == "live"]
        if len(live) <= 1:
            return False   # never drain the last worker
        self.rs.drain(live[-1].index)
        return True

    def signals(self) -> dict:
        """Depth/size signals for an Autoscaler driving THIS tier."""
        stats = self.rs.stats
        ra = stats.retry_after_s() if stats is not None else 0.0
        return {"queue_depth": self.rs.live_depth(),
                "retry_after_s": ra, "size": self.live()}
