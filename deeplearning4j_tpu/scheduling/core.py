"""SchedulingCore: one admission discipline for the whole serving tier.

Before this module, admission control lived in four places with four
different answers to "may this request enter?":

- ``MicroBatcher.submit`` capped its own ticket queue (FIFO, no
  classes);
- ``ReplicaSet.submit`` capped the SUM of replica depths (and counted
  dead replicas — the bug fixed alongside this refactor);
- ``DecodeEngine`` inherited whatever its private fleet did;
- ``FrontDoorRouter`` shed only when EVERY host had already said 503.

All four treated every request identically, so one tenant's batch
backfill could starve another tenant's interactive traffic and nobody
could tell the difference in the metrics. ``SchedulingCore`` unifies
the decision:

- **Admission classes.** Three strict-priority tiers —
  ``interactive`` > ``batch`` > ``best_effort`` — parsed from the
  ``X-DL4J-Priority`` header (absent ⇒ interactive, so legacy traffic
  keeps its exact pre-scheduler behavior). The class rides the batcher
  ticket as an integer priority: the device thread seeds each
  coalesced bucket from the oldest ticket of the HIGHEST class
  present, so an interactive request never queues behind a batch
  backlog (the priority-inversion test pins this).
- **Per-tenant token-bucket quotas.** ``X-DL4J-Tenant`` names the
  bucket; rate/burst come from ``quotas`` (per tenant) or
  ``default_quota``. A tenant with no configured quota is unlimited —
  quotas are an opt-in isolation tool, not a default tax. Quota sheds
  answer 503 with reason ``quota`` BEFORE the request touches a queue,
  so tenant A's flood cannot occupy the capacity tenant B's admitted
  requests need.
- **Watermark shedding, batch first.** Under backpressure the classes
  shed in reverse priority order: ``best_effort`` above 25% of queue
  capacity, ``batch`` above 50%, ``interactive`` only at 100% — which
  is exactly the old single-threshold behavior, so a scheduler-on
  fleet with default-class traffic rejects at the same point a
  scheduler-off fleet does.
- **Deadline-aware shedding.** ``X-DL4J-Deadline-Ms`` declares how
  long the client will wait. When the *derived* wait estimate (the
  same backlog-over-drain-rate signal Retry-After already reports)
  says the deadline cannot be met, the request sheds immediately with
  reason ``deadline`` — a fast 503 the client can retry elsewhere
  beats a doomed enqueue.

Sheds raise :class:`ShedError` (a ``QueueFullError`` subclass, so
every existing 503 + Retry-After mapping applies unchanged) carrying
the class and reason; the HTTP layers echo the class in the
``X-DL4J-Shed-Class`` header and the per-class
``dl4j_sched_shed_total{class=...}`` counters let a load test verify
batch really sheds before interactive.

The module never imports jax (the router runs it in a jax-free
process) and every clock is injectable — tests pin quota refill and
deadline math without sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from deeplearning4j_tpu.analysis.guards import guarded_by
from deeplearning4j_tpu.serving.batcher import QueueFullError

__all__ = [
    "SchedulingCore", "ShedError", "TokenBucket", "normalize_class",
    "CLASSES", "PRIORITY", "INTERACTIVE", "BATCH", "BEST_EFFORT",
    "TENANT_HEADER", "PRIORITY_HEADER", "DEADLINE_HEADER",
    "SHED_CLASS_HEADER", "SCHED_HEADERS", "DEFAULT_WATERMARKS",
    "parse_sched_headers", "build_sched_headers",
]

#: which tenant's quota bucket a request draws from (absent ⇒ "default")
TENANT_HEADER = "X-DL4J-Tenant"
#: admission class: interactive | batch | best_effort (absent ⇒ interactive)
PRIORITY_HEADER = "X-DL4J-Priority"
#: how long the client will wait, in milliseconds — the deadline-aware
#: shed compares this against the derived wait estimate
DEADLINE_HEADER = "X-DL4J-Deadline-Ms"
#: echoed on every scheduler 503: which class was shed (satellite: load
#: tests verify batch sheds before interactive)
SHED_CLASS_HEADER = "X-DL4J-Shed-Class"

#: the end-to-end scheduling headers, forwarded hop to hop and echoed
#: back exactly like X-DL4J-Trace-Id
SCHED_HEADERS = (TENANT_HEADER, PRIORITY_HEADER, DEADLINE_HEADER)

INTERACTIVE = "interactive"
BATCH = "batch"
BEST_EFFORT = "best_effort"
CLASSES: Tuple[str, ...] = (INTERACTIVE, BATCH, BEST_EFFORT)

#: strict-priority rank (lower = served first); also the integer the
#: batcher ticket carries
PRIORITY: Dict[str, int] = {INTERACTIVE: 0, BATCH: 1, BEST_EFFORT: 2}

#: queue-fraction watermark above which each class sheds. interactive
#: at 1.0 reproduces the legacy single-threshold reject exactly.
DEFAULT_WATERMARKS: Dict[str, float] = {
    INTERACTIVE: 1.0, BATCH: 0.5, BEST_EFFORT: 0.25}

_SHED_REASONS = ("quota", "backpressure", "deadline")


def normalize_class(name) -> str:
    """Map a header value onto a known class. Absent/unknown values
    become ``interactive`` — legacy clients (no header) must keep their
    exact pre-scheduler admission behavior, and an unrecognized class
    must not be silently demoted to shed-first."""
    if not name:
        return INTERACTIVE
    k = str(name).strip().lower().replace("-", "_")
    return k if k in PRIORITY else INTERACTIVE


def parse_sched_headers(headers) -> dict:
    """Pull (tenant, klass, deadline_ms) from an HTTP header mapping —
    the one parse shared by ModelServer and FrontDoorRouter. A
    malformed deadline is treated as absent (a bad client must not be
    able to 400 itself into a different admission tier)."""
    deadline = headers.get(DEADLINE_HEADER)
    if deadline is not None:
        try:
            deadline = float(deadline)
        except (TypeError, ValueError):
            deadline = None
    return {"tenant": headers.get(TENANT_HEADER),
            "klass": normalize_class(headers.get(PRIORITY_HEADER)),
            "deadline_ms": deadline}


def build_sched_headers(sched) -> dict:
    """The inverse of :func:`parse_sched_headers`: the header dict a
    forwarding hop (the router's proxy) attaches so the backend sees
    the same tenant/class/deadline the client declared."""
    out = {PRIORITY_HEADER: normalize_class((sched or {}).get("klass"))}
    if (sched or {}).get("tenant"):
        out[TENANT_HEADER] = str(sched["tenant"])
    if (sched or {}).get("deadline_ms") is not None:
        out[DEADLINE_HEADER] = f"{float(sched['deadline_ms']):g}"
    return out


class ShedError(QueueFullError):
    """Admission denied by the scheduler. Subclasses ``QueueFullError``
    so every existing 503 + Retry-After mapping (server handler, router
    retry-the-others loop, client backoff) applies unchanged; carries
    WHICH class was shed and WHY so the 503 can say so."""

    def __init__(self, msg: str, klass: str, reason: str):
        super().__init__(msg)
        self.klass = klass
        self.reason = reason


@guarded_by("_lock", "tokens", "_t_last")
class TokenBucket:
    """Per-tenant admission quota: ``rate`` tokens/s refill up to
    ``burst``; one request consumes ``cost`` tokens (callers pass rows,
    so a 64-row POST spends 64× what a 1-row POST does). Injectable
    clock — quota tests refill deterministically."""

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self.tokens = float(burst)
        self._t_last = clock()
        self._lock = threading.Lock()

    def try_take(self, cost: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self.tokens = min(self.burst,
                              self.tokens + (now - self._t_last) * self.rate)
            self._t_last = now
            if self.tokens >= cost:
                self.tokens -= cost
                return True
            return False

    def peek(self) -> float:
        with self._lock:
            now = self._clock()
            return min(self.burst,
                       self.tokens + (now - self._t_last) * self.rate)


@guarded_by("_lock", "_buckets", "_quota_conf", "admitted_total",
            "shed_total", "shed_by_reason", "deepest_admitted_fraction")
class SchedulingCore:
    """The unified admission decision. Stateless with respect to the
    queues themselves: callers pass the observed ``depth``/``capacity``
    (fleet backlog over live replicas, or the router's federated sum)
    and the derived ``wait_estimate_s`` (the Retry-After signal), and
    ``admit`` answers by raising :class:`ShedError` or returning the
    normalized class — so ONE core serves the batcher, the fleet, the
    decode engine and the router without owning any of their locks.

    ``quotas`` maps tenant -> (rate_per_s, burst); ``default_quota``
    applies to tenants with no explicit entry (None = unlimited).
    ``watermarks`` maps class -> queue fraction above which it sheds
    (``DEFAULT_WATERMARKS`` degrades batch first, interactive last).
    """

    #: class -> strict-priority tier, exposed on the instance so
    #: queue owners (serving/fleet.py) can map an admitted class to
    #: its tier without importing this module — serving and
    #: scheduling import each other's packages in opposite
    #: directions, and the attribute breaks the cycle
    PRIORITY = PRIORITY

    def __init__(self, *, quotas=None, default_quota=None,
                 watermarks=None, clock=time.monotonic):
        self._clock = clock
        self._quota_conf = dict(quotas or {})
        self._default_quota = default_quota
        self.watermarks = dict(DEFAULT_WATERMARKS)
        if watermarks:
            self.watermarks.update(watermarks)
        for k in self.watermarks:
            if k not in PRIORITY:
                raise ValueError(f"unknown admission class {k!r}")
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self.admitted_total: Dict[str, int] = {c: 0 for c in CLASSES}
        self.shed_total: Dict[str, int] = {c: 0 for c in CLASSES}
        self.shed_by_reason: Dict[Tuple[str, str], int] = {}
        #: high-water mark of the queue fraction an admitted request
        #: saw — the "how close to the cliff did we run" gauge
        self.deepest_admitted_fraction = 0.0

    # ---------------------------------------------------------------- quotas
    def _bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        with self._lock:
            conf = self._quota_conf.get(tenant, self._default_quota)
            if conf is None:
                return None
            b = self._buckets.get(tenant)
            if b is None:
                rate, burst = conf
                b = TokenBucket(rate, burst, clock=self._clock)
                self._buckets[tenant] = b
            return b

    def set_quota(self, tenant: str, rate: float, burst: float):
        """(Re)configure one tenant's bucket; live buckets rebuild on
        next admit so a raised quota takes effect immediately."""
        with self._lock:
            self._quota_conf[tenant] = (float(rate), float(burst))
            self._buckets.pop(tenant, None)

    # ------------------------------------------------------------- admission
    def admit(self, *, tenant=None, klass=None, deadline_ms=None,
              rows: int = 1, depth=None, capacity=None,
              wait_estimate_s=None) -> str:
        """Admit or shed one request. Returns the normalized class on
        admission; raises :class:`ShedError` (a ``QueueFullError``) on
        shed. Checks run cheapest-first and each is skipped when its
        signal was not supplied, so the default path (no headers, no
        quotas, no deadline) costs two dict lookups and one compare."""
        k = klass if klass in PRIORITY else normalize_class(klass)
        # 1) tenant quota: shed before the request touches any queue
        bucket = self._bucket_for(tenant or "default")
        if bucket is not None and not bucket.try_take(max(1, int(rows))):
            self._record_shed(k, "quota")
            raise ShedError(
                f"tenant {tenant or 'default'!r} quota exhausted "
                f"({bucket.rate:g}/s, burst {bucket.burst:g})", k, "quota")
        # 2) class watermark against observed backlog: batch first
        if depth is not None and capacity:
            frac = depth / float(capacity)
            if frac >= self.watermarks[k]:
                self._record_shed(k, "backpressure")
                raise ShedError(
                    f"{k} sheds at {self.watermarks[k]:.0%} of queue "
                    f"capacity (depth {depth}/{capacity})",
                    k, "backpressure")
            with self._lock:
                if frac > self.deepest_admitted_fraction:
                    self.deepest_admitted_fraction = frac
        # 3) deadline vs the derived wait estimate (the Retry-After
        #    signal): a request that cannot make it sheds NOW
        if deadline_ms is not None and wait_estimate_s is not None \
                and wait_estimate_s * 1000.0 > float(deadline_ms):
            self._record_shed(k, "deadline")
            raise ShedError(
                f"estimated wait {wait_estimate_s * 1000.0:.0f}ms exceeds "
                f"deadline {float(deadline_ms):.0f}ms", k, "deadline")
        with self._lock:
            self.admitted_total[k] += 1
        return k

    def _record_shed(self, klass: str, reason: str):
        with self._lock:
            self.shed_total[klass] += 1
            key = (klass, reason)
            self.shed_by_reason[key] = self.shed_by_reason.get(key, 0) + 1

    def record_shed(self, klass, reason: str = "backpressure"):
        """Account a shed decided OUTSIDE admit() — the router's
        all-hosts-overloaded 503 and the legacy QueueFullError path
        still count into the same per-class families."""
        self._record_shed(normalize_class(klass), reason)

    # --------------------------------------------------------------- metrics
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "admitted_total": dict(self.admitted_total),
                "shed_total": dict(self.shed_total),
                "shed_by_reason": {f"{k}/{r}": n for (k, r), n
                                   in sorted(self.shed_by_reason.items())},
                "quota_tokens": {t: round(b.peek(), 3)
                                 for t, b in self._buckets.items()},
                "deepest_admitted_fraction": round(
                    self.deepest_admitted_fraction, 4),
                "watermarks": dict(self.watermarks),
            }

    def metric_families(self, labels=None):
        """``dl4j_sched_*`` families (OBSERVABILITY.md): per-class
        admitted/shed counters (the satellite contract: a load test can
        watch batch shed while interactive is admitted), per-reason
        shed counters, and per-tenant quota-token gauges."""
        from deeplearning4j_tpu.observability.metrics import MetricFamily
        L = dict(labels or {})
        snap = self.snapshot()
        admitted = MetricFamily(
            "dl4j_sched_admitted_total", "counter",
            "Requests admitted by the scheduling core, per class")
        shed = MetricFamily(
            "dl4j_sched_shed_total", "counter",
            "Requests shed (503) by the scheduling core, per class — "
            "batch must rise before interactive under overload")
        for c in CLASSES:
            admitted.add(snap["admitted_total"][c], {**L, "class": c})
            shed.add(snap["shed_total"][c], {**L, "class": c})
        reason = MetricFamily(
            "dl4j_sched_shed_reason_total", "counter",
            "Sheds by (class, reason): quota | backpressure | deadline")
        for key, n in snap["shed_by_reason"].items():
            c, r = key.split("/", 1)
            reason.add(n, {**L, "class": c, "reason": r})
        tokens = MetricFamily(
            "dl4j_sched_quota_tokens", "gauge",
            "Token-bucket balance per tenant (refills at the quota rate)")
        for t, v in snap["quota_tokens"].items():
            tokens.add(v, {**L, "tenant": t})
        frac = MetricFamily(
            "dl4j_sched_deepest_admitted_fraction", "gauge",
            "High-water queue fraction an admitted request has seen")
        frac.add(snap["deepest_admitted_fraction"], L)
        return [admitted, shed, reason, tokens, frac]
