"""Classification evaluation.

Parity: eval/Evaluation.java (1,110 LoC; eval() :195, f1() :667,
accuracy() :681, ConfusionMatrix). Accumulation happens host-side in numpy
(cheap) over device-computed predictions; metrics match the reference's
definitions (per-class precision/recall/F1; macro-averaged f1(); micro
accuracy).
"""

from __future__ import annotations

import numpy as np


class ConfusionMatrix:
    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: np.ndarray, predicted: np.ndarray):
        np.add.at(self.matrix, (actual, predicted), 1)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def actual_total(self, cls: int) -> int:
        return int(self.matrix[cls].sum())

    def predicted_total(self, cls: int) -> int:
        return int(self.matrix[:, cls].sum())

    def __str__(self):
        return str(self.matrix)


class Evaluation:
    def __init__(self, num_classes: int | None = None, labels: list | None = None):
        self.class_names = labels
        self.num_classes = num_classes if num_classes else (
            len(labels) if labels else None)
        self.confusion: ConfusionMatrix | None = None
        if self.num_classes:
            self.confusion = ConfusionMatrix(self.num_classes)
        self.predictions: list = []  # Prediction records (meta-aware eval)

    # ------------------------------------------------------------------ eval
    def eval(self, labels, predictions, mask=None, meta=None):
        """Accumulate a batch. ``labels`` one-hot (or class indices),
        ``predictions`` probabilities/scores [batch(, time), classes].
        ``meta`` (optional): per-example record metadata list — each
        surviving example is recorded as a ``Prediction`` for
        error-tracing (Evaluation.java eval-with-metadata /
        meta/Prediction.java parity)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if meta is not None:
            meta = list(meta)
            if len(meta) != predictions.shape[0]:
                raise ValueError(
                    f"meta has {len(meta)} records for a batch of "
                    f"{predictions.shape[0]} examples")
        if predictions.ndim == 3:  # time series -> flatten (mask-aware)
            b, t, c = predictions.shape
            predictions = predictions.reshape(b * t, c)
            labels = labels.reshape(b * t, -1)
            if meta is not None:
                meta = [m for m in meta for _ in range(t)]
            if mask is not None:
                m = np.asarray(mask).reshape(b * t).astype(bool)
                predictions, labels = predictions[m], labels[m]
                if meta is not None:
                    meta = [md for md, keep in zip(meta, m) if keep]
        elif mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            predictions, labels = predictions[m], labels[m]
            if meta is not None:
                meta = [md for md, keep in zip(meta, m) if keep]
        if labels.ndim == 2 and labels.shape[1] > 1:
            actual = labels.argmax(axis=1)
            ncls = labels.shape[1]
        else:
            actual = labels.reshape(-1).astype(int)
            ncls = predictions.shape[1]
        if predictions.shape[1] == 1:
            # single-output binary head: threshold at 0.5 (Evaluation.java's
            # binary path), two-class confusion matrix
            predicted = (predictions.reshape(-1) > 0.5).astype(int)
            ncls = 2
        else:
            predicted = predictions.argmax(axis=1)
        if self.confusion is None:
            self.num_classes = ncls
            self.confusion = ConfusionMatrix(ncls)
        self.confusion.add(actual, predicted)
        if meta is not None:
            from deeplearning4j_tpu.eval.meta import Prediction
            self.predictions.extend(
                Prediction(int(a), int(p), md)
                for a, p, md in zip(actual, predicted, meta))

    # --------------------------------------------------------------- metrics
    def _tp(self, c):
        return self.confusion.get_count(c, c)

    def _fp(self, c):
        return self.confusion.predicted_total(c) - self._tp(c)

    def _fn(self, c):
        return self.confusion.actual_total(c) - self._tp(c)

    def accuracy(self) -> float:
        m = self.confusion.matrix
        total = m.sum()
        return float(np.trace(m) / total) if total else 0.0

    def precision(self, cls: int | None = None) -> float:
        if cls is not None:
            denom = self._tp(cls) + self._fp(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.precision(c) for c in range(self.num_classes)
                if self.confusion.actual_total(c) > 0 or
                self.confusion.predicted_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: int | None = None) -> float:
        if cls is not None:
            denom = self._tp(cls) + self._fn(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.recall(c) for c in range(self.num_classes)
                if self.confusion.actual_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: int | None = None) -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) else 0.0
        p, r = self.precision(), self.recall()
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def false_positive_rate(self, cls: int) -> float:
        fp = self._fp(cls)
        tn = self.confusion.matrix.sum() - self.confusion.actual_total(cls) - fp
        return fp / (fp + tn) if (fp + tn) else 0.0

    def matthews_correlation(self, cls: int) -> float:
        tp, fp, fn = self._tp(cls), self._fp(cls), self._fn(cls)
        tn = self.confusion.matrix.sum() - tp - fp - fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return float((tp * tn - fp * fn) / denom) if denom else 0.0

    def stats(self) -> str:
        lines = ["", "========================Evaluation Metrics========================"]
        lines.append(f" # of classes: {self.num_classes}")
        lines.append(f" Accuracy:  {self.accuracy():.4f}")
        lines.append(f" Precision: {self.precision():.4f}")
        lines.append(f" Recall:    {self.recall():.4f}")
        lines.append(f" F1 Score:  {self.f1():.4f}")
        lines.append("")
        lines.append("=========================Confusion Matrix=========================")
        lines.append(str(self.confusion))
        lines.append("==================================================================")
        return "\n".join(lines)

    def merge(self, other: "Evaluation"):
        """Combine accumulators (distributed eval reduction parity:
        spark IEvaluateFlatMapFunction result merging)."""
        if other.confusion is None:
            return self
        if self.confusion is None:
            self.num_classes = other.num_classes
            self.confusion = ConfusionMatrix(other.num_classes)
        self.confusion.matrix += other.confusion.matrix
        self.predictions.extend(other.predictions)
        return self

    # ----------------------------------------------- prediction metadata
    def get_prediction_errors(self):
        """Misclassified examples with their record metadata
        (Evaluation.getPredictionErrors parity; requires eval(..., meta=))."""
        return [p for p in self.predictions
                if p.actual_class != p.predicted_class]

    def get_predictions_by_actual_class(self, cls: int):
        return [p for p in self.predictions if p.actual_class == cls]

    def get_predictions_by_predicted_class(self, cls: int):
        return [p for p in self.predictions if p.predicted_class == cls]
