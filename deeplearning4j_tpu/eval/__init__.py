"""Evaluation suite (parity: deeplearning4j-nn/.../eval — Evaluation.java,
ROC.java, RegressionEvaluation.java, EvaluationBinary.java, ConfusionMatrix)."""

from deeplearning4j_tpu.eval.evaluation import Evaluation, ConfusionMatrix
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass
from deeplearning4j_tpu.eval.binary import EvaluationBinary
