"""Per-example prediction metadata (eval/meta/Prediction.java parity):
actual class, predicted class, and the caller-supplied record metadata
object that produced the example (e.g. a filename or row id), so
misclassified examples can be traced back to their source records."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


@dataclass
class Prediction:
    actual_class: int
    predicted_class: int
    record_meta_data: Any = None

    def __str__(self):
        return (f"Prediction(actualClass={self.actual_class},"
                f"predictedClass={self.predicted_class},"
                f"RecordMetaData={self.record_meta_data})")
