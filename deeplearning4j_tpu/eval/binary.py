"""Per-output binary evaluation (parity: eval/EvaluationBinary.java —
independent accuracy/precision/recall/F1 per output column at threshold 0.5)."""

from __future__ import annotations

import numpy as np


class EvaluationBinary:
    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        y = labels >= 0.5
        p = predictions >= self.threshold
        if self.tp is None:
            c = labels.shape[-1]
            self.tp = np.zeros(c, np.int64)
            self.fp = np.zeros(c, np.int64)
            self.tn = np.zeros(c, np.int64)
            self.fn = np.zeros(c, np.int64)
        self.tp += (p & y).sum(axis=0)
        self.fp += (p & ~y).sum(axis=0)
        self.tn += (~p & ~y).sum(axis=0)
        self.fn += (~p & y).sum(axis=0)

    def num_outputs(self):
        return 0 if self.tp is None else len(self.tp)

    def accuracy(self, col: int) -> float:
        total = self.tp[col] + self.fp[col] + self.tn[col] + self.fn[col]
        return float((self.tp[col] + self.tn[col]) / total) if total else 0.0

    def precision(self, col: int) -> float:
        d = self.tp[col] + self.fp[col]
        return float(self.tp[col] / d) if d else 0.0

    def recall(self, col: int) -> float:
        d = self.tp[col] + self.fn[col]
        return float(self.tp[col] / d) if d else 0.0

    def f1(self, col: int) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def stats(self) -> str:
        lines = ["Output    Acc      Prec     Recall   F1"]
        for c in range(self.num_outputs()):
            lines.append(f"{c:<10}{self.accuracy(c):<9.4f}{self.precision(c):<9.4f}"
                         f"{self.recall(c):<9.4f}{self.f1(c):.4f}")
        return "\n".join(lines)
