"""ROC / AUC evaluation (parity: eval/ROC.java, ROCBinary.java,
ROCMultiClass.java — threshold-stepped ROC curves and AUC).

The reference builds curves from ``thresholdSteps`` fixed thresholds; we
accumulate per-threshold TP/FP/FN/TN counts the same way (streaming-friendly,
bounded memory) and integrate AUC by trapezoid.
"""

from __future__ import annotations

import numpy as np


class ROC:
    """Binary ROC: labels are 1-column {0,1} or 2-column one-hot (positive
    class = column 1, matching the reference)."""

    def __init__(self, threshold_steps: int = 100):
        self.threshold_steps = threshold_steps
        self.thresholds = np.linspace(0.0, 1.0, threshold_steps + 1)
        self.tp = np.zeros(threshold_steps + 1, dtype=np.int64)
        self.fp = np.zeros(threshold_steps + 1, dtype=np.int64)
        self.fn = np.zeros(threshold_steps + 1, dtype=np.int64)
        self.tn = np.zeros(threshold_steps + 1, dtype=np.int64)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim >= 2 and labels.shape[-1] == 2:
            y = labels[..., 1].reshape(-1)
            p = predictions[..., 1].reshape(-1)
        else:
            y = labels.reshape(-1)
            p = predictions.reshape(-1)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            y, p = y[m], p[m]
        y = y.astype(bool)
        # vectorized over thresholds: predicted-positive = p >= t
        pred_pos = p[None, :] >= self.thresholds[:, None]
        self.tp += (pred_pos & y[None, :]).sum(axis=1)
        self.fp += (pred_pos & ~y[None, :]).sum(axis=1)
        self.fn += (~pred_pos & y[None, :]).sum(axis=1)
        self.tn += (~pred_pos & ~y[None, :]).sum(axis=1)

    def get_roc_curve(self):
        pos = self.tp + self.fn
        neg = self.fp + self.tn
        tpr = np.where(pos > 0, self.tp / np.maximum(pos, 1), 0.0)
        fpr = np.where(neg > 0, self.fp / np.maximum(neg, 1), 0.0)
        return fpr, tpr

    def calculate_auc(self) -> float:
        fpr, tpr = self.get_roc_curve()
        order = np.argsort(fpr, kind="stable")
        fpr, tpr = fpr[order], tpr[order]
        fpr = np.concatenate([[0.0], fpr, [1.0]])
        tpr = np.concatenate([[0.0], tpr, [1.0]])
        return float(np.trapezoid(tpr, fpr))

    def get_precision_recall_curve(self):
        prec = np.where(self.tp + self.fp > 0,
                        self.tp / np.maximum(self.tp + self.fp, 1), 1.0)
        rec = np.where(self.tp + self.fn > 0,
                       self.tp / np.maximum(self.tp + self.fn, 1), 0.0)
        return rec, prec


class ROCBinary:
    """Per-output independent binary ROC (ROCBinary.java parity) for
    multi-label sigmoid outputs."""

    def __init__(self, threshold_steps: int = 100):
        self.threshold_steps = threshold_steps
        self.rocs: list[ROC] | None = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        n_out = labels.shape[-1]
        if self.rocs is None:
            self.rocs = [ROC(self.threshold_steps) for _ in range(n_out)]
        for c in range(n_out):
            self.rocs[c].eval(labels[..., c], predictions[..., c], mask)

    def calculate_auc(self, col: int) -> float:
        return self.rocs[col].calculate_auc()

    def average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self.rocs]))


class ROCMultiClass:
    """One-vs-all ROC per class (ROCMultiClass.java parity)."""

    def __init__(self, threshold_steps: int = 100):
        self.threshold_steps = threshold_steps
        self.rocs: list[ROC] | None = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        ncls = labels.shape[-1]
        if self.rocs is None:
            self.rocs = [ROC(self.threshold_steps) for _ in range(ncls)]
        for c in range(ncls):
            self.rocs[c].eval(labels[..., c], predictions[..., c], mask)

    def calculate_auc(self, cls: int) -> float:
        return self.rocs[cls].calculate_auc()

    def average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self.rocs]))
