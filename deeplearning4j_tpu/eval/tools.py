"""Self-contained HTML evaluation reports.

Parity: deeplearning4j-core evaluation/EvaluationTools.java
(exportRocChartsToHtmlFile / exportevaluationToHtmlFile) — the reference
renders ROC + precision/recall charts and the confusion matrix through
its UI component library; here the charts are inline SVG with zero
external assets (works in zero-egress environments, same stance as
ui/server.py)."""

from __future__ import annotations

import html

import numpy as np

_STYLE = """
body{font-family:system-ui,sans-serif;margin:18px;color:#222}
h2{color:#1a237e} h3{margin:18px 0 6px;font-size:15px;color:#444}
.row{display:flex;flex-wrap:wrap;gap:22px}
svg{background:#fff;border:1px solid #ccc}
table{border-collapse:collapse;font-size:13px;margin:8px 0}
td,th{border:1px solid #ddd;padding:4px 9px;text-align:right}
th{background:#f0f0f4}
.diag{background:#e4efe4;font-weight:600}
"""


def _svg_curve(xs, ys, *, title, xlabel, ylabel, diagonal=False,
               size=360, pad=42):
    """One framed SVG line chart on the unit square."""
    s = size - 2 * pad

    def X(v):
        return pad + float(v) * s

    def Y(v):
        return size - pad - float(v) * s

    pts = " ".join(f"{X(x):.1f},{Y(y):.1f}" for x, y in zip(xs, ys))
    grid = "".join(
        f'<line x1="{X(v)}" y1="{Y(0)}" x2="{X(v)}" y2="{Y(1)}" '
        f'stroke="#eee"/>'
        f'<line x1="{X(0)}" y1="{Y(v)}" x2="{X(1)}" y2="{Y(v)}" '
        f'stroke="#eee"/>'
        f'<text x="{X(v)}" y="{size - pad + 16}" font-size="10" '
        f'text-anchor="middle">{v:.1f}</text>'
        f'<text x="{pad - 8}" y="{Y(v) + 3}" font-size="10" '
        f'text-anchor="end">{v:.1f}</text>'
        for v in (0.0, 0.25, 0.5, 0.75, 1.0))
    diag = (f'<line x1="{X(0)}" y1="{Y(0)}" x2="{X(1)}" y2="{Y(1)}" '
            f'stroke="#bbb" stroke-dasharray="4"/>' if diagonal else "")
    return f"""<svg width="{size}" height="{size}">
<text x="{size / 2}" y="16" text-anchor="middle" font-size="13"
 font-weight="600">{html.escape(title)}</text>
{grid}{diag}
<rect x="{pad}" y="{pad}" width="{s}" height="{s}" fill="none"
 stroke="#999"/>
<polyline points="{pts}" fill="none" stroke="#1a74bb" stroke-width="2"/>
<text x="{size / 2}" y="{size - 6}" text-anchor="middle"
 font-size="11">{html.escape(xlabel)}</text>
<text x="12" y="{size / 2}" font-size="11" text-anchor="middle"
 transform="rotate(-90 12 {size / 2})">{html.escape(ylabel)}</text>
</svg>"""


def roc_chart_html(roc, title: str = "ROC") -> str:
    """ROC + precision/recall chart pair for one ``ROC`` accumulator."""
    fpr, tpr = roc.get_roc_curve()
    order = np.argsort(fpr, kind="stable")
    rec, prec = roc.get_precision_recall_curve()
    ro = np.argsort(rec, kind="stable")
    auc = roc.calculate_auc()
    return (f'<div class="row">'
            + _svg_curve(fpr[order], tpr[order],
                         title=f"{title} (AUC {auc:.4f})",
                         xlabel="False positive rate",
                         ylabel="True positive rate", diagonal=True)
            + _svg_curve(rec[ro], prec[ro], title=f"{title} P-R",
                         xlabel="Recall", ylabel="Precision")
            + "</div>")


def export_roc_charts_to_html_file(roc, path: str,
                                   title: str = "ROC evaluation"):
    """EvaluationTools.exportRocChartsToHtmlFile parity. ``roc`` is a
    ``ROC`` or a ``ROCMultiClass`` (one chart pair per class)."""
    body = []
    if hasattr(roc, "rocs"):  # ROCMultiClass / ROCBinary
        for i, r in enumerate(getattr(roc, "rocs")):
            body.append(roc_chart_html(r, title=f"class {i}"))
    else:
        body.append(roc_chart_html(roc, title="ROC"))
    _write_html(path, title, "\n".join(body))


def evaluation_html(ev, class_names=None) -> str:
    """Confusion matrix + per-class metric table for an ``Evaluation``."""
    n = ev.num_classes
    names = class_names or ev.class_names or [str(i) for i in range(n)]
    head = "".join(f"<th>{html.escape(str(c))}</th>" for c in names)
    rows = []
    for i in range(n):
        cells = "".join(
            f'<td class="{"diag" if i == j else ""}">'
            f"{ev.confusion.get_count(i, j)}</td>" for j in range(n))
        rows.append(f"<tr><th>{html.escape(str(names[i]))}</th>{cells}</tr>")
    conf = (f"<h3>Confusion matrix (rows = actual)</h3>"
            f"<table><tr><th></th>{head}</tr>{''.join(rows)}</table>")
    met_rows = "".join(
        f"<tr><th>{html.escape(str(names[c]))}</th>"
        f"<td>{ev.precision(c):.4f}</td><td>{ev.recall(c):.4f}</td>"
        f"<td>{ev.f1(c):.4f}</td></tr>" for c in range(n))
    mets = (f"<h3>Per-class metrics</h3><table><tr><th>class</th>"
            f"<th>precision</th><th>recall</th><th>f1</th></tr>"
            f"{met_rows}</table>"
            f"<p>accuracy {ev.accuracy():.4f} — macro-F1 {ev.f1():.4f}</p>")
    return conf + mets


def export_evaluation_to_html_file(ev, path: str,
                                   title: str = "Classification evaluation",
                                   class_names=None):
    """EvaluationTools evaluation-report parity (confusion + metrics)."""
    _write_html(path, title, evaluation_html(ev, class_names))


def _write_html(path: str, title: str, body: str):
    with open(path, "w") as f:
        f.write(f"<!doctype html><html><head><meta charset='utf-8'>"
                f"<title>{html.escape(title)}</title>"
                f"<style>{_STYLE}</style></head><body>"
                f"<h2>{html.escape(title)}</h2>{body}</body></html>")
