"""Self-contained HTML evaluation reports.

Parity: deeplearning4j-core evaluation/EvaluationTools.java
(exportRocChartsToHtmlFile / exportEvaluationToHtmlFile). The reference
composes its reports from the deeplearning4j-ui-components library; this
module does the same through ``ui/components.py`` (ChartLine for
ROC/precision-recall, ComponentTable for the confusion matrix and metric
tables, rendered to one standalone page with inline SVG — zero external
assets, same stance as ui/server.py)."""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.ui.components import (ChartLine, ComponentDiv,
                                              ComponentTable, ComponentText,
                                              Style,
                                              render_components_to_file)

def _chart_style() -> Style:
    """Per-chart style instance (Style is mutable — never share one)."""
    return Style(width=380, height=380)


def _unit_chart(title, xlabel, ylabel, xs, ys, diagonal=False) -> ChartLine:
    c = ChartLine(title, _chart_style(), xlabel=xlabel, ylabel=ylabel)
    if diagonal:
        c.add_series("chance", [0.0, 1.0], [0.0, 1.0])
    c.add_series(title, list(map(float, xs)), list(map(float, ys)))
    return c


def roc_components(roc, title: str = "ROC"):
    """ROC + precision/recall chart pair for one ``ROC`` accumulator, as
    UI components (the reference builds the same pair of ChartLine
    components in EvaluationTools.rocChart)."""
    fpr, tpr = roc.get_roc_curve()
    order = np.argsort(fpr, kind="stable")
    rec, prec = roc.get_precision_recall_curve()
    ro = np.argsort(rec, kind="stable")
    auc = roc.calculate_auc()
    return ComponentDiv(
        _unit_chart(f"{title} (AUC {auc:.4f})", "False positive rate",
                    "True positive rate", fpr[order], tpr[order],
                    diagonal=True),
        _unit_chart(f"{title} P-R", "Recall", "Precision", rec[ro],
                    prec[ro]))


def roc_chart_html(roc, title: str = "ROC") -> str:
    """Rendered HTML for one ROC chart pair (back-compat surface)."""
    return roc_components(roc, title).render()


def export_roc_charts_to_html_file(roc, path: str,
                                   title: str = "ROC evaluation"):
    """EvaluationTools.exportRocChartsToHtmlFile parity. ``roc`` is a
    ``ROC`` or a ``ROCMultiClass`` (one chart pair per class)."""
    comps = []
    if hasattr(roc, "rocs"):  # ROCMultiClass / ROCBinary
        for i, r in enumerate(getattr(roc, "rocs")):
            comps.append(roc_components(r, title=f"class {i}"))
    else:
        comps.append(roc_components(roc, title="ROC"))
    render_components_to_file(comps, path, title)


def evaluation_components(ev, class_names=None):
    """Confusion matrix + per-class metric tables for an ``Evaluation``,
    as UI components."""
    n = ev.num_classes
    names = class_names or ev.class_names or [str(i) for i in range(n)]
    conf_rows = [[str(names[i])]
                 + [str(ev.confusion.get_count(i, j)) for j in range(n)]
                 for i in range(n)]
    conf = ComponentTable(
        [""] + [str(c) for c in names], conf_rows,
        title="Confusion matrix (rows = actual)",
        highlight_cells=[(i, i + 1) for i in range(n)])
    met_rows = [[str(names[c]), f"{ev.precision(c):.4f}",
                 f"{ev.recall(c):.4f}", f"{ev.f1(c):.4f}"]
                for c in range(n)]
    mets = ComponentTable(["class", "precision", "recall", "f1"], met_rows,
                          title="Per-class metrics")
    summary = ComponentText(
        f"accuracy {ev.accuracy():.4f} — macro-F1 {ev.f1():.4f}")
    return [conf, mets, summary]


def evaluation_html(ev, class_names=None) -> str:
    """Rendered HTML fragment (back-compat surface)."""
    return "\n".join(c.render()
                     for c in evaluation_components(ev, class_names))


def export_evaluation_to_html_file(ev, path: str,
                                   title: str = "Classification evaluation",
                                   class_names=None):
    """EvaluationTools evaluation-report parity (confusion + metrics)."""
    render_components_to_file(evaluation_components(ev, class_names), path,
                              title)
