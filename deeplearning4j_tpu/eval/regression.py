"""Regression evaluation (parity: eval/RegressionEvaluation.java — per-column
MSE, MAE, RMSE, RSE, correlation R)."""

from __future__ import annotations

import numpy as np


class RegressionEvaluation:
    def __init__(self, column_names: list | None = None):
        self.column_names = column_names
        self._n = 0
        self._sum_err2 = None
        self._sum_abs = None
        self._sum_label = None
        self._sum_label2 = None
        self._sum_pred = None
        self._sum_pred2 = None
        self._sum_lp = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        if self._sum_err2 is None:
            c = labels.shape[-1]
            for name in ("_sum_err2", "_sum_abs", "_sum_label", "_sum_label2",
                         "_sum_pred", "_sum_pred2", "_sum_lp"):
                setattr(self, name, np.zeros(c))
        err = predictions - labels
        self._n += labels.shape[0]
        self._sum_err2 += (err ** 2).sum(axis=0)
        self._sum_abs += np.abs(err).sum(axis=0)
        self._sum_label += labels.sum(axis=0)
        self._sum_label2 += (labels ** 2).sum(axis=0)
        self._sum_pred += predictions.sum(axis=0)
        self._sum_pred2 += (predictions ** 2).sum(axis=0)
        self._sum_lp += (labels * predictions).sum(axis=0)

    def mean_squared_error(self, col: int) -> float:
        return float(self._sum_err2[col] / self._n)

    def mean_absolute_error(self, col: int) -> float:
        return float(self._sum_abs[col] / self._n)

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def relative_squared_error(self, col: int) -> float:
        mean_label = self._sum_label[col] / self._n
        ss_tot = self._sum_label2[col] - self._n * mean_label ** 2
        return float(self._sum_err2[col] / ss_tot) if ss_tot else 0.0

    def correlation_r2(self, col: int) -> float:
        n = self._n
        num = n * self._sum_lp[col] - self._sum_label[col] * self._sum_pred[col]
        den = np.sqrt(n * self._sum_label2[col] - self._sum_label[col] ** 2) * \
            np.sqrt(n * self._sum_pred2[col] - self._sum_pred[col] ** 2)
        return float(num / den) if den else 0.0

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self._sum_err2 / self._n))

    def average_mean_absolute_error(self) -> float:
        return float(np.mean(self._sum_abs / self._n))

    def num_columns(self) -> int:
        return 0 if self._sum_err2 is None else len(self._sum_err2)

    def stats(self) -> str:
        lines = ["Column    MSE            MAE            RMSE           RSE            R"]
        for c in range(self.num_columns()):
            name = (self.column_names[c] if self.column_names else f"col_{c}")
            lines.append(
                f"{name:<10}{self.mean_squared_error(c):<15.6g}"
                f"{self.mean_absolute_error(c):<15.6g}"
                f"{self.root_mean_squared_error(c):<15.6g}"
                f"{self.relative_squared_error(c):<15.6g}"
                f"{self.correlation_r2(c):.6g}")
        return "\n".join(lines)
