"""``@guarded_by`` — declare which lock protects which attributes.

Threaded classes register their lock-guarded state at class level:

    @guarded_by("_cond", "_pending", "_stopping", "_crashed", "_thread")
    class MicroBatcher: ...

The declaration does two jobs:

- **Statically** (analysis/concurrency.py): the AST lint reads the
  decorator literally and flags any write to a registered attribute
  (assignment, augmented assignment, item write/delete, or a mutator
  method call like ``.append``/``.clear``/``.update``) that is not
  lexically inside ``with self.<lock>:`` — the DL4J-C005 finding.
  Methods whose name ends in ``_locked`` are treated as running with
  the lock already held (the existing ``_gather_locked`` convention),
  and ``__init__`` is exempt (no other thread can hold a reference
  yet).
- **At runtime**: the registry is kept on the class as
  ``__guarded_by__`` (attr -> lock attr name) so tests and tools can
  introspect the declared contract.

The decorator itself is deliberately free: no wrapping, no
``__setattr__`` hook, zero per-access cost — enforcement lives in the
lint, not the hot path. This module must therefore stay import-light
(the threaded serving/datapipe modules import it).
"""

from __future__ import annotations

__all__ = ["guarded_by"]


def guarded_by(lock_attr: str, *attrs: str):
    """Class decorator: register ``attrs`` as guarded by
    ``self.<lock_attr>``. Stack multiple decorators when a class uses
    more than one lock. The registry accumulates across subclasses."""
    if not attrs:
        raise ValueError("guarded_by needs at least one guarded attribute")

    def deco(cls):
        reg = dict(getattr(cls, "__guarded_by__", {}))
        for a in attrs:
            reg[a] = lock_attr
        cls.__guarded_by__ = reg
        return cls

    return deco
