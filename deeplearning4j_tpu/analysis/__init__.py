"""Correctness-analysis subsystem: the checks that enforce the stack's
fragile contracts *before* a chaos demo trips over them.

The runtime spans 20+ threaded modules (batchers, replica fleets, the
front-door router, heartbeat pushers, checkpoint writers, prefetchers)
and sells three contracts — bit-identity, zero-fresh-compiles warm
boots, attributed≈wall goodput — that receipts only verify after the
fact. This package verifies them by analysis (ANALYSIS.md):

- :mod:`~deeplearning4j_tpu.analysis.concurrency` — an AST pass over
  the source tree: unguarded ``acquire()``, untimed blocking calls
  (worse while a lock is held), non-daemon threads, and writes to
  ``@guarded_by``-registered attributes outside their lock.
- :mod:`~deeplearning4j_tpu.analysis.jaxpr_lint` — traces the jitted
  fit steps and serving forwards of the real models and walks the
  closed jaxprs for dtype-promotion hazards, retrace bombs, donation
  misses, and primitives outside the determinism allowlist.
- :mod:`~deeplearning4j_tpu.analysis.lockorder` — an opt-in
  instrumented lock wrapper (``DL4J_TPU_LOCK_CHECK=1``, default-on
  under pytest) recording the cross-thread acquisition-order graph;
  cycles are would-be deadlocks, long holds land in the span tracer.

Everything reports :class:`Finding`s; ``scripts/static_check.py`` gates
them against the committed ``ANALYSIS_BASELINE.json`` the same way
``check_budgets.py`` gates efficiency receipts.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from deeplearning4j_tpu.analysis.guards import guarded_by

__all__ = ["Finding", "guarded_by", "CODES"]

#: finding code -> one-line meaning (the full table lives in ANALYSIS.md)
CODES = {
    "DL4J-C001": "lock acquire() without a guaranteed release "
                 "(use `with` or try/finally)",
    "DL4J-C002": "untimed blocking call while a lock is held",
    "DL4J-C003": "untimed blocking call (no timeout/deadline)",
    "DL4J-C004": "non-daemon thread with no join-on-shutdown",
    "DL4J-C005": "write to a @guarded_by attribute outside its lock",
    "DL4J-J000": "analysis target failed to trace",
    "DL4J-J001": "f32 matmul/conv under a half-precision compute policy",
    "DL4J-J002": "x64 weak-type promotion (float64 value in the jaxpr)",
    "DL4J-J003": "Python-scalar retrace bomb (jit cache grows per call)",
    "DL4J-J004": "donation miss: fit step re-allocates params/opt_state",
    "DL4J-J005": "primitive outside the determinism allowlist",
    "DL4J-L001": "lock acquisition-order cycle (would-be deadlock)",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analysis finding. ``fingerprint()`` deliberately excludes the
    line number so the committed baseline survives unrelated edits that
    shift code up or down a file."""

    code: str      #: DL4J-Cxxx / DL4J-Jxxx / DL4J-L001
    path: str      #: repo-relative source path, or the jaxpr target name
    line: int      #: 1-based line (0 when not tied to a source line)
    symbol: str    #: enclosing Class.method / function / target symbol
    message: str   #: human-readable detail (stable: no line numbers)

    def fingerprint(self) -> str:
        return f"{self.code}|{self.path}|{self.symbol}|{self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "Finding":
        return Finding(code=d["code"], path=d["path"],
                       line=int(d.get("line", 0)),
                       symbol=d.get("symbol", ""),
                       message=d.get("message", ""))

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{self.code} {loc} [{self.symbol}] {self.message}"


def sort_findings(findings: List[Finding]) -> List[Finding]:
    """Stable report order: by code, then path, then line."""
    return sorted(findings, key=lambda f: (f.code, f.path, f.line,
                                           f.symbol, f.message))
