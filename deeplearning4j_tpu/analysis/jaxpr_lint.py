"""Jaxpr hazard lint: trace the real jitted programs, walk the IR.

PR 6 (precision) and PR 13 (transformers) each hand-fixed the same
class of bug once: a silent f32 matmul inside a bf16 policy, a Python
scalar baked into a trace forcing a recompile per step, a fit step that
re-allocated its parameter buffers because ``donate_argnums`` was
dropped. This pass makes those one-off fixes a standing check: it
builds the *production* jitted callables — ``net._build_train_step()``
and the serving ``_get_apply`` forward — for both net classes and the
zoo models (incl. ``gpt_mini``), traces them on tiny dummy batches
(host-only: ``make_jaxpr`` / ``lower``, never ``compile``), and walks
the closed jaxpr recursively (into scan/while/pjit sub-jaxprs) for:

- **DL4J-J001** — a ``dot_general``/``conv_general_dilated`` producing
  float32 under a half-precision compute policy: the matmul the policy
  was supposed to run in bf16/f16 silently upcast.
- **DL4J-J002** — any float64 value in the jaxpr: an x64 weak-type
  promotion that doubles memory and voids cross-backend bit-identity.
- **DL4J-J003** — retrace bomb: lowering the same callable twice with
  value-varied (shape-identical) arguments yields different StableHLO,
  i.e. some input value was baked into the trace as a constant and
  every new value will pay a fresh trace+compile.
- **DL4J-J004** — donation miss: a fit step whose lowering carries no
  buffer-donation markers re-allocates params/opt_state every step.
- **DL4J-J005** — a primitive outside the determinism allowlist below,
  which would void the bit-identity contract (IDENTITY.md).

Findings are :class:`~deeplearning4j_tpu.analysis.Finding`s with
``path="<jaxpr>"`` and ``symbol=<target name>``; targets that fail to
build at all surface as **DL4J-J000** rather than a silent skip.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.analysis import Finding

__all__ = ["list_targets", "lint_target", "lint_all",
            "DETERMINISM_ALLOWLIST"]

#: Primitives the bit-identity contract trusts: shipped models must not
#: stray outside this set without an explicit review (grow it in the
#: same PR that introduces the new op, with an IDENTITY.md note).
DETERMINISM_ALLOWLIST = frozenset({
    # structure / data movement
    "add_any", "broadcast_in_dim", "concatenate", "convert_element_type",
    "copy", "device_put", "dynamic_slice", "dynamic_update_slice",
    "gather", "iota", "pad", "reshape", "rev", "scatter", "scatter-add",
    "scatter_add", "select_n", "slice", "squeeze", "transpose",
    # control flow / staging
    "closed_call", "cond", "custom_jvp_call", "custom_vjp_call",
    "custom_vjp_call_jaxpr", "pjit", "remat", "remat2", "scan", "while",
    # elementwise math
    "abs", "add", "and", "cbrt", "ceil", "clamp", "cos", "cosh", "div",
    "eq", "erf", "exp", "expm1", "floor", "ge", "gt", "integer_pow",
    "is_finite", "le", "log", "log1p", "logistic", "lt", "max", "min",
    "mul", "ne", "neg", "not", "or", "pow", "rem", "round", "rsqrt",
    "sign", "sin", "sinh", "sqrt", "square", "stop_gradient", "sub",
    "tan", "tanh", "xor",
    # reductions / linalg / windows (XLA lowers these without atomics —
    # the pooling fwd/bwd pair is bit-stable across runs)
    "argmax", "argmin", "cumsum", "cumlogsumexp", "cummax", "dot_general",
    "conv_general_dilated", "reduce_and", "reduce_max", "reduce_min",
    "reduce_or", "reduce_precision", "reduce_prod", "reduce_sum",
    "reduce_window_max", "reduce_window_min", "reduce_window_sum",
    "select_and_scatter_add", "sort",
    # RNG (threefry is the deterministic counter-based generator)
    "random_bits", "random_fold_in", "random_seed", "random_split",
    "random_unwrap", "random_wrap", "threefry2x32",
    # collectives (deterministic reductions on a fixed mesh)
    "all_gather", "all_to_all", "ppermute", "psum", "pmax", "pmin",
})

_HALF_DTYPES = ("bfloat16", "float16")
_MATMUL_PRIMS = ("dot_general", "conv_general_dilated")


# --------------------------------------------------------------------------
# jaxpr walking
# --------------------------------------------------------------------------

def _iter_eqns(jaxpr):
    """Yield every eqn in a (closed) jaxpr, recursing into sub-jaxprs
    carried in eqn params (pjit/scan/while/cond/custom_vjp...)."""
    import jax.core as jcore

    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else [v]):
                if isinstance(sub, (jcore.Jaxpr, jcore.ClosedJaxpr)):
                    yield from _iter_eqns(sub)


def _check_ir(closed, target: str, compute_dtype: str) -> List[Finding]:
    """J001 + J002 + J005 over one traced program (deduped messages)."""
    findings: Dict[str, Finding] = {}

    def emit(code, message):
        f = Finding(code=code, path="<jaxpr>", line=0, symbol=target,
                    message=message)
        findings.setdefault(f.fingerprint(), f)

    for eqn in _iter_eqns(closed):
        prim = eqn.primitive.name
        out_dtypes = {str(getattr(v.aval, "dtype", ""))
                      for v in eqn.outvars if hasattr(v, "aval")}
        if compute_dtype in _HALF_DTYPES and prim in _MATMUL_PRIMS \
                and "float32" in out_dtypes:
            emit("DL4J-J001",
                 f"{prim} produces float32 under a {compute_dtype} "
                 "compute policy")
        if "float64" in out_dtypes:
            emit("DL4J-J002", f"{prim} produces float64 (x64 weak-type "
                              "promotion)")
        if prim not in DETERMINISM_ALLOWLIST:
            emit("DL4J-J005",
                 f"primitive '{prim}' outside the determinism allowlist")
    return list(findings.values())


def _check_retrace(text_a: str, text_b: str, target: str) -> List[Finding]:
    """J003: two lowerings with value-varied, shape-identical args must
    produce identical StableHLO — a diff means a value got baked in."""
    if text_a != text_b:
        return [Finding(
            code="DL4J-J003", path="<jaxpr>", line=0, symbol=target,
            message="lowering differs between value-varied calls of the "
                    "same shape (a Python scalar/const is baked into the "
                    "trace; every new value retraces)")]
    return []


def _check_donation(lowered_text: str, target: str) -> List[Finding]:
    """J004: a fit step's lowering must carry buffer-donation markers
    for the params/opt_state operands."""
    if "tf.aliasing_output" in lowered_text \
            or "jax.buffer_donor" in lowered_text:
        return []
    return [Finding(
        code="DL4J-J004", path="<jaxpr>", line=0, symbol=target,
        message="no buffer-donation markers in the step lowering "
                "(donate_argnums dropped: params/opt_state re-allocate "
                "every step)")]


# --------------------------------------------------------------------------
# targets: the production jitted programs, on tiny dummy batches
# --------------------------------------------------------------------------

def _fit_args(net, variant: int, row=None, label_row=None):
    """Dummy fit-step args mirroring fit_batch's dispatch, with every
    *value* varied by ``variant`` while shapes/dtypes stay fixed (the
    J003 probe needs two such sets). ``row``/``label_row`` override the
    server-side shape inference (sequence models have no fixed length
    to infer)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.compilecache.precompile import (
        _infer_row_shapes, _output_widths)

    batch = 2
    row_shapes = [row] if row is not None else _infer_row_shapes(net)
    if row_shapes is None:
        raise ValueError(f"cannot infer input shapes for {type(net)}")
    fill = float(variant) * 0.25
    it = jnp.asarray(variant, jnp.int32)
    rng = jax.random.PRNGKey(variant)
    if hasattr(net.conf, "network_inputs"):        # ComputationGraph
        inputs = {name: jnp.full((batch,) + tuple(s), fill, jnp.float32)
                  for name, s in zip(net.conf.network_inputs, row_shapes)}
        labels = [jnp.full((batch, n), fill, jnp.float32)
                  for n in _output_widths(net)]
        return (net.params, net.state, net.opt_state, it, inputs, labels,
                {}, None, rng)
    label_row = label_row if label_row is not None \
        else (_output_widths(net)[0],)
    x = jnp.full((batch,) + tuple(row_shapes[0]), fill, jnp.float32)
    y = jnp.full((batch,) + tuple(label_row), fill, jnp.float32)
    return (net.params, net.state, net.opt_state, it, x, y, None, None, rng)


def _forward_args(net, variant: int, row=None):
    import jax.numpy as jnp
    from deeplearning4j_tpu.compilecache.precompile import _infer_row_shapes

    row_shapes = [row] if row is not None else _infer_row_shapes(net)
    x = jnp.full((2,) + tuple(row_shapes[0]), float(variant), jnp.float32)
    return (net.params, net.state, x, None, None)


def _tiny_mlp():
    from deeplearning4j_tpu.zoo import models as zoo
    return zoo.mnist_mlp()


def _tiny_gpt():
    from deeplearning4j_tpu.zoo import models as zoo
    return zoo.gpt_mini(vocab_size=11, width=16, n_layers=2, n_heads=2,
                        max_len=8)


def _tiny_lenet():
    from deeplearning4j_tpu.zoo import models as zoo
    return zoo.lenet()


def _tiny_graph():
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import Dense, Output
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.updater import Adam

    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater(Adam(1e-3)).graph_builder()
            .add_inputs("in")
            .add_layer("d1", Dense(n_out=6, activation="tanh"), "in")
            .add_layer("out", Output(n_out=3, activation="softmax",
                                     loss="mcxent"), "d1")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(5))
            .build())
    return ComputationGraph(conf).init()


def _target(make_net: Callable, kind: str, row=None, label_row=None):
    """-> (jit_fn, args_a, args_b, compute_dtype, check_donation)."""
    net = make_net()
    compute = net.conf.global_conf.dtype.compute_dtype
    if kind == "fit":
        return (net._build_train_step(), _fit_args(net, 0, row, label_row),
                _fit_args(net, 1, row, label_row), compute, True)
    return (net._get_apply(collect=False, train=False),
            _forward_args(net, 0, row), _forward_args(net, 1, row),
            compute, False)


#: target name -> zero-arg builder (kept lazy: building traces a model)
TARGETS: Dict[str, Callable] = {
    "mnist_mlp.fit_step": lambda: _target(_tiny_mlp, "fit"),
    "mnist_mlp.forward": lambda: _target(_tiny_mlp, "forward"),
    "lenet.fit_step": lambda: _target(_tiny_lenet, "fit"),
    # one-hot token rows (T=8, V=11): the sequence length is a serving
    # choice, not inferable from the conf
    "gpt_mini.fit_step": lambda: _target(_tiny_gpt, "fit", row=(8, 11),
                                         label_row=(8, 11)),
    "gpt_mini.forward": lambda: _target(_tiny_gpt, "forward", row=(8, 11)),
    "graph.fit_step": lambda: _target(_tiny_graph, "fit"),
}


def list_targets() -> List[str]:
    return sorted(TARGETS)


def lint_target(name: str) -> List[Finding]:
    """All jaxpr checks for one named target. A target that fails to
    build/trace is itself a finding (J000), never a silent skip."""
    import jax

    try:
        jit_fn, args_a, args_b, compute, want_donation = TARGETS[name]()
        closed = jax.make_jaxpr(jit_fn)(*args_a)
        findings = _check_ir(closed, name, compute)
        lowered_a = jit_fn.lower(*args_a).as_text()
        lowered_b = jit_fn.lower(*args_b).as_text()
        findings.extend(_check_retrace(lowered_a, lowered_b, name))
        if want_donation:
            findings.extend(_check_donation(lowered_a, name))
        return findings
    except Exception as e:  # noqa: BLE001 — any failure is a finding
        return [Finding(
            code="DL4J-J000", path="<jaxpr>", line=0, symbol=name,
            message=f"target failed to trace: {type(e).__name__}: {e}")]


def lint_all(names: Optional[List[str]] = None) -> List[Finding]:
    out: List[Finding] = []
    for name in (names or list_targets()):
        out.extend(lint_target(name))
    return out
