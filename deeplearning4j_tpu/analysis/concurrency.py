"""Concurrency lint: an AST pass over the whole source tree.

Four hazard classes, each a documented finding code (ANALYSIS.md):

- **DL4J-C001** — ``lock.acquire()`` with no guaranteed release: the
  call is neither inside a ``try`` whose ``finally`` releases the same
  receiver, nor the statement immediately before one. A raise between
  acquire and release leaves the lock held forever; ``with`` is free.
- **DL4J-C002 / DL4J-C003** — untimed blocking calls: zero-argument
  ``.get()`` (queue), ``.join()`` (thread), ``.result()`` (future) and
  ``urlopen(...)`` without ``timeout=``. C002 when a lock is lexically
  held (``with <lock>:`` in scope, or the enclosing function follows
  the ``*_locked`` naming convention) — a blocked holder starves every
  other thread; C003 anywhere else — a dead producer/fleet hangs the
  caller forever instead of surfacing an error.
- **DL4J-C004** — ``threading.Thread(...)`` that is neither
  ``daemon=True`` nor marked daemon in the enclosing function: a
  forgotten non-daemon thread blocks interpreter shutdown.
- **DL4J-C005** — a write (assignment, augmented assignment, item
  write/delete, or mutator call such as ``.append``/``.clear``) to an
  attribute registered via ``@guarded_by`` (analysis/guards.py)
  outside ``with self.<lock>:``. ``__init__`` and ``*_locked`` methods
  are exempt.

Intentional exceptions are suppressed inline with ``# analysis: ok`` on
the offending line (optionally ``# analysis: ok(C003) — reason``);
everything else lands in the findings list that
``scripts/static_check.py`` gates against ``ANALYSIS_BASELINE.json``.

The pass is purely lexical — it never imports the code under analysis,
so it runs in milliseconds over the full tree and can lint broken or
heavyweight modules alike.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set

from deeplearning4j_tpu.analysis import Finding

__all__ = ["lint_source", "lint_file", "lint_tree", "DEFAULT_ROOTS"]

#: zero-argument method calls that block without bound
_BLOCKING_ZERO_ARG = {
    "get": "queue.get() with no timeout",
    "join": "Thread.join() with no timeout",
    "result": "Future.result() with no timeout",
}

#: functions taking an optional timeout kwarg that blocks forever absent
_BLOCKING_NEEDS_TIMEOUT_KW = {"urlopen": "urlopen() with no timeout="}

_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "clear", "remove", "discard", "add", "update",
    "setdefault", "sort", "reverse",
})

_LOCKISH = re.compile(r"(lock|cond|mutex|sem)", re.IGNORECASE)
_SUPPRESS = re.compile(r"#\s*analysis:\s*ok(?:\(([A-Z0-9, -]+)\))?")

#: the source roots static_check lints, relative to the repo root
DEFAULT_ROOTS = ("deeplearning4j_tpu", "scripts", "bench.py")


def _dotted(node) -> Optional[str]:
    """``self.fleet._lock`` -> that string; None for non-name chains."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_self_attr(node, attrs: Dict[str, str]) -> Optional[str]:
    """The guarded attr name when ``node`` is ``self.<registered>``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self" and node.attr in attrs):
        return node.attr
    return None


class _Ctx:
    """Lexical context threaded through the recursive statement walk."""

    __slots__ = ("symbol", "held", "self_locks", "guarded", "lock_attrs",
                 "assume_locked", "in_init")

    def __init__(self):
        self.symbol: List[str] = []
        self.held: List[str] = []        # dotted receivers of held locks
        self.self_locks: Set[str] = set()  # self.<attr> locks held
        self.guarded: Dict[str, str] = {}  # attr -> lock attr (class scope)
        self.lock_attrs: Set[str] = set()  # all lock attrs of the class
        self.assume_locked = False
        self.in_init = False

    @property
    def lock_held(self) -> bool:
        return bool(self.held) or self.assume_locked


class _Linter:
    def __init__(self, tree: ast.Module, src: str, path: str):
        self.tree = tree
        self.path = path
        self.lines = src.splitlines()
        self.findings: List[Finding] = []
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    # ------------------------------------------------------------- plumbing
    def _suppressed(self, node, code: str) -> bool:
        line = getattr(node, "lineno", 0)
        if not (1 <= line <= len(self.lines)):
            return False
        m = _SUPPRESS.search(self.lines[line - 1])
        if not m:
            return False
        which = m.group(1)
        return which is None or code.replace("DL4J-", "") in which \
            or code in which

    def _emit(self, code: str, node, ctx: _Ctx, message: str):
        if self._suppressed(node, code):
            return
        self.findings.append(Finding(
            code=code, path=self.path, line=getattr(node, "lineno", 0),
            symbol=".".join(ctx.symbol) or "<module>", message=message))

    # ----------------------------------------------------------- entry point
    def run(self) -> List[Finding]:
        ctx = _Ctx()
        for stmt in self.tree.body:
            self._stmt(stmt, ctx)
        return self.findings

    # ------------------------------------------------------------ statements
    def _stmt(self, node, ctx: _Ctx):
        if isinstance(node, ast.ClassDef):
            self._class(node, ctx)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._func(node, ctx)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            self._with(node, ctx)
        else:
            self._scan_exprs(node, ctx)
            self._check_writes(node, ctx)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.stmt,)):
                    self._stmt(child, ctx)
                # compound statements keep their bodies as stmt lists —
                # iter_child_nodes yields them flattened, handled above

    def _class(self, node: ast.ClassDef, ctx: _Ctx):
        sub = _Ctx()
        sub.symbol = ctx.symbol + [node.name]
        sub.guarded = self._read_guarded(node)
        sub.lock_attrs = set(sub.guarded.values())
        for stmt in node.body:
            self._stmt(stmt, sub)

    def _func(self, node, ctx: _Ctx):
        sub = _Ctx()
        sub.symbol = ctx.symbol + [node.name]
        sub.guarded = ctx.guarded
        sub.lock_attrs = ctx.lock_attrs
        # nested helpers inherit the caller's held-lock convention; a
        # fresh thread-target closure does not hold its definer's `with`
        sub.assume_locked = (node.name.endswith("_locked")
                             or ctx.assume_locked)
        sub.in_init = node.name == "__init__" or ctx.in_init
        for stmt in node.body:
            self._stmt(stmt, sub)

    def _with(self, node, ctx: _Ctx):
        added_held, added_self = [], []
        for item in node.items:
            dn = _dotted(item.context_expr)
            if dn is None:
                continue
            leaf = dn.rsplit(".", 1)[-1]
            if _LOCKISH.search(leaf) or leaf in ctx.lock_attrs:
                added_held.append(dn)
                if dn.startswith("self.") and dn.count(".") == 1:
                    added_self.append(leaf)
            # the context expr itself may contain calls to scan
            self._scan_expr_tree(item.context_expr, ctx)
        ctx.held.extend(added_held)
        ctx.self_locks.update(added_self)
        for stmt in node.body:
            self._stmt(stmt, ctx)
        for _ in added_held:
            ctx.held.pop()
        ctx.self_locks.difference_update(added_self)

    # ---------------------------------------------------------- expressions
    def _scan_exprs(self, stmt, ctx: _Ctx):
        """Scan every expression directly inside one statement (without
        entering nested function/class bodies — those get their own
        context when visited as statements)."""
        for field, value in ast.iter_fields(stmt):
            vals = value if isinstance(value, list) else [value]
            for v in vals:
                if isinstance(v, ast.expr):
                    self._scan_expr_tree(v, ctx)

    def _scan_expr_tree(self, expr, ctx: _Ctx):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._check_call(node, ctx)

    # ----------------------------------------------------------- call checks
    def _check_call(self, call: ast.Call, ctx: _Ctx):
        func = call.func
        # C001: bare acquire() outside with/try-finally
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            recv = _dotted(func.value)
            if recv is not None and not self._release_guaranteed(call, recv):
                self._emit("DL4J-C001", call, ctx,
                           f"{recv}.acquire() without try/finally release "
                           "(prefer `with`)")
        # C002/C003: untimed blocking calls
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if name in _BLOCKING_ZERO_ARG and not call.args \
                and not call.keywords:
            recv = (_dotted(func.value) or "<expr>") \
                if isinstance(func, ast.Attribute) else ""
            what = _BLOCKING_ZERO_ARG[name]
            if ctx.lock_held:
                self._emit("DL4J-C002", call, ctx,
                           f"{what} while holding "
                           f"{ctx.held[-1] if ctx.held else 'a lock'}")
            else:
                self._emit("DL4J-C003", call, ctx,
                           f"{what} on {recv or 'call result'}")
        if name in _BLOCKING_NEEDS_TIMEOUT_KW:
            if not any(kw.arg == "timeout" for kw in call.keywords) \
                    and len(call.args) < 3:
                code = "DL4J-C002" if ctx.lock_held else "DL4J-C003"
                self._emit(code, call, ctx, _BLOCKING_NEEDS_TIMEOUT_KW[name])
        # C004: non-daemon thread construction
        if name == "Thread":
            self._check_thread(call, ctx)
        # C005 via mutator call on a guarded attr
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            attr = _is_self_attr(func.value, ctx.guarded)
            if attr is not None:
                self._check_guarded_write(call, ctx, attr,
                                          f".{func.attr}()")

    def _check_thread(self, call: ast.Call, ctx: _Ctx):
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return
        # accept a `<x>.daemon = True` anywhere in the enclosing function
        anc = call
        func_node = None
        while anc in self.parents:
            anc = self.parents[anc]
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_node = anc
                break
        if func_node is not None:
            for node in ast.walk(func_node):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and t.attr == "daemon":
                            return
        self._emit("DL4J-C004", call, ctx,
                   "Thread() without daemon=True or a join-on-shutdown "
                   "daemon mark")

    def _release_guaranteed(self, call: ast.Call, recv: str) -> bool:
        """True when the acquire sits inside a Try whose finally releases
        the same receiver, or immediately precedes such a Try."""
        node = call
        stmt = None
        while node in self.parents:
            parent = self.parents[node]
            if isinstance(parent, ast.Try):
                if node in parent.body and self._releases(parent.finalbody,
                                                          recv):
                    return True
            if isinstance(node, ast.stmt) and stmt is None:
                stmt = node
            node = parent
        if stmt is None:
            return False
        parent = self.parents.get(stmt)
        body = getattr(parent, "body", None)
        if isinstance(body, list) and stmt in body:
            i = body.index(stmt)
            if i + 1 < len(body) and isinstance(body[i + 1], ast.Try):
                return self._releases(body[i + 1].finalbody, recv)
        return False

    def _releases(self, finalbody, recv: str) -> bool:
        for stmt in finalbody or ():
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "release" \
                        and _dotted(node.func.value) == recv:
                    return True
        return False

    # ---------------------------------------------------------- write checks
    def _check_writes(self, stmt, ctx: _Ctx):
        if not ctx.guarded:
            return
        targets = []
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                targets.extend(t.elts if isinstance(t, ast.Tuple) else [t])
        elif isinstance(stmt, ast.AugAssign):
            targets.append(stmt.target)
        elif isinstance(stmt, ast.Delete):
            targets.extend(stmt.targets)
        for t in targets:
            attr = _is_self_attr(t, ctx.guarded)
            if attr is not None:
                self._check_guarded_write(stmt, ctx, attr, "assignment")
                continue
            if isinstance(t, ast.Subscript):
                attr = _is_self_attr(t.value, ctx.guarded)
                if attr is not None:
                    self._check_guarded_write(stmt, ctx, attr, "item write")

    def _check_guarded_write(self, node, ctx: _Ctx, attr: str, how: str):
        lock = ctx.guarded[attr]
        if ctx.in_init or ctx.assume_locked or lock in ctx.self_locks:
            return
        self._emit("DL4J-C005", node, ctx,
                   f"write ({how}) to self.{attr} outside `with "
                   f"self.{lock}` (declared @guarded_by)")

    # -------------------------------------------------------- class registry
    @staticmethod
    def _read_guarded(node: ast.ClassDef) -> Dict[str, str]:
        reg: Dict[str, str] = {}
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            fn = dec.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if name != "guarded_by":
                continue
            args = [a.value for a in dec.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, str)]
            if len(args) >= 2:
                for a in args[1:]:
                    reg[a] = args[0]
        return reg


# -------------------------------------------------------------------------
# public entry points
# -------------------------------------------------------------------------

def lint_source(src: str, path: str) -> List[Finding]:
    """Lint one source string (``path`` is the repo-relative label)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(code="DL4J-C000", path=path, line=e.lineno or 0,
                        symbol="<module>", message=f"syntax error: {e.msg}")]
    return _Linter(tree, src, path).run()


def lint_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, rel or path)


def lint_tree(repo_root: str, roots=DEFAULT_ROOTS) -> List[Finding]:
    """Lint every ``.py`` file under the given roots (files or
    directories, repo-relative)."""
    findings: List[Finding] = []
    for root in roots:
        full = os.path.join(repo_root, root)
        if os.path.isfile(full):
            findings.extend(lint_file(full, root))
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fn)
                findings.extend(lint_file(p, os.path.relpath(p, repo_root)))
    return findings
