"""Runtime lock-order detector: would-be deadlocks without the hang.

A deadlock needs two threads taking the same pair of locks in opposite
orders — but the *order violation* exists on every run, even when the
interleaving happens to win the race. This module makes the violation
observable: with instrumentation installed, every ``threading.Lock()`` /
``threading.RLock()`` becomes a thin wrapper that

- names itself after its allocation site (``serving/batcher.py:58``),
- records a directed edge *held-lock -> newly-acquired-lock* into a
  process-global :class:`LockOrderGraph` on every acquisition made
  while other locks are held,
- times every hold and, when a lock was held longer than
  ``DL4J_TPU_LOCK_HOLD_MS`` (default 50), records a ``lock_hold`` span
  into the ambient tracer (observability/trace.py) — held-across-
  blocking-call spans show up right next to ``device_step`` in the same
  timeline.

A cycle in the accumulated graph is a would-be deadlock and is reported
as a ``DL4J-L001`` :class:`~deeplearning4j_tpu.analysis.Finding`.

Instrumentation is opt-in: ``DL4J_TPU_LOCK_CHECK=1`` (conftest turns it
on by default under pytest, and fails the session if the graph ends
with a cycle). The wrapper is deliberately cheap — one thread-local
list append/pop per acquire/release and a set lookup per edge — and the
``bench.py lockcheck_overhead`` entry pins the fit-loop cost under 3%.

Tests that *construct* deadlock cycles on purpose must pass their own
``LockOrderGraph`` to :func:`instrument` so the poison edges never
touch the global graph the conftest gate checks.
"""

from __future__ import annotations

import os
import sys
import threading
from time import perf_counter as _now
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.analysis import Finding

__all__ = [
    "LockOrderGraph", "InstrumentedLock", "instrument", "get_graph",
    "install", "uninstall", "installed", "maybe_install",
]

# the real factories, captured before any monkeypatching
_RAW_LOCK = threading.Lock
_RAW_RLOCK = threading.RLock

_THIS_FILE = os.path.abspath(__file__)
_THREADING_FILE = os.path.abspath(threading.__file__)
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(_THIS_FILE)))


def _hold_threshold_s() -> float:
    try:
        return float(os.environ.get("DL4J_TPU_LOCK_HOLD_MS", "50")) / 1e3
    except ValueError:
        return 0.05


#: cached hold threshold — the release path runs on every lock release,
#: so the env var is read once here and refreshed by install()/instrument()
#: rather than per release
_HOLD_S = _hold_threshold_s()


def _alloc_site() -> Tuple[str, bool]:
    """Allocation site of the lock being constructed: a stable
    repo-relative ``path:lineno`` label plus whether the allocating
    code lives inside this repo. Locks allocated by stdlib /
    third-party code (jax, orbax, concurrent.futures, ...) are not our
    audit surface and must keep exact raw-lock semantics — the
    installed factories leave them unwrapped."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _THIS_FILE and os.path.abspath(fn) != _THREADING_FILE:
            afn = os.path.abspath(fn)
            in_repo = afn.startswith(_REPO_ROOT + os.sep)
            parts = fn.replace(os.sep, "/").split("/")
            if "deeplearning4j_tpu" in parts:
                rel = "/".join(parts[parts.index("deeplearning4j_tpu"):])
            else:
                rel = "/".join(parts[-2:])
            return f"{rel}:{f.f_lineno}", in_repo
        f = f.f_back
    return "<unknown>", False


def _site_name() -> str:
    return _alloc_site()[0]


class LockOrderGraph:
    """Cross-thread lock acquisition-order graph.

    Nodes are allocation-site names; a directed edge a->b means some
    thread acquired lock b while holding lock a. Any cycle means two
    code paths disagree about ordering — a deadlock waiting for the
    right interleaving."""

    def __init__(self):
        self._lock = _RAW_LOCK()
        self._seen: set = set()                    # lock-free fast path
        self._edges: Dict[Tuple[str, str], int] = {}
        self._edge_thread: Dict[Tuple[str, str], str] = {}

    def record_edge(self, held: str, acquired: str, thread: str) -> None:
        if held == acquired:
            return          # reentrant / same-site locks are not an order
        key = (held, acquired)
        if key in self._seen:
            return
        with self._lock:
            self._seen.add(key)
            self._edges[key] = self._edges.get(key, 0) + 1
            self._edge_thread.setdefault(key, thread)

    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._edges)

    def clear(self) -> None:
        with self._lock:
            self._seen = set()
            self._edges.clear()
            self._edge_thread.clear()

    # ------------------------------------------------------------- analysis
    def cycles(self) -> List[List[str]]:
        """Strongly-connected components with >1 node (each is at least
        one acquisition-order cycle), nodes sorted for determinism."""
        adj: Dict[str, set] = {}
        for a, b in self.edges():
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: set = set()
        stack: List[str] = []
        counter = [0]
        out: List[List[str]] = []

        def strongconnect(v: str):
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in adj[v]:
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    out.append(sorted(scc))

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        return sorted(out)

    def findings(self) -> List[Finding]:
        found = []
        for cyc in self.cycles():
            found.append(Finding(
                code="DL4J-L001", path="<runtime>", line=0,
                symbol="lockorder",
                message="acquisition-order cycle: "
                        + " <-> ".join(cyc)))
        return found


_GLOBAL_GRAPH = LockOrderGraph()


def get_graph() -> LockOrderGraph:
    return _GLOBAL_GRAPH


# thread-local acquisition state, shared by every instrumented lock
class _TLS(threading.local):
    def __init__(self):
        self.held: List[Tuple[int, str, float]] = []  # (lock id, name, t0)
        self.busy = False          # reentrancy guard for bookkeeping


_tls = _TLS()


class InstrumentedLock:
    """Drop-in wrapper for ``threading.Lock``/``RLock`` objects that
    feeds a :class:`LockOrderGraph` and emits ``lock_hold`` tracer spans
    for long holds. Condition-compatible: forwards ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` (with the stdlib's documented
    fallbacks when the inner lock lacks them) and keeps the held-stack
    honest across ``Condition.wait``."""

    __slots__ = ("_inner", "name", "_graph")

    def __init__(self, inner, name: str, graph: LockOrderGraph):
        self._inner = inner
        self.name = name
        self._graph = graph

    # ---------------------------------------------------------- bookkeeping
    # (the common case — no other lock held — touches only the TLS list
    # and perf_counter; bench.py lockcheck_overhead pins the cost)
    def _note_acquire(self) -> None:
        tls = _tls
        if tls.busy:
            return
        held = tls.held
        me = id(self)
        if held:
            tls.busy = True
            try:
                for h in held:
                    if h[0] == me:          # reentrant: no edges
                        break
                else:
                    thread = threading.current_thread().name
                    record = self._graph.record_edge
                    for _, hname, _ in held:
                        record(hname, self.name, thread)
            finally:
                tls.busy = False
        held.append((me, self.name, _now()))

    def _note_release(self) -> None:
        tls = _tls
        if tls.busy:
            return
        held = tls.held
        me = id(self)
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == me:
                t0 = held[i][2]
                del held[i]
                t1 = _now()
                if t1 - t0 >= _HOLD_S:      # rare: long hold -> tracer span
                    tls.busy = True
                    try:
                        from deeplearning4j_tpu.observability.trace import \
                            get_tracer
                        get_tracer().record("lock_hold", t0, t1,
                                            {"lock": self.name})
                    finally:
                        tls.busy = False
                break

    # ------------------------------------------------------------- lock API
    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)  # analysis: ok(C001) — the wrapper IS the lock API
        if ok:
            self._note_acquire()
        return ok

    def release(self) -> None:
        self._note_release()
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()  # analysis: ok(C001) — __exit__ is the paired release
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<InstrumentedLock {self.name} {self._inner!r}>"

    def _at_fork_reinit(self) -> None:
        # os.register_at_fork handlers (concurrent.futures, logging)
        # reinit their module locks in the forked child
        self._inner._at_fork_reinit()

    # --------------------------------------------- Condition compatibility
    def _release_save(self):
        inner = self._inner
        save = getattr(inner, "_release_save", None)
        if save is not None:
            self._note_release()
            return save()
        self.release()
        return None

    def _acquire_restore(self, state) -> None:
        inner = self._inner
        restore = getattr(inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
            self._note_acquire()
        else:
            self.acquire()  # analysis: ok(C001) — Condition re-acquire protocol

    def _is_owned(self) -> bool:
        inner = self._inner
        owned = getattr(inner, "_is_owned", None)
        if owned is not None:
            return owned()
        # stdlib Condition's own fallback for plain locks
        if inner.acquire(False):  # analysis: ok(C001) — probe, released on next line
            inner.release()
            return False
        return True


def instrument(lock=None, *, name: Optional[str] = None,
               graph: Optional[LockOrderGraph] = None) -> InstrumentedLock:
    """Wrap one lock explicitly (tests building intentional deadlock
    cycles pass their own ``graph`` so the global gate stays clean)."""
    global _HOLD_S
    _HOLD_S = _hold_threshold_s()
    return InstrumentedLock(lock if lock is not None else _RAW_LOCK(),
                            name or _site_name(),
                            graph or _GLOBAL_GRAPH)


# --------------------------------------------------------------------------
# process-wide installation (monkeypatches the threading factories)
# --------------------------------------------------------------------------

_installed = False


def _make_lock(*a, **kw):
    name, in_repo = _alloc_site()
    raw = _RAW_LOCK(*a, **kw)
    if not in_repo:
        return raw      # stdlib/third-party lock: not our audit surface
    return InstrumentedLock(raw, name, _GLOBAL_GRAPH)


def _make_rlock(*a, **kw):
    name, in_repo = _alloc_site()
    raw = _RAW_RLOCK(*a, **kw)
    if not in_repo:
        return raw
    return InstrumentedLock(raw, name, _GLOBAL_GRAPH)


def install() -> LockOrderGraph:
    """Replace ``threading.Lock``/``RLock`` with instrumented factories.
    Only locks allocated from code inside this repo are wrapped —
    stdlib/third-party allocations (jax, orbax, concurrent.futures)
    get the raw lock back, both because they are not our audit surface
    and because stdlib import-time code touches raw-lock internals
    (``_at_fork_reinit`` registration). Locks created *before* install
    (and modules that froze the factory with ``from threading import
    Lock``) stay raw — acceptable: the graph covers every lock the
    repo's code allocates after startup, which under pytest is all of
    them."""
    global _installed, _HOLD_S
    _HOLD_S = _hold_threshold_s()
    if not _installed:
        threading.Lock = _make_lock
        threading.RLock = _make_rlock
        _installed = True
    return _GLOBAL_GRAPH


def uninstall() -> None:
    global _installed
    if _installed:
        threading.Lock = _RAW_LOCK
        threading.RLock = _RAW_RLOCK
        _installed = False


def installed() -> bool:
    return _installed


def maybe_install() -> Optional[LockOrderGraph]:
    """Honor ``DL4J_TPU_LOCK_CHECK`` (conftest default-on under pytest)."""
    if os.environ.get("DL4J_TPU_LOCK_CHECK", "0") == "1":
        return install()
    return None
