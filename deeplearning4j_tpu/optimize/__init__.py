"""Optimization drivers + listeners (parity: optimize/ in the reference).
The SGD train step itself lives fused inside MultiLayerNetwork's jitted step;
this package holds the listener API and the full-batch optimizers."""

from deeplearning4j_tpu.optimize.listeners import (
    TrainingListener,
    ScoreIterationListener,
    PerformanceListener,
    ProfilerListener,
    CollectScoresIterationListener,
    ParamAndGradientIterationListener,
)
