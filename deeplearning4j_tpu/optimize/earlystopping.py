"""Early stopping: config, termination conditions, score calculators,
model savers, trainer.

Parity: earlystopping/ in the reference — EarlyStoppingConfiguration.java,
trainer/EarlyStoppingTrainer.java (+Graph variant; one trainer here handles
both since the model API is shared), scorecalc/DataSetLossCalculator.java,
termination/ (MaxEpochs, BestScoreEpoch, ScoreImprovementEpoch, MaxTime,
MaxScore, InvalidScore epoch+iteration conditions), saver/ (LocalFile +
InMemory), listener/EarlyStoppingListener.java.
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional


# ---------------------------------------------------------------------------
# Termination conditions (termination/ parity: 8 conditions)
# ---------------------------------------------------------------------------

class EpochTerminationCondition:
    #: conditions calibrated for the (validation) score are only checked on
    #: scoring epochs when evaluate_every_n_epochs > 1; epoch-count /
    #: sanity conditions run every epoch
    uses_validation_score = True

    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, iteration: int, score: float) -> bool:
        raise NotImplementedError


@dataclass
class MaxEpochsTermination(EpochTerminationCondition):
    max_epochs: int = 10
    uses_validation_score = False

    def terminate(self, epoch, score):
        return epoch >= self.max_epochs - 1


@dataclass
class BestScoreEpochTermination(EpochTerminationCondition):
    """Stop once the score reaches/beats a target value."""

    best_expected_score: float = 0.0

    def terminate(self, epoch, score):
        return score <= self.best_expected_score


@dataclass
class ScoreImprovementEpochTermination(EpochTerminationCondition):
    """Stop after max_epochs_without_improvement (optionally requiring at
    least min_improvement)."""

    max_epochs_without_improvement: int = 5
    min_improvement: float = 0.0

    def initialize(self):
        self._best = math.inf
        self._since = 0

    def terminate(self, epoch, score):
        if score < self._best - self.min_improvement:
            self._best = score
            self._since = 0
            return False
        self._since += 1
        return self._since > self.max_epochs_without_improvement


@dataclass
class MaxScoreEpochTermination(EpochTerminationCondition):
    """Stop (diverged) if the score exceeds max_score."""

    max_score: float = 1e9
    uses_validation_score = False  # divergence guard: check every epoch

    def terminate(self, epoch, score):
        return score > self.max_score


@dataclass
class InvalidScoreEpochTermination(EpochTerminationCondition):
    uses_validation_score = False

    def terminate(self, epoch, score):
        return math.isnan(score) or math.isinf(score)


@dataclass
class MaxTimeIterationTermination(IterationTerminationCondition):
    max_seconds: float = 3600.0

    def initialize(self):
        self._start = time.time()

    def terminate(self, iteration, score):
        return (time.time() - self._start) > self.max_seconds


@dataclass
class MaxScoreIterationTermination(IterationTerminationCondition):
    max_score: float = 1e9

    def terminate(self, iteration, score):
        return score > self.max_score


@dataclass
class InvalidScoreIterationTermination(IterationTerminationCondition):
    def terminate(self, iteration, score):
        return math.isnan(score) or math.isinf(score)


# ---------------------------------------------------------------------------
# Score calculators (scorecalc/ parity)
# ---------------------------------------------------------------------------

class DataSetLossCalculator:
    """Average loss over a validation iterator
    (DataSetLossCalculator.java parity; works for MLN and CG)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        total, count = 0.0, 0
        for ds in self.iterator:
            n = ds.num_examples
            total += net.score(ds) * n
            count += n
        self.iterator.reset()
        if count == 0:
            return float("nan")
        return total / count if self.average else total


class EvaluationScoreCalculator:
    """Score = 1 - accuracy (so 'minimize' semantics hold)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, net) -> float:
        ev = net.evaluate(self.iterator)
        self.iterator.reset()
        return 1.0 - ev.accuracy()


# ---------------------------------------------------------------------------
# Model savers (saver/ parity)
# ---------------------------------------------------------------------------

class InMemoryModelSaver:
    def __init__(self):
        self.best = None
        self.latest = None

    def save_best(self, net):
        self.best = net.clone()

    def save_latest(self, net):
        self.latest = net.clone()

    def get_best(self):
        return self.best

    def get_latest(self):
        return self.latest


class LocalFileModelSaver:
    """Writes bestModel.zip / latestModel.zip via the checkpoint format
    (LocalFileModelSaver.java parity)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _write(self, net, fname):
        from deeplearning4j_tpu.utils.serialization import (
            write_computation_graph, write_model)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        path = os.path.join(self.directory, fname)
        # write-temp-then-rename: a crash mid-save must never leave a
        # truncated zip where the previous (valid) best/latest model was
        # — the rename is atomic, so readers see old-complete or
        # new-complete, nothing in between
        tmp = path + ".tmp"
        try:
            if isinstance(net, MultiLayerNetwork):
                write_model(net, tmp)
            else:
                write_computation_graph(net, tmp)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return path

    def save_best(self, net):
        self._write(net, "bestModel.zip")

    def save_latest(self, net):
        self._write(net, "latestModel.zip")

    def get_best(self):
        from deeplearning4j_tpu.utils.serialization import restore_model
        return restore_model(os.path.join(self.directory, "bestModel.zip"))

    def get_latest(self):
        from deeplearning4j_tpu.utils.serialization import restore_model
        return restore_model(os.path.join(self.directory, "latestModel.zip"))


# ---------------------------------------------------------------------------
# Configuration + result + trainer
# ---------------------------------------------------------------------------

@dataclass
class EarlyStoppingConfiguration:
    score_calculator: object = None
    epoch_terminations: List[EpochTerminationCondition] = field(
        default_factory=list)
    iteration_terminations: List[IterationTerminationCondition] = field(
        default_factory=list)
    model_saver: object = field(default_factory=InMemoryModelSaver)
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1


@dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: object = None
    score_vs_epoch: dict = field(default_factory=dict)


class EarlyStoppingTrainer:
    """Epoch loop around fit + validation scoring + best-model saving
    (trainer/EarlyStoppingTrainer.java parity; handles MultiLayerNetwork and
    ComputationGraph — the 'GraphTrainer' of the reference is the same loop)."""

    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator,
                 listener=None):
        self.config = config
        self.net = net
        self.iterator = train_iterator
        self.listener = listener

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_terminations:
            c.initialize()
        for c in cfg.iteration_terminations:
            c.initialize()
        best_score, best_epoch = math.inf, -1
        scores = {}
        epoch = 0
        reason, details = "max_epochs", "no epoch termination configured"
        if self.listener:
            self.listener.on_start(cfg, self.net)
        while True:
            stop_iter = None
            for ds in self.iterator:
                score = float(self.net.fit_batch(ds))
                for c in cfg.iteration_terminations:
                    if c.terminate(self.net.iteration, score):
                        stop_iter = (type(c).__name__,
                                     f"iteration {self.net.iteration}, "
                                     f"score {score}")
                        break
                if stop_iter:
                    break
            self.iterator.reset()
            if stop_iter:
                reason, details = stop_iter
                break

            scoring_epoch = epoch % cfg.evaluate_every_n_epochs == 0
            if scoring_epoch:
                if cfg.score_calculator is not None:
                    score = cfg.score_calculator.calculate_score(self.net)
                else:
                    score = float(self.net.score_value)
                scores[epoch] = score
                if self.listener:
                    self.listener.on_epoch(epoch, score, cfg, self.net)
                if score < best_score:
                    best_score, best_epoch = score, epoch
                    cfg.model_saver.save_best(self.net)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest(self.net)
            else:
                # off-schedule epochs: only epoch-count/sanity conditions
                # run (the raw last-batch training score is too noisy for
                # validation-calibrated conditions and would pollute
                # ScoreImprovement's counter)
                score = float(self.net.score_value)
            stop_epoch = None
            for c in cfg.epoch_terminations:
                if c.uses_validation_score and not scoring_epoch:
                    continue
                if c.terminate(epoch, score):
                    stop_epoch = (type(c).__name__,
                                  f"epoch {epoch}, score {score}")
                    break
            if stop_epoch:
                reason, details = stop_epoch
                break
            self.net.epoch += 1
            epoch += 1

        result = EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            total_epochs=epoch + 1,
            best_model=cfg.model_saver.get_best(),
            score_vs_epoch=scores,
        )
        if self.listener:
            self.listener.on_completion(result)
        return result


# Reference-name alias: the Graph variant is the same trainer.
EarlyStoppingGraphTrainer = EarlyStoppingTrainer
