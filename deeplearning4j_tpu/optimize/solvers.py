"""Second-order-ish full-batch optimizers: line gradient descent, conjugate
gradient, L-BFGS, with Armijo backtracking line search.

Parity: optimize/solvers/{BaseOptimizer, StochasticGradientDescent, LBFGS,
ConjugateGradient, LineGradientDescent, BackTrackLineSearch}.java +
optimize/Solver.java (SURVEY.md §2.4). The SGD path is the jitted train
step inside MultiLayerNetwork/ComputationGraph; these drivers cover the
reference's remaining OptimizationAlgorithm values. TPU-native design:
parameters are raveled to ONE flat vector (jax.flatten_util) and the
loss/grad are jitted once — every line-search probe is a single compiled
device call, the host only steers.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


@dataclass
class SolverResult:
    score: float
    iterations: int
    converged: bool


def _flat_problem(net, ds):
    flat0, unravel = ravel_pytree(net.params)
    x = jnp.asarray(ds.features)
    y = jnp.asarray(ds.labels)
    fmask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
    lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
    # Fixed rng: line search needs a deterministic objective, so a dropout
    # net optimizes one fixed mask per optimize() call.
    rng = jax.random.PRNGKey(int(np.asarray(net._rng_key)[-1]))

    def loss(flat):
        l, _ = net._loss(unravel(flat), net.state, x, y, fmask, lmask,
                         rng=rng, train=True)
        return l

    return flat0, unravel, jax.jit(loss), jax.jit(jax.value_and_grad(loss))


def backtrack_line_search(loss_fn, x, fx, g, direction, *, step0=1.0,
                          c1=1e-4, rho=0.5, max_steps=30):
    """Armijo backtracking (BackTrackLineSearch.java parity, 369 LoC there):
    shrink step until f(x + a*d) <= f(x) + c1*a*g.d.

    Returns (step, f_new, direction) — the direction is swapped to -g when
    the supplied one is not a descent direction, so callers MUST step along
    the returned direction."""
    gd = float(g @ direction)
    if gd >= 0:  # not a descent direction — fall back to -g
        direction = -g
        gd = float(g @ direction)
    a = step0
    for _ in range(max_steps):
        fnew = float(loss_fn(x + a * direction))
        if fnew <= fx + c1 * a * gd and np.isfinite(fnew):
            return a, fnew, direction
        a *= rho
    return 0.0, fx, direction  # no acceptable step


class BaseSolver:
    """Template loop (BaseOptimizer.optimize :180 parity): direction ->
    line search -> update, until max_iterations or gradient/score tolerance."""

    def __init__(self, net, max_iterations: int = 100, tolerance: float = 1e-8):
        self.net = net
        self.max_iterations = max_iterations
        self.tolerance = tolerance

    def _directions(self, flat0, loss, vg):
        raise NotImplementedError

    def optimize(self, ds) -> SolverResult:
        flat0, unravel, loss, vg = _flat_problem(self.net, ds)
        flat, iters, converged = self._run(flat0, loss, vg)
        self.net.params = unravel(flat)
        score = float(loss(flat))
        self.net.score_value = score
        return SolverResult(score=score, iterations=iters, converged=converged)


class LineGradientDescent(BaseSolver):
    """Steepest descent + line search (LineGradientDescent.java parity)."""

    def _run(self, flat, loss, vg):
        fx, g = vg(flat)
        fx = float(fx)
        for i in range(self.max_iterations):
            a, fnew, d = backtrack_line_search(loss, flat, fx, g, -g)
            if a == 0.0:
                return flat, i + 1, False  # line search stalled
            if abs(fx - fnew) < self.tolerance:
                return flat, i + 1, True
            flat = flat + a * d
            fx, g = vg(flat)
            fx = float(fx)
        return flat, self.max_iterations, False


class ConjugateGradient(BaseSolver):
    """Nonlinear CG, Polak-Ribiere+ with automatic restart
    (ConjugateGradient.java parity)."""

    def _run(self, flat, loss, vg):
        fx, g = vg(flat)
        fx = float(fx)
        d = -g
        for i in range(self.max_iterations):
            a, fnew, d = backtrack_line_search(loss, flat, fx, g, d)
            if a == 0.0:
                return flat, i + 1, False  # line search stalled
            if abs(fx - fnew) < self.tolerance:
                return flat, i + 1, True
            flat = flat + a * d
            fx_new, g_new = vg(flat)
            beta = float(g_new @ (g_new - g)) / max(float(g @ g), 1e-20)
            beta = max(beta, 0.0)  # PR+ restart
            d = -g_new + beta * d
            fx, g = float(fx_new), g_new
        return flat, self.max_iterations, False


class LBFGS(BaseSolver):
    """Limited-memory BFGS, two-loop recursion, memory m
    (LBFGS.java parity — the reference also uses m=10 ringbuffers)."""

    def __init__(self, net, max_iterations: int = 100, tolerance: float = 1e-8,
                 m: int = 10):
        super().__init__(net, max_iterations, tolerance)
        self.m = m

    def _run(self, flat, loss, vg):
        fx, g = vg(flat)
        fx = float(fx)
        s_hist, y_hist = [], []
        for i in range(self.max_iterations):
            # two-loop recursion
            q = np.asarray(g, dtype=np.float64).copy()
            alphas = []
            for s, y in reversed(list(zip(s_hist, y_hist))):
                rho = 1.0 / max(float(y @ s), 1e-20)
                a = rho * float(s @ q)
                alphas.append((a, rho, s, y))
                q -= a * np.asarray(y)
            if y_hist:
                s, y = s_hist[-1], y_hist[-1]
                gamma = float(s @ y) / max(float(y @ y), 1e-20)
                q *= gamma
            for a, rho, s, y in reversed(alphas):
                b = rho * float(y @ q)
                q += np.asarray(s) * (a - b)
            d = jnp.asarray(-q, dtype=flat.dtype)

            a, fnew, d = backtrack_line_search(loss, flat, fx, g, d)
            if a == 0.0:
                return flat, i + 1, False  # line search stalled
            if abs(fx - fnew) < self.tolerance:
                return flat, i + 1, True
            new_flat = flat + a * d
            fx_new, g_new = vg(new_flat)
            s_hist.append(new_flat - flat)
            y_hist.append(g_new - g)
            if len(s_hist) > self.m:
                s_hist.pop(0)
                y_hist.pop(0)
            flat, fx, g = new_flat, float(fx_new), g_new
        return flat, self.max_iterations, False


class Solver:
    """Dispatch by algorithm name (optimize/Solver.java :48 parity).
    'sgd' is the jitted minibatch train step on the network itself."""

    ALGOS = {
        "line_gradient_descent": LineGradientDescent,
        "conjugate_gradient": ConjugateGradient,
        "lbfgs": LBFGS,
    }

    def __init__(self, net):
        self.net = net

    def optimize(self, ds, algo: str = "lbfgs", **kwargs) -> SolverResult:
        if algo in ("sgd", "stochastic_gradient_descent"):
            score = self.net.fit_batch(ds)
            return SolverResult(score=float(score), iterations=1,
                                converged=False)
        cls = self.ALGOS.get(algo)
        if cls is None:
            raise ValueError(f"Unknown optimization algorithm '{algo}'; "
                             f"one of {sorted(self.ALGOS)} or 'sgd'")
        return cls(self.net, **kwargs).optimize(ds)
