"""Training listeners.

Parity: optimize/api/IterationListener.java + TrainingListener.java and the
impls in optimize/listeners/ (ScoreIterationListener, PerformanceListener,
CollectScoresIterationListener, ComposableIterationListener).

Note: reading ``net.score_value`` forces a device sync; listeners that log
every iteration therefore sample (print frequency) exactly like the
reference, and PerformanceListener measures wall-clock between calls without
forcing a sync unless reporting. ``score_value`` itself stays a lazy device
array — only a listener's own cadence (or an explicit ``float()``) pulls it
to the host.

``needs_per_iteration`` (class attribute, default True): declares whether
the listener's semantics depend on being invoked at the real wall-clock
moment each iteration finishes (timing listeners, per-step param pulls).
Listeners that only consume ``(iteration, score_value)`` pairs declare
False; when every attached listener does, ``fit`` may dispatch several
steps as one jitted scan chunk and REPLAY ``iteration_done`` per inner
iteration afterwards with identical (iteration, score) values.
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """Base listener (TrainingListener.java parity: onEpochStart/End,
    iterationDone; forward/backward hooks are meaningless inside one fused
    XLA step, so they are not exposed)."""

    # True = must run at the real per-step boundary (timings, param pulls);
    # False = only consumes (iteration, score) and tolerates chunked
    # dispatch with post-hoc replay (see module docstring).
    needs_per_iteration = True

    def iteration_done(self, net, iteration: int, epoch: int):
        pass

    def on_epoch_start(self, net):
        pass

    def on_epoch_end(self, net):
        pass

    def on_recovery(self, net, event):
        """Resilience hook: called by the TrainingSupervisor with a
        resilience.RecoveryEvent for every checkpoint / resume / retry /
        rollback / preemption (no reference analogue — the reference has
        no recovery loop to observe)."""
        pass


class ScoreIterationListener(TrainingListener):
    """Logs the loss every N iterations (ScoreIterationListener parity)."""

    needs_per_iteration = False  # cadence-sampled score only

    def __init__(self, print_iterations: int = 10, out=None):
        self.print_iterations = max(1, print_iterations)
        self.out = out

    def iteration_done(self, net, iteration, epoch):
        if iteration % self.print_iterations == 0:
            msg = (f"Score at iteration {iteration} is "
                   f"{float(net.score_value):.6f}")
            if self.out is not None:
                print(msg, file=self.out)
            else:
                logger.info(msg)


class CollectScoresIterationListener(TrainingListener):
    """Collects (iteration, score) pairs (CollectScoresIterationListener
    parity)."""

    needs_per_iteration = False  # cadence-sampled score only

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: list[tuple[int, float]] = []

    def iteration_done(self, net, iteration, epoch):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(net.score_value)))


class PerformanceListener(TrainingListener):
    """Throughput reporting (PerformanceListener parity: iterations/sec,
    examples/sec, iteration wall time) + MFU when ``report_mfu`` is set:
    per-step FLOPs come from XLA's cost model on the compiled train step
    (SURVEY.md §5.1 — the reference has no MFU concept; the TPU framework
    reports it first-class), peak from the device kind."""

    needs_per_iteration = True  # measures real wall-clock per step

    def __init__(self, frequency: int = 10, report_examples: bool = True,
                 flops_per_step: float | None = None,
                 report_mfu: bool = False):
        self.frequency = max(1, frequency)
        self.report_examples = report_examples
        self.flops_per_step = flops_per_step  # net.step_cost_analysis(ds)["flops"]
        # report_mfu without explicit flops: use the FLOPs the fit loop
        # auto-derived from the lowered cost model (net.flops_per_step)
        self.report_mfu = bool(report_mfu) or flops_per_step is not None
        self.records: list[dict] = []
        self._last_time = None
        self._last_iter = None
        self._examples = 0

    def _resolve_flops(self, net) -> float | None:
        if self.flops_per_step:
            return self.flops_per_step
        if self.report_mfu:
            return getattr(net, "flops_per_step", None)
        return None

    def _peak(self):
        import jax

        from deeplearning4j_tpu.utils.perf import peak_flops
        return peak_flops(jax.devices()[0])

    def iteration_done(self, net, iteration, epoch):
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time, self._last_iter = now, iteration
            self._examples = 0
            return
        self._examples += getattr(net, "last_batch_examples", 0)
        if iteration % self.frequency == 0:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            rec = {
                "iteration": iteration,
                "iterations_per_sec": iters / dt if dt > 0 else float("inf"),
                "ms_per_iteration": 1000.0 * dt / max(iters, 1),
            }
            msg = (f"iteration {iteration}: "
                   f"{rec['iterations_per_sec']:.1f} it/s, "
                   f"{rec['ms_per_iteration']:.2f} ms/it")
            if self.report_examples and self._examples:
                rec["examples_per_sec"] = (
                    self._examples / dt if dt > 0 else float("inf"))
                msg += f", {rec['examples_per_sec']:.1f} examples/s"
            flops = self._resolve_flops(net)
            if flops and dt > 0:
                peak = self._peak()
                if peak:
                    mfu = flops * iters / dt / peak
                    if 0.0 < mfu <= 1.0:  # never publish impossible MFU
                        rec["mfu"] = mfu
                        msg += f", MFU {100 * mfu:.1f}%"
            self.records.append(rec)
            logger.info(msg)
            self._last_time, self._last_iter = now, iteration
            self._examples = 0


class RecoveryEventListener(TrainingListener):
    """Collects (and optionally logs) supervisor recovery events — the
    listener-tier view of the resilience runtime's restarts, rollbacks
    and retries (ResilienceStats carries the counter view)."""

    needs_per_iteration = False  # only observes recovery events

    def __init__(self, log: bool = True):
        self.log = log
        self.events: list = []

    def on_recovery(self, net, event):
        self.events.append(event)
        if self.log:
            logger.warning("recovery: %s", event)

    def counts(self) -> dict:
        out: dict[str, int] = {}
        for e in self.events:
            out[e.kind] = out.get(e.kind, 0) + 1
        return out


class ComposableIterationListener(TrainingListener):
    def __init__(self, *listeners):
        self.listeners = listeners

    @property
    def needs_per_iteration(self):
        return any(getattr(l, "needs_per_iteration", True)
                   for l in self.listeners)

    def iteration_done(self, net, iteration, epoch):
        for l in self.listeners:
            l.iteration_done(net, iteration, epoch)

    def on_epoch_start(self, net):
        for l in self.listeners:
            l.on_epoch_start(net)

    def on_epoch_end(self, net):
        for l in self.listeners:
            l.on_epoch_end(net)

    def on_recovery(self, net, event):
        for l in self.listeners:
            l.on_recovery(net, event)


class ProfilerListener(TrainingListener):
    """Captures a JAX/XLA profiler trace for a window of training
    iterations (SURVEY.md §5.1: the reference has only wall-clock
    listeners; the TPU framework exposes the real profiler). The trace
    (xplane.pb) lands in ``log_dir`` and opens with xprof/tensorboard;
    PERF.md documents the in-repo parsing recipe."""

    def __init__(self, log_dir: str, start_iteration: int = 5,
                 num_iterations: int = 5):
        self.log_dir = log_dir
        self.start_iteration = start_iteration
        self.num_iterations = max(1, num_iterations)
        self._active = False
        self.captured = False
        import atexit
        # the JAX trace is process-wide: if training ends mid-window
        # (short fit_batch loop, exception inside fit), the trace must
        # still be flushed or it is silently lost AND blocks any later
        # start_trace in this process
        atexit.register(self.close)

    def _warn_once(self, what: str, exc: Exception):
        if not getattr(self, "_warned", False):
            self._warned = True
            import logging
            logging.getLogger("deeplearning4j_tpu").warning(
                "ProfilerListener: %s failed (%s: %s) — profiling "
                "disabled for this window, training continues",
                what, type(exc).__name__, exc)

    def _stop(self, net):
        import jax
        # sync so the trace includes the in-flight device work
        if net is not None and net.score_value is not None:
            try:
                float(net.score_value)
            except Exception:
                pass
        # idempotent: a second listener instance (or anything else) may
        # already have stopped the process-wide trace — stop_trace then
        # raises, which must not abort training or leave _active stuck
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            self._warn_once("stop_trace", e)
        self._active = False
        self.captured = True

    def close(self, net=None):
        """Flush the trace if still recording (safe to call anytime)."""
        if self._active:
            self._stop(net)

    def iteration_done(self, net, iteration, epoch):
        import jax
        if (not self.captured and not self._active
                and iteration >= self.start_iteration):
            # idempotent: the process-wide trace may already be running
            # (a re-attached listener, or an outer profiling harness) —
            # start_trace raises; warn once, mark captured, keep training
            try:
                jax.profiler.start_trace(self.log_dir)
            except Exception as e:
                self._warn_once("start_trace", e)
                self.captured = True
                return
            self._active = True
            self._stop_at = iteration + self.num_iterations
            return
        if self._active and iteration >= self._stop_at:
            self._stop(net)

    def on_epoch_end(self, net):
        self.close(net)  # epoch shorter than the window: flush cleanly


class ParamAndGradientIterationListener(TrainingListener):
    """Per-iteration param/update magnitude logging to delimited text.

    Parity: optimize/listeners/ParamAndGradientIterationListener.java —
    one row per sampled iteration: ``n``, ``score``, then per parameter
    tensor mean / min / max / meanAbsValue for the PARAMETER and for the
    step's weight change. The reference logs raw gradients; here
    forward+backward+updater fuse into one XLA program (the gradient is
    never materialized on the host), so the logged "G" columns are the
    applied per-step update delta — the same tuning/debugging signal the
    reference's columns serve (an update IS the updater-scaled gradient),
    at zero extra device traffic. Column names keep the reference's
    ``_meanG``/``_minG``/``_maxG``/``_meanAbsValueG`` suffixes so
    downstream tooling parses both.
    """

    def __init__(self, iterations: int = 1, *, print_header: bool = True,
                 print_mean: bool = True, print_min_max: bool = True,
                 print_mean_abs: bool = True, file=None,
                 output_to_console: bool = False, delimiter: str = "\t"):
        self.iterations = max(1, iterations)
        self.print_header = print_header
        self.print_mean = print_mean
        self.print_min_max = print_min_max
        self.print_mean_abs = print_mean_abs
        self.file = file
        self.output_to_console = output_to_console
        self.delimiter = delimiter
        self._count = 0
        self._prev = None
        self._wrote_header = False

    # -- helpers ----------------------------------------------------------
    def _flat_params(self, net):
        import jax
        import numpy as np
        out = {}
        for ln, sub in net.params.items():
            for pn, arr in sub.items():
                out[f"{ln}_{pn}"] = np.asarray(jax.device_get(arr),
                                               dtype=np.float64)
        return out

    def _stat_cols(self, arr):
        import numpy as np
        cols = []
        if self.print_mean:
            cols.append(float(np.mean(arr)) if arr.size else 0.0)
        if self.print_min_max:
            cols.append(float(np.min(arr)) if arr.size else 0.0)
            cols.append(float(np.max(arr)) if arr.size else 0.0)
        if self.print_mean_abs:
            cols.append(float(np.mean(np.abs(arr))) if arr.size else 0.0)
        return cols

    def _emit(self, line: str):
        if self.file is not None:
            self.file.write(line + "\n")
            self.file.flush()
        if self.output_to_console:
            print(line)
        if self.file is None and not self.output_to_console:
            logger.info(line)

    # -- listener ---------------------------------------------------------
    def on_epoch_start(self, net):
        # snapshot pre-step params so the FIRST sampled row has real
        # update columns (without this the first delta would be zero)
        if self._prev is None and net.params is not None:
            self._prev = self._flat_params(net)

    def iteration_done(self, net, iteration, epoch):
        import numpy as np
        self._count += 1
        # fetch device params only for sampled rows and the iteration just
        # before one (the delta's left edge) — a every-step device->host
        # pull of the full param tree would stall the dispatch pipeline
        # the fused step exists to keep full
        nxt = self._count + 1
        if not (self._count % self.iterations == 0
                or nxt % self.iterations == 0):
            return
        params = self._flat_params(net)
        if self.print_header and not self._wrote_header:
            names = []
            for s in params:
                if self.print_mean:
                    names.append(f"{s}_mean")
                if self.print_min_max:
                    names += [f"{s}_min", f"{s}_max"]
                if self.print_mean_abs:
                    names.append(f"{s}_meanAbsValue")
                if self.print_mean:
                    names.append(f"{s}_meanG")
                if self.print_min_max:
                    names += [f"{s}_minG", f"{s}_maxG"]
                if self.print_mean_abs:
                    names.append(f"{s}_meanAbsValueG")
            self._emit(self.delimiter.join(["n", "score"] + names))
            self._wrote_header = True
        if self._count % self.iterations != 0:
            self._prev = params
            return
        cols = [str(self._count), repr(float(net.score_value))]
        prev = self._prev if self._prev is not None else params
        for s, arr in params.items():
            delta = arr - prev.get(s, arr)
            for v in self._stat_cols(arr) + self._stat_cols(delta):
                cols.append(repr(v))
        self._emit(self.delimiter.join(cols))
        self._prev = params
