"""ParagraphVectors (doc2vec): PV-DM and PV-DBOW + inferVector.

Parity: models/paragraphvectors/ParagraphVectors.java (1,436 LoC) with
learning algorithms embeddings/learning/impl/sequence/{DM, DBOW}.java.
Documents are (label, text) pairs; label vectors live in their own table.
``infer_vector`` trains a fresh doc vector against FROZEN word tables —
exactly the reference's inference path.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import elements
from deeplearning4j_tpu.nlp.sequence_vectors import (
    SequenceVectors,
    SequenceVectorsConfig,
)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory


class ParagraphVectors(SequenceVectors):
    def __init__(self, config: SequenceVectorsConfig | None = None,
                 sequence_algorithm: str = "dbow", **kw):
        """sequence_algorithm: 'dbow' (PV-DBOW) or 'dm' (PV-DM)."""
        super().__init__(config, **kw)
        if not self.config.use_hs:
            raise ValueError("ParagraphVectors here uses hierarchical "
                             "softmax (negative-sampling variant TBD); "
                             "leave negative=0")
        self.sequence_algorithm = sequence_algorithm
        self.doc_labels: List[str] = []
        self.doc_vecs = None

    # ------------------------------------------------------------- training
    def fit_documents(self, documents, tokenizer_factory=None):
        """documents: LabelAwareIterator / iterable of (label, text)."""
        tf = tokenizer_factory or DefaultTokenizerFactory()
        labels, token_seqs = [], []
        for label, text in documents:
            tokens = tf.create(text).get_tokens()
            if tokens:
                labels.append(label)
                token_seqs.append(tokens)
        self.doc_labels = labels
        self.build_vocab(token_seqs)
        cfg = self.config
        rng = np.random.default_rng(cfg.seed + 1)
        self.doc_vecs = jnp.asarray(
            (rng.random((len(labels), cfg.vector_size)) - 0.5)
            / cfg.vector_size, dtype=jnp.float32)

        seqs = self._sequences_to_indices(token_seqs)
        total = sum(len(s) for s in seqs) * cfg.epochs
        seen = 0
        for _ in range(cfg.epochs):
            for di in self._rng.permutation(len(seqs)):
                seq = self._subsample(seqs[di])
                if len(seq) < 1:
                    seen += len(seqs[di])
                    continue
                lr = max(cfg.min_learning_rate,
                         cfg.learning_rate * (1 - seen / max(total, 1)))
                self._train_doc(int(di), seq, lr, frozen_words=False)
                seen += len(seqs[di])
        return self

    def _train_doc(self, doc_idx, seq, lr, *, frozen_words, doc_vecs=None,
                   table=None):
        """One pass of DM/DBOW updates for one document."""
        cfg = self.config
        lk = self.lookup
        dv = self.doc_vecs if doc_vecs is None else doc_vecs
        if self.sequence_algorithm == "dbow":
            targets = np.asarray(seq, np.int32)
            docs = np.full(len(targets), doc_idx, np.int32)
            points, codes, mask = self._hs_arrays(targets)
            if frozen_words:
                dv = elements.dbow_hs_step_frozen(
                    lk.syn1, dv, jnp.asarray(docs), points, codes, mask, lr)
            else:
                lk.syn1, dv = elements.dbow_hs_step(
                    lk.syn1, dv, jnp.asarray(docs), points, codes, mask, lr)
        else:  # dm
            n = len(seq)
            rows = []
            bs = self._rng.integers(1, cfg.window + 1, size=n)
            for pos in range(n):
                b = bs[pos]
                ctx = [seq[j] for j in range(max(0, pos - b),
                                             min(n, pos + b + 1)) if j != pos]
                if ctx:
                    rows.append((ctx, seq[pos]))
            if not rows:
                return dv
            W = max(len(c) for c, _ in rows)
            ctx_arr = np.zeros((len(rows), W), np.int32)
            ctx_mask = np.zeros((len(rows), W), np.float32)
            targets = np.empty(len(rows), np.int32)
            for i, (c, t) in enumerate(rows):
                ctx_arr[i, :len(c)] = c
                ctx_mask[i, :len(c)] = 1.0
                targets[i] = t
            docs = np.full(len(rows), doc_idx, np.int32)
            points, codes, mask = self._hs_arrays(targets)
            if frozen_words:
                dv = elements.dm_hs_step_frozen(
                    lk.syn0, lk.syn1, dv, jnp.asarray(docs),
                    jnp.asarray(ctx_arr), jnp.asarray(ctx_mask), points,
                    codes, mask, lr)
            else:
                lk.syn0, lk.syn1, dv = elements.dm_hs_step(
                    lk.syn0, lk.syn1, dv, jnp.asarray(docs),
                    jnp.asarray(ctx_arr), jnp.asarray(ctx_mask), points,
                    codes, mask, lr)
        if doc_vecs is None:
            self.doc_vecs = dv
        return dv

    # ------------------------------------------------------------ inference
    def infer_vector(self, text: str, tokenizer_factory=None,
                     iterations: int = 10, lr: float = 0.025) -> np.ndarray:
        """Train a fresh doc vector for unseen text with word tables frozen
        (ParagraphVectors.inferVector parity)."""
        tf = tokenizer_factory or DefaultTokenizerFactory()
        tokens = tf.create(text).get_tokens()
        seq = np.asarray([self.vocab.index_of(t) for t in tokens
                          if self.vocab.index_of(t) >= 0], np.int32)
        rng = np.random.default_rng(0)
        dv = jnp.asarray((rng.random((1, self.config.vector_size)) - 0.5)
                         / self.config.vector_size, dtype=jnp.float32)
        if len(seq) == 0:
            return np.asarray(dv[0])
        for i in range(iterations):
            step_lr = lr * (1 - i / iterations) + 1e-4
            dv = self._train_doc(0, seq, step_lr, frozen_words=True,
                                 doc_vecs=dv)
        return np.asarray(dv[0])

    # -------------------------------------------------------------- queries
    def doc_vector(self, label: str) -> np.ndarray:
        return np.asarray(self.doc_vecs[self.doc_labels.index(label)])

    def similarity_doc(self, a: str, b: str) -> float:
        va, vb = self.doc_vector(a), self.doc_vector(b)
        return float(va @ vb / max(np.linalg.norm(va) * np.linalg.norm(vb),
                                   1e-12))

    def nearest_labels(self, vec_or_label, top_n: int = 5):
        if isinstance(vec_or_label, str):
            v = self.doc_vector(vec_or_label)
            exclude = {self.doc_labels.index(vec_or_label)}
        else:
            v, exclude = np.asarray(vec_or_label), set()
        dvs = np.asarray(self.doc_vecs)
        dvs = dvs / np.maximum(np.linalg.norm(dvs, axis=1, keepdims=True),
                               1e-12)
        sims = dvs @ (v / max(np.linalg.norm(v), 1e-12))
        order = np.argsort(-sims)
        return [(self.doc_labels[i], float(sims[i]))
                for i in order if i not in exclude][:top_n]
