"""Multi-process embedding training — the Spark word2vec tier.

Parity: dl4j-spark-nlp's map-side SkipGram
(spark/dl4j-spark-nlp/.../word2vec/Word2VecPerformer.java:46 applies
word2vec updates inside Spark partitions against driver-broadcast vocab
and weights; FirstIterationFunction/SecondIterationFunction shard the
corpus). TPU-native rendering: every process builds the IDENTICAL vocab +
Huffman tree from the full corpus (deterministic construction replaces
the driver broadcast), trains the batched device SkipGram/CBOW updates
(nlp/sequence_vectors.py) on its strided corpus shard, and the embedding
tables (syn0 / syn1 / syn1neg) are averaged across processes over DCN
after every epoch — the LocalSGD schedule the DP tiers use
(parallel/distributed.py), applied to the embedding "parameter server"
state.

Equivalence contract (statistical, not bitwise — the update ORDER differs
from single-process by construction, exactly as the reference's Hogwild
and Spark modes differ): tests/test_multihost.py asserts 2-process
training leaves all processes bit-identical to EACH OTHER and preserves
the corpus's similarity structure the way a single-process run does.
"""

from __future__ import annotations

from typing import Iterable, List

import jax
import numpy as np


def _average_across_processes(arr):
    """Element-wise mean of one array across all processes (the
    processResults aggregate/divide of ParameterAveragingTrainingMaster
    .java:851-877, as one DCN allgather)."""
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray(jax.device_get(arr)))
    return jnp.asarray(np.mean(gathered, axis=0, dtype=np.float64).astype(
        np.asarray(arr).dtype))


class MultiProcessSequenceVectors:
    """Wrap a SequenceVectors/Word2Vec/ParagraphVectors trainer for
    multi-process corpus-sharded training."""

    def __init__(self, vectors, shard: bool = True):
        self.vectors = vectors
        self.shard = shard
        from deeplearning4j_tpu.parallel.stats import TrainingStatsCollector
        self.stats = TrainingStatsCollector(
            worker_id=f"worker_{jax.process_index()}")

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def process_count(self) -> int:
        return jax.process_count()

    def _local_shard(self, sequences: List[List[str]]) -> List[List[str]]:
        if not self.shard or self.process_count == 1:
            return sequences
        return sequences[self.process_index::self.process_count]

    def average_now(self):
        with self.stats.time_phase("average"):
            lt = self.vectors.lookup
            lt.syn0 = _average_across_processes(lt.syn0)
            if getattr(lt, "syn1", None) is not None:
                lt.syn1 = _average_across_processes(lt.syn1)
            if getattr(lt, "syn1neg", None) is not None:
                lt.syn1neg = _average_across_processes(lt.syn1neg)
        return self

    def fit(self, sequences: Iterable[List[str]]):
        """Vocab from the FULL corpus on every process (identical by
        determinism), per-epoch training on the local shard, table
        averaging after each epoch."""
        sequences = list(sequences)
        v = self.vectors
        if v.vocab is None:
            with self.stats.time_phase("vocab"):
                v.build_vocab(sequences)
        local = self._local_shard(sequences)
        epochs = v.config.epochs
        lr0 = v.config.learning_rate
        # drive the inner trainer one epoch at a time so the averaging
        # schedule sits between epochs (Word2VecPerformer's per-iteration
        # map/aggregate rounds collapse to this under LocalSGD semantics);
        # each call is handed its WINDOW of the global linear lr schedule
        # so annealing matches a single multi-epoch run
        v.config.epochs = 1
        try:
            for e in range(epochs):
                with self.stats.time_phase("fit"):
                    v.fit(local, lr_range=(lr0 * (1 - e / epochs),
                                           lr0 * (1 - (e + 1) / epochs)))
                if self.process_count > 1:
                    self.average_now()
        finally:
            v.config.epochs = epochs
        return self

    # convenience delegates
    def similarity(self, a: str, b: str) -> float:
        return self.vectors.similarity(a, b)

    def get_word_vector(self, word: str):
        return self.vectors.get_word_vector(word)
