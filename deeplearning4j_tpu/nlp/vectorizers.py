"""Text vectorizers: bag-of-words counts and TF-IDF document vectors.

Parity: deeplearning4j-nlp bagofwords/vectorizer/ —
``BaseTextVectorizer.java`` (corpus scan -> vocab via TokenizerFactory +
min word frequency + stop words, ``buildVocab`` :40), ``TfidfVectorizer
.java:35`` (``transform`` :105: per-document term counts -> tf-idf with
tf = count/docLength and idf = log10(totalDocs/docFreq), MathUtils
.java:258,271,283) and ``BagOfWordsVectorizer.java:32``.

Semantics notes (pinned by tests/test_vectorizers.py):
- tf-idf of a word absent from the document (or pruned from the vocab)
  is 0; idf uses log10 (the reference's MathUtils.idf), so a word
  appearing in ALL documents gets weight 0.
- ``BagOfWordsVectorizer.transform`` in the reference writes the
  corpus-wide ``wordFrequency`` at each present token's column
  (BagOfWordsVectorizer.java:81), NOT the in-document count. The default
  here is the in-document count (the standard bag-of-words feature a
  downstream classifier needs); pass ``corpus_frequency=True`` for the
  reference's exact behavior.

All host-side (CPU) code: vectorization is input-pipeline work; the
resulting dense [n_docs, vocab] matrices feed the device through the
ordinary DataSet path.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache


class LabelsSource:
    """Ordered label registry (documentiterator/LabelsSource.java parity):
    labels get stable indices in first-seen order."""

    def __init__(self, labels: Optional[Iterable[str]] = None):
        self._labels: List[str] = []
        self._index = {}
        for l in labels or ():
            self.add(l)

    def add(self, label: str) -> int:
        if label not in self._index:
            self._index[label] = len(self._labels)
            self._labels.append(label)
        return self._index[label]

    def index_of(self, label: str) -> int:
        return self._index.get(label, -1)

    @property
    def labels(self) -> List[str]:
        return list(self._labels)

    def __len__(self):
        return len(self._labels)


class BaseTextVectorizer:
    """Corpus scan -> vocab + document frequencies (BaseTextVectorizer
    .java:40 buildVocab). Subclasses define the per-document weighting."""

    def __init__(self, tokenizer_factory=None, min_word_frequency: int = 1,
                 stop_words: Sequence[str] = ()):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.stop_words = set(stop_words)
        self.vocab = VocabCache()
        self.doc_freq: Counter = Counter()   # word -> #docs containing it
        self.n_docs = 0
        self.labels_source = LabelsSource()

    # ------------------------------------------------------------------ fit
    def _tokens(self, text: str) -> List[str]:
        toks = self.tokenizer_factory.create(text).get_tokens()
        return [t for t in toks if t and t not in self.stop_words]

    def fit(self, documents: Iterable[str],
            labels: Optional[Iterable[str]] = None):
        """Scan the corpus: token counts, document frequencies, vocab
        pruning by ``min_word_frequency``, label registry."""
        counts = Counter()
        docs = 0
        for i, text in enumerate(documents):
            toks = self._tokens(text)
            counts.update(toks)
            self.doc_freq.update(set(toks))
            docs += 1
        self.n_docs = docs
        for word, c in counts.items():
            if c >= self.min_word_frequency:
                self.vocab.add(word, c)
        self.vocab.finalize_indices()
        if labels is not None:
            for l in labels:
                self.labels_source.add(l)
        return self

    # ------------------------------------------------------------ transform
    def _weight(self, word: str, doc_count: int, doc_len: int) -> float:
        raise NotImplementedError

    def transform_tokens(self, tokens: List[str]) -> np.ndarray:
        """[vocab]-sized weight row for one tokenized document
        (TfidfVectorizer.java:105 transform(List<String>))."""
        out = np.zeros((len(self.vocab),), np.float32)
        counts = Counter(tokens)
        for word, c in counts.items():
            idx = self.vocab.index_of(word)
            if idx >= 0:
                out[idx] = self._weight(word, c, len(tokens))
        return out

    def transform(self, documents) -> np.ndarray:
        """One doc (str) -> [vocab]; list of docs -> [n_docs, vocab]."""
        if isinstance(documents, str):
            return self.transform_tokens(self._tokens(documents))
        return np.stack([self.transform_tokens(self._tokens(d))
                         for d in documents])

    def fit_transform(self, documents: Sequence[str],
                      labels: Optional[Iterable[str]] = None) -> np.ndarray:
        docs = list(documents)
        self.fit(docs, labels)
        return self.transform(docs)

    def vectorize(self, text: str, label: str) -> DataSet:
        """One (document, label) -> DataSet(weights row, one-hot label)
        (TfidfVectorizer.java:66 vectorize)."""
        self.labels_source.add(label)
        x = self.transform(text)[None, :]
        y = np.zeros((1, len(self.labels_source)), np.float32)
        y[0, self.labels_source.index_of(label)] = 1.0
        return DataSet(x, y)


class BagOfWordsVectorizer(BaseTextVectorizer):
    """Count vectorizer (BagOfWordsVectorizer.java:32). Default weight is
    the in-document count; ``corpus_frequency=True`` reproduces the
    reference's transform exactly (global wordFrequency at each present
    column, BagOfWordsVectorizer.java:81)."""

    def __init__(self, tokenizer_factory=None, min_word_frequency: int = 1,
                 stop_words: Sequence[str] = (),
                 corpus_frequency: bool = False):
        super().__init__(tokenizer_factory, min_word_frequency, stop_words)
        self.corpus_frequency = corpus_frequency

    def _weight(self, word, doc_count, doc_len):
        if self.corpus_frequency:
            return float(self.vocab.words[word].count)
        return float(doc_count)


class TfidfVectorizer(BaseTextVectorizer):
    """TF-IDF vectorizer (TfidfVectorizer.java:35): weight =
    (count/docLength) * log10(totalDocs/docFreq)."""

    def idf(self, word: str) -> float:
        """MathUtils.idf parity: log10(totalDocs / docsContainingWord);
        0 when the corpus is empty or the word was never seen."""
        df = self.doc_freq.get(word, 0)
        if self.n_docs == 0 or df == 0:
            return 0.0
        return math.log10(self.n_docs / df)

    def _weight(self, word, doc_count, doc_len):
        tf = doc_count / doc_len if doc_len else 0.0
        return float(tf * self.idf(word))
