"""NLP stack (parity: deeplearning4j-nlp-parent, 36.5k LoC — SURVEY.md
§2.6): tokenization pipeline, vocab + Huffman, batched SkipGram/CBOW/
PV-DM/PV-DBOW/GloVe on device, word-vector serializers."""

from deeplearning4j_tpu.nlp.sequence_vectors import (
    SequenceVectors,
    SequenceVectorsConfig,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.vectorizers import (
    BagOfWordsVectorizer,
    TfidfVectorizer,
)
from deeplearning4j_tpu.nlp.distributed import MultiProcessSequenceVectors
from deeplearning4j_tpu.nlp.cjk import (
    DictionarySegmenter,
    DictionaryTokenizerFactory,
    KoreanTokenizerFactory,
    LatticeSegmenter,
    MorphToken,
)
