"""Dictionary-driven CJK segmentation behind the TokenizerFactory seam.

Parity target: the reference vendors the Kuromoji Japanese morphological
analyzer (deeplearning4j-nlp-japanese/src/main/java/com/atilika/kuromoji/,
~6.8k LoC of lattice Viterbi over a bundled lexicon) plus Korean/UIMA
annotator plug-ins, all consumed through the SAME TokenizerFactory
extension point the rest of the NLP stack uses. This module proves that
seam with an actual analyzer rather than the char-bigram baseline
(CJKCharTokenizerFactory):

- ``DictionarySegmenter``: cost-based dynamic-programming segmentation
  (the Viterbi-over-lattice core of MeCab/Kuromoji, minus
  part-of-speech connection costs): every dictionary word spans an edge
  with cost ``len-discounted``; unknown single characters get a penalty
  cost, so known multi-character words win over character soup. A small
  built-in Japanese function-word/common-noun lexicon is bundled; real
  deployments load a full lexicon with ``load_dictionary`` (one word per
  line, optionally ``word<TAB>cost``).
- ``DictionaryTokenizerFactory``: the TokenizerFactory adapter — Han/Kana
  runs go through the segmenter, other text through whitespace rules;
  drop-in everywhere a DefaultTokenizerFactory is accepted (Word2Vec,
  vectorizers, SequenceVectors).
- ``mecab_tokenizer_factory()``: optional-dependency wrapper that returns
  a factory backed by ``fugashi``/``MeCab`` when one is importable
  (none are in this image — the wrapper raises with instructions, and is
  unit-tested via a stub module), demonstrating the external-analyzer
  plug-in path the reference's add-on modules occupy.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from deeplearning4j_tpu.nlp.tokenization import (CJKCharTokenizerFactory,
                                                 DefaultTokenizerFactory)

# Compact starter lexicon: Japanese particles/copulas + common nouns and
# verbs — enough to segment everyday sentences sensibly; extend with
# load_dictionary for real corpora.
_BUILTIN_JA = (
    "私 僕 彼 彼女 猫 犬 鳥 魚 本 水 山 川 空 海 雨 雪 花 木 日本 東京 "
    "学校 先生 学生 友達 家族 電車 車 道 店 駅 会社 仕事 料理 写真 音楽 "
    "映画 言葉 名前 時間 今日 明日 昨日 今 朝 夜 昼 年 月 週 毎日 "
    "は が を に で と も の へ から まで より だ です ます でした "
    "した する して いる ある ない なかった れる られる せる たい "
    "食べる 飲む 行く 来る 見る 聞く 話す 読む 書く 買う 売る 作る "
    "好き 嫌い 大きい 小さい 新しい 古い 高い 安い 良い 悪い "
    "とても すこし たくさん これ それ あれ ここ そこ どこ 何 誰 いつ"
).split()


class DictionarySegmenter:
    """Min-cost DP segmentation over a word dictionary (the lattice
    Viterbi at Kuromoji's core, with unigram costs only)."""

    #: cost charged per unknown character (a known word of length L costs
    #: L - bonus, so any dictionary word beats spelling it out)
    UNKNOWN_COST = 2.0
    KNOWN_BONUS = 0.5

    def __init__(self, words: Optional[Iterable[str]] = None,
                 costs: Optional[Dict[str, float]] = None):
        self._costs: Dict[str, float] = {}
        self._max_len = 1
        for w in (words if words is not None else _BUILTIN_JA):
            self.add_word(w)
        for w, c in (costs or {}).items():
            self.add_word(w, c)

    def add_word(self, word: str, cost: Optional[float] = None) -> None:
        if not word:
            return
        self._costs[word] = (float(cost) if cost is not None
                             else len(word) - self.KNOWN_BONUS)
        self._max_len = max(self._max_len, len(word))

    def load_dictionary(self, path: str) -> "DictionarySegmenter":
        """Load ``word`` or ``word<TAB>cost`` lines (full-lexicon path)."""
        with open(path, encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if not parts or not parts[0]:
                    continue
                self.add_word(parts[0],
                              float(parts[1]) if len(parts) > 1 else None)
        return self

    def __contains__(self, word: str) -> bool:
        return word in self._costs

    def segment(self, text: str) -> List[str]:
        """Min-total-cost split of ``text``; ties prefer longer words
        (fewer segments)."""
        n = len(text)
        if n == 0:
            return []
        INF = float("inf")
        best = [INF] * (n + 1)
        back = [0] * (n + 1)
        nseg = [0] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] is INF:
                continue
            # unknown single character
            cand = best[i] + self.UNKNOWN_COST
            if (cand < best[i + 1]
                    or (cand == best[i + 1] and nseg[i] + 1 < nseg[i + 1])):
                best[i + 1] = cand
                back[i + 1] = i
                nseg[i + 1] = nseg[i] + 1
            # dictionary words starting at i
            for L in range(2, min(self._max_len, n - i) + 1):
                w = text[i:i + L]
                c = self._costs.get(w)
                if c is None:
                    continue
                j = i + L
                cand = best[i] + c
                if (cand < best[j]
                        or (cand == best[j] and nseg[i] + 1 < nseg[j])):
                    best[j] = cand
                    back[j] = i
                    nseg[j] = nseg[i] + 1
        out: List[str] = []
        j = n
        while j > 0:
            i = back[j]
            out.append(text[i:j])
            j = i
        out.reverse()
        return out


class DictionaryTokenizerFactory(CJKCharTokenizerFactory):
    """TokenizerFactory whose CJK runs are segmented by a
    DictionarySegmenter instead of char bigrams — the Kuromoji-shaped
    plug-in exercising the reference's extension point for real."""

    def __init__(self, segmenter: Optional[DictionarySegmenter] = None):
        super().__init__()
        self.segmenter = segmenter or DictionarySegmenter()

    def create(self, text: str):
        # walk the text the same way the parent does, but route CJK runs
        # through the segmenter instead of bigram-splitting them
        tokens: List[str] = []
        run: List[str] = []
        word: List[str] = []

        def flush_run():
            if run:
                tokens.extend(self.segmenter.segment("".join(run)))
                run.clear()

        def flush_word():
            if word:
                tokens.append("".join(word))
                word.clear()

        for ch in text:
            if self._is_cjk(ch):
                flush_word()
                run.append(ch)
            elif ch.isspace() or ch in "、。，．・「」『』（）!?！？":
                flush_run()
                flush_word()
            else:
                flush_run()
                word.append(ch)
        flush_run()
        flush_word()

        pre = self._pre

        class _T:
            def get_tokens(self_inner):
                out = []
                for t in tokens:
                    if pre is not None:
                        t = pre.pre_process(t)
                    if t:
                        out.append(t)
                return out
        return _T()


def mecab_tokenizer_factory(dicdir: Optional[str] = None):
    """Optional-dependency wrapper: a TokenizerFactory backed by a real
    installed MeCab binding (``fugashi`` or ``MeCab``) — the add-on-module
    path (deeplearning4j-nlp-japanese's role). Raises ImportError with
    instructions when neither binding is present."""
    tagger = None
    try:
        import fugashi
        tagger = fugashi.Tagger()
        parse = lambda text: [w.surface for w in tagger(text)]
    except ImportError:
        try:
            import MeCab
            tagger = MeCab.Tagger(f"-d {dicdir}" if dicdir else "")
            parse = lambda text: [
                line.split("\t")[0]
                for line in tagger.parse(text).splitlines()
                if line and line != "EOS"]
        except ImportError:
            raise ImportError(
                "mecab_tokenizer_factory needs an installed MeCab binding "
                "(pip install fugashi[unidic-lite] or mecab-python3); for "
                "offline environments use DictionaryTokenizerFactory with "
                "a bundled lexicon instead")

    class _MecabFactory(DefaultTokenizerFactory):
        def create(self, text: str):
            toks = parse(text)
            pre = self._pre

            class _T:
                def get_tokens(self_inner):
                    out = []
                    for t in toks:
                        if pre is not None:
                            t = pre.pre_process(t)
                        if t:
                            out.append(t)
                    return out
            return _T()

    return _MecabFactory()
