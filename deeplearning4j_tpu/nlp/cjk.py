"""Dictionary-driven CJK segmentation behind the TokenizerFactory seam.

Parity target: the reference vendors the Kuromoji Japanese morphological
analyzer (deeplearning4j-nlp-japanese/src/main/java/com/atilika/kuromoji/,
~6.8k LoC of lattice Viterbi over a bundled lexicon) plus Korean/UIMA
annotator plug-ins, all consumed through the SAME TokenizerFactory
extension point the rest of the NLP stack uses. This module proves that
seam with an actual analyzer rather than the char-bigram baseline
(CJKCharTokenizerFactory):

- ``LatticeSegmenter``: the full Kuromoji algorithm — bigram
  connection-cost Viterbi over a dictionary lattice
  (viterbi/ViterbiBuilder.java + ViterbiSearcher.java), char-class-based
  unknown-word insertion (CharacterDefinitions semantics: invoke/group
  per class), and part-of-speech tags carried on every token
  (``MorphToken``). Context disambiguates: すもももももももものうち
  parses noun-particle-noun…, which no unigram cost model can produce.
- ``DictionarySegmenter``: the lighter unigram tier (no connection
  costs): every dictionary word spans an edge with cost
  ``len-discounted``; unknown single characters get a penalty cost, so
  known multi-character words win over character soup. A small built-in
  Japanese function-word/common-noun lexicon is bundled; real
  deployments load a full lexicon with ``load_dictionary`` (one word per
  line, optionally ``word<TAB>cost[<TAB>pos]``).
- ``DictionaryTokenizerFactory``: the TokenizerFactory adapter — Han/Kana
  runs go through the segmenter, other text through whitespace rules;
  drop-in everywhere a DefaultTokenizerFactory is accepted (Word2Vec,
  vectorizers, SequenceVectors).
- ``mecab_tokenizer_factory()``: optional-dependency wrapper that returns
  a factory backed by ``fugashi``/``MeCab`` when one is importable
  (none are in this image — the wrapper raises with instructions, and is
  unit-tested via a stub module), demonstrating the external-analyzer
  plug-in path the reference's add-on modules occupy.
"""

from __future__ import annotations

import unicodedata
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from deeplearning4j_tpu.nlp.tokenization import (CJKCharTokenizerFactory,
                                                 DefaultTokenizerFactory)

# Starter lexicon: Japanese particles/copulas + common vocabulary —
# enough to segment everyday sentences sensibly; extend with
# load_dictionary for real corpora (one word per line,
# ``word[<TAB>cost[<TAB>pos]]``).
_JA_NOUNS = (
    "私 僕 彼 彼女 猫 犬 鳥 魚 本 水 山 川 空 海 雨 雪 花 木 日本 東京 "
    "学校 先生 学生 友達 家族 電車 車 道 店 駅 会社 仕事 料理 写真 音楽 "
    "映画 言葉 名前 時間 今日 明日 昨日 今 朝 夜 昼 年 月 週 毎日 "
    "これ それ あれ ここ そこ どこ 何 誰 いつ "
    "人 男 女 子供 手 足 目 耳 口 頭 心 体 声 顔 力 お金 紙 部屋 家 国 "
    "町 村 世界 場所 物 事 話 問題 質問 答え 意味 理由 方法 結果 情報 "
    "電話 手紙 番号 文字 文章 漢字 言語 英語 日本語 外国 旅行 買い物 "
    "食事 パン 肉 野菜 果物 卵 牛乳 お茶 酒 天気 風 火 土 石 季節 "
    "春 夏 秋 冬 色 赤 青 白 黒 緑 時計 週末 休み 病院 銀行 図書館 "
    "公園 空港 橋 建物 窓 机 椅子 箱 袋 服 靴 帽子 眼鏡 傘 荷物 切符 "
    "新聞 雑誌 辞書 地下鉄 バス 飛行機 船 自転車 歌 絵 遊び 運動 練習 "
    "勉強 試験 授業 宿題 教室 鉛筆 ノート 意見 気持ち 気分 病気 薬 "
    "医者 警察 火事 事故 地震 台風 戦争 平和 歴史 文化 社会 経済 政治 "
    "法律 科学 技術 自然 動物 植物 言い方 考え方 みんな 全部 一部 最初 "
    "最後 次 前 後ろ 上 下 中 外 右 左 隣 間 近く 遠く 今年 去年 来年 "
    "今週 来週 先週 今月 来月 先月 午前 午後 半分 大学 高校 中学 小学校"
).split()
_JA_VERBS = (
    "食べる 飲む 行く 来る 見る 聞く 話す 読む 書く 買う 売る 作る "
    "使う 持つ 待つ 会う 言う 思う 知る 分かる 出る 入る 乗る 降りる "
    "歩く 走る 泳ぐ 飛ぶ 帰る 休む 働く 遊ぶ 学ぶ 教える 覚える "
    "忘れる 始める 終わる 開ける 閉める 消す 置く 取る 送る 届く 着く "
    "立つ 座る 寝る 起きる 死ぬ 生きる 住む 呼ぶ 答える 聞こえる "
    "見える 考える 感じる 信じる 笑う 泣く 怒る 歌う 踊る 洗う 切る "
    "貸す 借りる 返す 払う 探す 見つける 決める 選ぶ 変わる 変える "
    "動く 止まる 止める 続く 続ける 助ける 手伝う 頼む 渡す 受ける "
    "落ちる 落とす 上がる 下がる 登る 並ぶ 集まる 集める"
).split()
_JA_ADJS = (
    "好き 嫌い 大きい 小さい 新しい 古い 高い 安い 良い 悪い "
    "美しい 楽しい 嬉しい 悲しい 暑い 寒い 暖かい 涼しい 強い 弱い "
    "早い 速い 遅い 近い 遠い 長い 短い 広い 狭い 重い 軽い 明るい "
    "暗い 忙しい 簡単 難しい 易しい 便利 不便 静か 有名 大切 大事 "
    "元気 親切 丁寧 綺麗 汚い 危ない 安全 白い 黒い 赤い 青い 若い "
    "面白い つまらない 甘い 辛い 苦い 美味しい 痛い 眠い"
).split()
_JA_ADVS = (
    "とても すこし たくさん もっと まだ もう ずっと きっと 多分 全然 "
    "いつも 時々 たまに すぐ ゆっくり ちょっと かなり 本当に 特に "
    "例えば でも しかし だから それで そして また"
).split()
_JA_PARTICLES = "は が を に で と も の へ から まで より や か ね よ".split()
_JA_AUX = (
    "だ です ます でした した する して いる ある ない なかった "
    "れる られる せる たい ました ません だった でしょう だろう"
).split()

_BUILTIN_JA = (_JA_NOUNS + _JA_VERBS + _JA_ADJS + _JA_ADVS + _JA_PARTICLES
               + _JA_AUX)


class DictionarySegmenter:
    """Min-cost DP segmentation over a word dictionary (the lattice
    Viterbi at Kuromoji's core, with unigram costs only)."""

    #: cost charged per unknown character (a known word of length L costs
    #: L - bonus, so any dictionary word beats spelling it out)
    UNKNOWN_COST = 2.0
    KNOWN_BONUS = 0.5

    def __init__(self, words: Optional[Iterable[str]] = None,
                 costs: Optional[Dict[str, float]] = None):
        self._costs: Dict[str, float] = {}
        self._max_len = 1
        for w in (words if words is not None else _BUILTIN_JA):
            self.add_word(w)
        for w, c in (costs or {}).items():
            self.add_word(w, c)

    def add_word(self, word: str, cost: Optional[float] = None) -> None:
        if not word:
            return
        self._costs[word] = (float(cost) if cost is not None
                             else len(word) - self.KNOWN_BONUS)
        self._max_len = max(self._max_len, len(word))

    def load_dictionary(self, path: str) -> "DictionarySegmenter":
        """Load ``word`` or ``word<TAB>cost`` lines (full-lexicon path)."""
        with open(path, encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if not parts or not parts[0]:
                    continue
                self.add_word(parts[0],
                              float(parts[1]) if len(parts) > 1 else None)
        return self

    def __contains__(self, word: str) -> bool:
        return word in self._costs

    def segment(self, text: str) -> List[str]:
        """Min-total-cost split of ``text``; ties prefer longer words
        (fewer segments)."""
        n = len(text)
        if n == 0:
            return []
        INF = float("inf")
        best = [INF] * (n + 1)
        back = [0] * (n + 1)
        nseg = [0] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] is INF:
                continue
            # unknown single character
            cand = best[i] + self.UNKNOWN_COST
            if (cand < best[i + 1]
                    or (cand == best[i + 1] and nseg[i] + 1 < nseg[i + 1])):
                best[i + 1] = cand
                back[i + 1] = i
                nseg[i + 1] = nseg[i] + 1
            # dictionary words starting at i
            for L in range(2, min(self._max_len, n - i) + 1):
                w = text[i:i + L]
                c = self._costs.get(w)
                if c is None:
                    continue
                j = i + L
                cand = best[i] + c
                if (cand < best[j]
                        or (cand == best[j] and nseg[i] + 1 < nseg[j])):
                    best[j] = cand
                    back[j] = i
                    nseg[j] = nseg[i] + 1
        out: List[str] = []
        j = n
        while j > 0:
            i = back[j]
            out.append(text[i:j])
            j = i
        out.reverse()
        return out


@dataclass(frozen=True)
class MorphToken:
    """One analyzed token: surface form + part of speech + whether it came
    from the dictionary (ViterbiNode.Type.KNOWN) or the unknown-word
    inserter (Type.UNKNOWN)."""
    surface: str
    pos: str
    known: bool


# Character classes for unknown-word handling, mirroring Kuromoji's
# CharacterDefinitions (char.def): per class (invoke, group, per-char cost,
# POS). ``invoke``: insert unknown nodes even when dictionary words match
# at this position; ``group``: one node per maximal same-class run instead
# of per character.
_CHAR_CLASSES: Dict[str, Tuple[bool, bool, float, str]] = {
    "KANJI": (False, False, 2.0, "noun"),
    "HIRAGANA": (False, False, 2.5, "unk"),
    "KATAKANA": (True, True, 1.0, "noun"),   # loanword runs are nouns
    "LATIN": (True, True, 1.0, "noun"),
    "NUMERIC": (True, True, 1.0, "noun"),
    "DEFAULT": (False, False, 3.0, "unk"),
}


def _char_class(ch: str) -> str:
    o = ord(ch)
    if 0x3040 <= o <= 0x309F:
        return "HIRAGANA"
    if 0x30A0 <= o <= 0x30FF or 0x31F0 <= o <= 0x31FF or o == 0xFF70 \
            or 0xFF66 <= o <= 0xFF9D:
        return "KATAKANA"
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF:
        return "KANJI"
    if ch.isascii() and ch.isalpha():
        return "LATIN"
    if unicodedata.category(ch) == "Nd":
        return "NUMERIC"
    return "DEFAULT"


# Default bigram connection costs over POS classes (the ConnectionCosts
# matrix tier — matrix.def in IPADIC, here a compact POS-level rendering:
# grammatical transitions are cheap, ungrammatical ones expensive).
_DEFAULT_CONNECTIONS: Dict[Tuple[str, str], float] = {
    ("noun", "particle"): 0.0, ("noun", "aux"): 0.1, ("noun", "noun"): 2.0,
    ("particle", "noun"): 0.0, ("particle", "verb"): 0.1,
    ("particle", "adj"): 0.1, ("particle", "particle"): 1.0,
    ("verb", "aux"): 0.0, ("verb", "particle"): 0.2,
    ("adj", "aux"): 0.1, ("adj", "noun"): 0.3,
    ("aux", "aux"): 0.1, ("aux", "particle"): 0.3,
    ("adv", "verb"): 0.1, ("adv", "adj"): 0.1,
    ("BOS", "particle"): 2.0, ("BOS", "aux"): 2.0,
    ("particle", "EOS"): 1.5, ("noun", "EOS"): 0.1, ("verb", "EOS"): 0.0,
    ("aux", "EOS"): 0.0, ("adj", "EOS"): 0.1,
}

# POS tags for the builtin starter lexicon (the TokenInfoDictionary tier),
# derived from the per-POS word lists above (nouns are the default).
_BUILTIN_POS: Dict[str, str] = {}
for _pos, _words in (("particle", _JA_PARTICLES), ("aux", _JA_AUX),
                     ("verb", _JA_VERBS), ("adj", _JA_ADJS),
                     ("adv", _JA_ADVS)):
    for _w in _words:
        _BUILTIN_POS[_w] = _pos


class LatticeSegmenter:
    """Connection-cost lattice Viterbi — the full Kuromoji tier.

    Upgrades DictionarySegmenter from unigram min-cost DP to the
    reference's actual algorithm (viterbi/ViterbiBuilder.java:69 build +
    ViterbiSearcher.java:68-117 search): every dictionary word spanning
    [i, j) becomes a lattice node carrying a word cost AND a POS class;
    path cost accumulates ``prev.path + connection(prev.pos, node.pos) +
    node.word_cost`` (ViterbiSearcher.updateNode:102), so the winning
    segmentation depends on grammatical CONTEXT, not just word lengths —
    the thing a unigram model cannot do (すもももももももものうち segments
    noun-particle-noun…, not noun-noun-noun). Unknown words follow
    CharacterDefinitions semantics (ViterbiBuilder.processUnknownWord:127):
    per character class, ``invoke`` inserts nodes even where dictionary
    matches exist, ``group`` spans maximal same-class runs (katakana
    loanwords, digits, latin), and each node carries the class's POS.

    BOS/EOS are real lattice nodes (ViterbiLattice.addBos/addEos), so
    sentence-position preferences participate in the search.
    """

    KNOWN_BONUS = 0.5

    def __init__(self, entries: Optional[Iterable] = None,
                 connections: Optional[Dict[Tuple[str, str], float]] = None,
                 default_connection: float = 0.5):
        self._entries: Dict[str, List[Tuple[str, float]]] = {}
        self._max_len = 1
        self._conn = dict(_DEFAULT_CONNECTIONS)
        if connections:
            self._conn.update(connections)
        self._default_conn = float(default_connection)
        if entries is None:
            for w in _BUILTIN_JA:
                self.add_word(w, pos=_BUILTIN_POS.get(w, "noun"))
        else:
            for e in entries:
                if isinstance(e, str):
                    self.add_word(e)
                else:
                    self.add_word(*e)

    # ------------------------------------------------------------ lexicon
    def add_word(self, word: str, pos: str = "noun",
                 cost: Optional[float] = None) -> None:
        if not word:
            return
        c = float(cost) if cost is not None else len(word) - self.KNOWN_BONUS
        self._entries.setdefault(word, []).append((pos, c))
        self._max_len = max(self._max_len, len(word))

    def set_connection(self, left_pos: str, right_pos: str,
                       cost: float) -> None:
        self._conn[(left_pos, right_pos)] = float(cost)

    def load_dictionary(self, path: str) -> "LatticeSegmenter":
        """``word``, ``word<TAB>cost`` or ``word<TAB>cost<TAB>pos`` lines."""
        with open(path, encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if not parts or not parts[0]:
                    continue
                cost = float(parts[1]) if len(parts) > 1 and parts[1] else None
                pos = parts[2] if len(parts) > 2 else "noun"
                self.add_word(parts[0], pos=pos, cost=cost)
        return self

    def __contains__(self, word: str) -> bool:
        return word in self._entries

    def connection(self, left_pos: str, right_pos: str) -> float:
        return self._conn.get((left_pos, right_pos), self._default_conn)

    # ------------------------------------------------------------ lattice
    def _build(self, text: str):
        """Lattice nodes (start, end, surface, pos, cost, known), grouped
        by end position (the endIndexArr of ViterbiLattice.java)."""
        n = len(text)
        nodes: List[Tuple[int, int, str, str, float, bool]] = []
        classes = [_char_class(c) for c in text]  # O(n), computed once
        for start in range(n):
            found = False
            for L in range(1, min(self._max_len, n - start) + 1):
                w = text[start:start + L]
                for pos, cost in self._entries.get(w, ()):
                    nodes.append((start, start + L, w, pos, cost, True))
                    found = True
            cls = classes[start]
            invoke, group, char_cost, pos = _CHAR_CLASSES[cls]
            if invoke or not found:
                run_start = start == 0 or classes[start - 1] != cls
                if group and run_start:
                    # ONE grouped node per maximal same-class run
                    # (Kuromoji inserts the grouped unknown at the run
                    # head; O(total_chars) overall, not O(run^2))
                    end = start + 1
                    while end < n and classes[end] == cls:
                        end += 1
                    if end > start + 1:
                        nodes.append((start, end, text[start:end], pos,
                                      char_cost * (end - start), False))
                # single-char node at EVERY position keeps mid-run
                # dictionary words reachable (a word starting inside a
                # grouped run needs an incoming edge at its start)
                nodes.append((start, start + 1, text[start], pos,
                              char_cost, False))
        return nodes

    def tokenize(self, text: str) -> List[MorphToken]:
        """Best path through the lattice as analyzed tokens."""
        n = len(text)
        if n == 0:
            return []
        nodes = self._build(text)
        ends: List[List[int]] = [[] for _ in range(n + 1)]
        for idx, nd in enumerate(nodes):
            ends[nd[1]].append(idx)
        INF = float("inf")
        path = [INF] * len(nodes)
        back = [-1] * len(nodes)   # -1 = BOS, else node index
        for idx, (start, _e, _w, pos, cost, _k) in enumerate(nodes):
            if start == 0:
                path[idx] = self.connection("BOS", pos) + cost
                continue
            best = INF
            best_prev = None
            for p in ends[start]:
                if path[p] is INF:
                    continue
                cand = path[p] + self.connection(nodes[p][3], pos) + cost
                if cand < best:
                    best, best_prev = cand, p
            if best_prev is not None:
                path[idx] = best
                back[idx] = best_prev
        # EOS
        best, best_last = INF, None
        for p in ends[n]:
            if path[p] is INF:
                continue
            cand = path[p] + self.connection(nodes[p][3], "EOS")
            if cand < best:
                best, best_last = cand, p
        if best_last is None:   # unreachable: unknown singles make every
            return [MorphToken(text, "unk", False)]  # position reachable
        out: List[MorphToken] = []
        idx = best_last
        while idx != -1:
            _s, _e, w, pos, _c, known = nodes[idx]
            out.append(MorphToken(w, pos, known))
            idx = back[idx]
        out.reverse()
        return out

    def segment(self, text: str) -> List[str]:
        """Surface forms of the best path (DictionarySegmenter-compatible,
        so this drops into DictionaryTokenizerFactory unchanged)."""
        return [t.surface for t in self.tokenize(text)]


class DictionaryTokenizerFactory(CJKCharTokenizerFactory):
    """TokenizerFactory whose CJK runs are segmented by a
    DictionarySegmenter/LatticeSegmenter instead of char bigrams — the
    Kuromoji-shaped plug-in exercising the reference's extension point
    for real.

    ``keep_pos``: optional POS whitelist (e.g. ``{"noun", "verb", "adj"}``)
    applied to analyzed CJK tokens — the PoStagger annotator tier
    (deeplearning4j-nlp-uima/.../text/annotator/PoStagger.java tags tokens
    so downstream consumers can select by part of speech; here the lattice
    carries the tags and the factory filters content words for Word2Vec /
    TF-IDF). Requires a segmenter with ``tokenize`` (LatticeSegmenter);
    non-CJK words pass through unfiltered."""

    def __init__(self, segmenter=None, keep_pos=None):
        super().__init__()
        self.segmenter = segmenter or DictionarySegmenter()
        if keep_pos is not None and not hasattr(self.segmenter, "tokenize"):
            raise ValueError(
                "keep_pos filtering needs a POS-aware segmenter "
                "(LatticeSegmenter), not "
                f"{type(self.segmenter).__name__}")
        self.keep_pos = frozenset(keep_pos) if keep_pos is not None else None

    def create(self, text: str):
        # walk the text the same way the parent does, but route CJK runs
        # through the segmenter instead of bigram-splitting them
        tokens: List[str] = []
        run: List[str] = []
        word: List[str] = []

        def flush_run():
            if run:
                if self.keep_pos is not None:
                    tokens.extend(
                        t.surface
                        for t in self.segmenter.tokenize("".join(run))
                        if t.pos in self.keep_pos)
                else:
                    tokens.extend(self.segmenter.segment("".join(run)))
                run.clear()

        def flush_word():
            if word:
                tokens.append("".join(word))
                word.clear()

        for ch in text:
            if self._is_cjk(ch):
                flush_word()
                run.append(ch)
            elif ch.isspace() or ch in "、。，．・「」『』（）!?！？":
                flush_run()
                flush_word()
            else:
                flush_run()
                word.append(ch)
        flush_run()
        flush_word()

        pre = self._pre

        class _T:
            def get_tokens(self_inner):
                out = []
                for t in tokens:
                    if pre is not None:
                        t = pre.pre_process(t)
                    if t:
                        out.append(t)
                return out
        return _T()


def mecab_tokenizer_factory(dicdir: Optional[str] = None):
    """Optional-dependency wrapper: a TokenizerFactory backed by a real
    installed MeCab binding (``fugashi`` or ``MeCab``) — the add-on-module
    path (deeplearning4j-nlp-japanese's role). Raises ImportError with
    instructions when neither binding is present."""
    tagger = None
    try:
        import fugashi
        tagger = fugashi.Tagger()
        parse = lambda text: [w.surface for w in tagger(text)]
    except ImportError:
        try:
            import MeCab
            tagger = MeCab.Tagger(f"-d {dicdir}" if dicdir else "")
            parse = lambda text: [
                line.split("\t")[0]
                for line in tagger.parse(text).splitlines()
                if line and line != "EOS"]
        except ImportError:
            raise ImportError(
                "mecab_tokenizer_factory needs an installed MeCab binding "
                "(pip install fugashi[unidic-lite] or mecab-python3); for "
                "offline environments use DictionaryTokenizerFactory with "
                "a bundled lexicon instead")

    class _MecabFactory(DefaultTokenizerFactory):
        def create(self, text: str):
            toks = parse(text)
            pre = self._pre

            class _T:
                def get_tokens(self_inner):
                    out = []
                    for t in toks:
                        if pre is not None:
                            t = pre.pre_process(t)
                        if t:
                            out.append(t)
                    return out
            return _T()

    return _MecabFactory()


# Korean particles (josa), longest-match-first — the twitter-korean-text
# stem/particle separation tier (deeplearning4j-nlp-korean/.../
# KoreanTokenizer.java wraps TwitterKoreanProcessorJava.tokenize, whose
# visible effect at this tier is splitting an eojeol into stem + josa)
_KO_JOSA = sorted(
    ("은 는 이 가 을 를 에 에서 에게 께 께서 와 과 도 만 의 로 으로 "
     "부터 까지 보다 처럼 마다 조차 밖에 라고 이라고 하고 이나 나 "
     "든지 라도 이라도 요 이며 며 랑 이랑").split(),
    key=len, reverse=True)

# Common-noun mini-lexicon validating stems before a SINGLE-syllable josa
# is stripped: many Korean nouns END in josa-lookalike syllables
# (고양이, 바나나), so suffix-only stripping would tokenize the same
# word differently bare vs particle-marked and split its embedding mass.
_KO_NOUNS = frozenset(
    ("고양이 강아지 개 새 물 우유 밥 사람 남자 여자 아이 학생 선생님 "
     "친구 가족 집 학교 회사 병원 도서관 공원 역 차 버스 기차 비행기 "
     "자전거 길 나라 한국 서울 일본 중국 미국 영어 한국어 일본어 말 "
     "글 책 신문 영화 음악 노래 사진 시간 오늘 내일 어제 아침 점심 "
     "저녁 밤 봄 여름 가을 겨울 날씨 비 눈 바람 하늘 바다 산 강 꽃 "
     "나무 색 돈 문 창문 책상 의자 옷 신발 모자 안경 우산 가방 전화 "
     "컴퓨터 커피 빵 고기 생선 야채 과일 계란 물건 일 이름 문제 질문 "
     "대답 뜻 이유 방법 결과 정보 이야기 마음 몸 손 발 귀 입 머리 "
     "얼굴 목소리 힘 바나나").split())


def _is_hangul(ch: str) -> bool:
    return 0xAC00 <= ord(ch) <= 0xD7A3


class KoreanTokenizerFactory(DefaultTokenizerFactory):
    """Korean eojeol tokenizer: whitespace-split, then each Hangul
    eojeol is separated into stem + trailing particle (josa) — the
    deeplearning4j-nlp-korean tier (KoreanTokenizer.java). This is the
    rule-based slice of what twitter-korean-text does; full
    morphological analysis plugs in through ``mecab_tokenizer_factory``
    (mecab-ko) exactly like the Japanese add-on path.

    Split policy (consistency beats recall): a single-syllable josa is
    stripped only when the remaining stem is a KNOWN noun (builtin
    mini-lexicon + ``add_noun``/``nouns=``) — otherwise 고양이 would
    tokenize as 고양+이 bare but 고양이 when particle-marked, splitting
    one word's embedding mass; multi-syllable josa (에서, 부터, ...)
    are rarely noun-final and strip from unknown stems too.

    ``emit_josa=False`` drops the particles (the common Word2Vec
    preprocessing — content words only)."""

    _STRIP = "。、，．！？!?\"'()[]{}.,;:«»\u201c\u201d\u2018\u2019"

    def __init__(self, emit_josa: bool = True, nouns=None):
        super().__init__()
        self.emit_josa = emit_josa
        self._nouns = set(_KO_NOUNS if nouns is None else nouns)

    def add_noun(self, word: str) -> "KoreanTokenizerFactory":
        self._nouns.add(word)
        return self

    def _split_eojeol(self, word: str) -> List[str]:
        if len(word) >= 2 and all(_is_hangul(c) for c in word):
            if word in self._nouns:
                return [word]  # a known bare noun is never split
            for josa in _KO_JOSA:
                if len(word) > len(josa) and word.endswith(josa):
                    stem = word[: -len(josa)]
                    if len(josa) >= 2 or stem in self._nouns:
                        parts = [stem]
                        if self.emit_josa:
                            parts.append(josa)
                        return parts
        return [word]

    def create(self, text: str):
        raw: List[str] = []
        for w in text.split():
            w = w.strip(self._STRIP)
            if w:
                raw.extend(self._split_eojeol(w))
        pre = self._pre

        class _T:
            def get_tokens(self_inner):
                out = []
                for t in raw:
                    if pre is not None:
                        t = pre.pre_process(t)
                    if t:
                        out.append(t)
                return out
        return _T()
