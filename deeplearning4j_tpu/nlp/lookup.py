"""In-memory embedding lookup table + nearest-neighbor queries.

Parity: models/embeddings/inmemory/InMemoryLookupTable.java (731 LoC:
syn0/syn1/syn1neg + negative table) and wordvectors.WordVectors query API
(similarity, wordsNearest). Tables are jnp arrays; similarity queries run
as one device matmul against the normalized table.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, make_negative_table


class InMemoryLookupTable:
    def __init__(self, cache: VocabCache, vector_size: int, seed: int = 42,
                 use_hs: bool = True, negative: int = 0,
                 negative_table_size: int = 1_000_000):
        self.cache = cache
        self.vector_size = vector_size
        V = len(cache)
        rng = np.random.default_rng(seed)
        # word2vec init: syn0 ~ U(-0.5/D, 0.5/D), syn1 zeros
        self.syn0 = jnp.asarray(
            (rng.random((V, vector_size)) - 0.5) / vector_size,
            dtype=jnp.float32)
        self.syn1 = (jnp.zeros((V, vector_size), jnp.float32)
                     if use_hs else None)
        self.syn1neg = (jnp.zeros((V, vector_size), jnp.float32)
                        if negative > 0 else None)
        self.negative = negative
        self.neg_table = (make_negative_table(cache, negative_table_size)
                          if negative > 0 else None)

    # ------------------------------------------------------------- queries
    def vector(self, word: str) -> np.ndarray:
        idx = self.cache.index_of(word)
        if idx < 0:
            raise KeyError(f"Word '{word}' not in vocabulary")
        return np.asarray(self.syn0[idx])

    def _normed(self) -> jnp.ndarray:
        norms = jnp.linalg.norm(self.syn0, axis=1, keepdims=True)
        return self.syn0 / jnp.maximum(norms, 1e-12)

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.vector(a), self.vector(b)
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / max(denom, 1e-12))

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[Tuple[str, float]]:
        if isinstance(word_or_vec, str):
            v = self.vector(word_or_vec)
            exclude = {self.cache.index_of(word_or_vec)}
        else:
            v = np.asarray(word_or_vec)
            exclude = set()
        v = v / max(np.linalg.norm(v), 1e-12)
        sims = np.asarray(self._normed() @ jnp.asarray(v, jnp.float32))
        order = np.argsort(-sims)
        out = []
        for idx in order:
            if int(idx) in exclude:
                continue
            out.append((self.cache.word_for_index(int(idx)),
                        float(sims[idx])))
            if len(out) >= top_n:
                break
        return out

    def words_nearest_sum(self, positive: Sequence[str],
                          negative: Sequence[str] = (), top_n: int = 10):
        """king - man + woman style analogy queries
        (WordVectorsImpl.wordsNearestSum parity)."""
        v = np.zeros(self.vector_size, dtype=np.float64)
        for w in positive:
            v += self.vector(w)
        for w in negative:
            v -= self.vector(w)
        exclude = {self.cache.index_of(w) for w in (*positive, *negative)}
        v = v / max(np.linalg.norm(v), 1e-12)
        sims = np.asarray(self._normed() @ jnp.asarray(v, jnp.float32))
        order = np.argsort(-sims)
        out = []
        for idx in order:
            if int(idx) in exclude:
                continue
            out.append((self.cache.word_for_index(int(idx)), float(sims[idx])))
            if len(out) >= top_n:
                break
        return out
