"""Word-vector serialization: word2vec text + Google binary formats.

Parity: models/embeddings/loader/WordVectorSerializer.java — writeWordVectors
(text: "word v1 v2 ..."), readWord2VecModel, and the Google word2vec binary
format (header "V D\n" then per word: "word " + D float32 little-endian).
"""

from __future__ import annotations

import struct

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_huffman


def write_word_vectors(lookup: InMemoryLookupTable, path: str):
    """Text format (WordVectorSerializer.writeWordVectors parity)."""
    syn0 = np.asarray(lookup.syn0)
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"{syn0.shape[0]} {syn0.shape[1]}\n")
        for i in range(syn0.shape[0]):
            word = lookup.cache.word_for_index(i)
            vec = " ".join(f"{v:.6f}" for v in syn0[i])
            f.write(f"{word} {vec}\n")


def read_word_vectors(path: str) -> InMemoryLookupTable:
    """Read the text format back (loadTxtVectors parity)."""
    with open(path, "r", encoding="utf-8") as f:
        header = f.readline().split()
        v, d = int(header[0]), int(header[1])
        cache = VocabCache()
        vecs = np.empty((v, d), np.float32)
        for i in range(v):
            parts = f.readline().rstrip("\n").split(" ")
            word = parts[0]
            vecs[i] = [float(x) for x in parts[1:d + 1]]
            cache.add(word, count=v - i)  # preserve index order
    cache.finalize_indices()
    build_huffman(cache)
    lookup = InMemoryLookupTable(cache, d, use_hs=False, negative=0)
    lookup.syn0 = jnp.asarray(vecs)
    return lookup


def write_word2vec_binary(lookup: InMemoryLookupTable, path: str):
    """Google word2vec .bin format (writeWordVectors binary parity)."""
    syn0 = np.asarray(lookup.syn0, dtype=np.float32)
    with open(path, "wb") as f:
        f.write(f"{syn0.shape[0]} {syn0.shape[1]}\n".encode("utf-8"))
        for i in range(syn0.shape[0]):
            word = lookup.cache.word_for_index(i)
            f.write(word.encode("utf-8") + b" ")
            f.write(syn0[i].tobytes())
            f.write(b"\n")


def read_word2vec_binary(path: str) -> InMemoryLookupTable:
    """Read Google word2vec .bin (readBinaryModel parity)."""
    with open(path, "rb") as f:
        header = f.readline().decode("utf-8").split()
        v, d = int(header[0]), int(header[1])
        cache = VocabCache()
        vecs = np.empty((v, d), np.float32)
        for i in range(v):
            word = bytearray()
            while True:
                ch = f.read(1)
                if ch == b"":
                    raise ValueError(
                        f"Truncated word2vec binary file: header promised "
                        f"{v} words, hit EOF at word {i}")
                if ch == b" ":
                    break
                if ch != b"\n":
                    word.extend(ch)
            vecs[i] = np.frombuffer(f.read(4 * d), dtype=np.float32)
            nl = f.read(1)
            if nl not in (b"\n", b""):
                f.seek(-1, 1)
            cache.add(word.decode("utf-8"), count=v - i)
    cache.finalize_indices()
    build_huffman(cache)
    lookup = InMemoryLookupTable(cache, d, use_hs=False, negative=0)
    lookup.syn0 = jnp.asarray(vecs)
    return lookup
