"""Vocabulary: cache, construction, Huffman coding, negative-sampling table.

Parity: models/word2vec/wordstore/ in the reference — VocabCache (word ->
VocabWord with counts/index), VocabConstructor (corpus scan + min-frequency
pruning), Huffman.java (binary tree over word frequencies -> codes/points
for hierarchical softmax), and InMemoryLookupTable's unigram^0.75 negative
sampling table (InMemoryLookupTable.java:731).
"""

from __future__ import annotations

import heapq
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

MAX_CODE_LENGTH = 40


@dataclass
class VocabWord:
    word: str
    count: int = 0
    index: int = -1
    code: List[int] = field(default_factory=list)    # Huffman code (0/1)
    points: List[int] = field(default_factory=list)  # inner-node indices


class VocabCache:
    """word -> VocabWord store (wordstore/VocabCache.java parity)."""

    def __init__(self):
        self.words: Dict[str, VocabWord] = {}
        self._by_index: List[VocabWord] = []

    def __len__(self):
        return len(self._by_index)

    def __contains__(self, word):
        return word in self.words

    def add(self, word: str, count: int = 1):
        vw = self.words.get(word)
        if vw is None:
            vw = VocabWord(word=word, count=0)
            self.words[word] = vw
        vw.count += count
        return vw

    def finalize_indices(self):
        """Assign indices by descending frequency (word2vec convention)."""
        self._by_index = sorted(self.words.values(),
                                key=lambda w: (-w.count, w.word))
        for i, vw in enumerate(self._by_index):
            vw.index = i

    def word_for_index(self, idx: int) -> str:
        return self._by_index[idx].word

    def index_of(self, word: str) -> int:
        vw = self.words.get(word)
        return -1 if vw is None else vw.index

    def total_count(self) -> int:
        return sum(w.count for w in self._by_index)

    @property
    def vocab_words(self) -> List[VocabWord]:
        return list(self._by_index)


class VocabConstructor:
    """Scan tokenized sequences, count, prune by min_word_frequency, index,
    and build the Huffman tree (VocabConstructor.java + Huffman.java)."""

    def __init__(self, min_word_frequency: int = 1):
        self.min_word_frequency = min_word_frequency

    def build(self, sequences) -> VocabCache:
        counts = Counter()
        for tokens in sequences:
            counts.update(tokens)
        cache = VocabCache()
        for word, c in counts.items():
            if c >= self.min_word_frequency:
                cache.add(word, c)
        cache.finalize_indices()
        build_huffman(cache)
        return cache


def build_huffman(cache: VocabCache):
    """Huffman.java parity: binary tree over word counts; each word gets its
    root-to-leaf ``code`` (0/1 branch choices) and ``points`` (inner-node
    row indices into syn1)."""
    words = cache.vocab_words
    n = len(words)
    if n == 0:
        return
    if n == 1:
        words[0].code, words[0].points = [0], [0]
        return
    # heap of (count, tiebreak, node_id); nodes 0..n-1 = leaves
    heap = [(w.count, i, i) for i, w in enumerate(words)]
    heapq.heapify(heap)
    parent = {}
    binary = {}
    next_id = n
    while len(heap) > 1:
        c1, _, n1 = heapq.heappop(heap)
        c2, _, n2 = heapq.heappop(heap)
        parent[n1], parent[n2] = next_id, next_id
        binary[n1], binary[n2] = 0, 1
        heapq.heappush(heap, (c1 + c2, next_id, next_id))
        next_id += 1
    root = heap[0][2]
    for i, w in enumerate(words):
        code, points = [], []
        node = i
        while node != root:
            code.append(binary[node])
            node = parent[node]
            points.append(node - n)  # inner nodes index syn1 rows
        code.reverse()
        points.reverse()
        w.code = code[:MAX_CODE_LENGTH]
        w.points = points[:MAX_CODE_LENGTH]


def make_negative_table(cache: VocabCache, table_size: int = 10_000_000,
                        power: float = 0.75) -> np.ndarray:
    """Unigram^power sampling table (InMemoryLookupTable.makeTable parity).
    Entry j holds a word index; sampling uniform j gives P(w) ∝ count^0.75."""
    counts = np.array([w.count for w in cache.vocab_words], dtype=np.float64)
    probs = counts ** power
    probs /= probs.sum()
    bounds = np.cumsum(probs)
    table = np.searchsorted(bounds, np.arange(table_size) / table_size)
    return np.minimum(table, len(counts) - 1).astype(np.int32)


def make_subsample_keep_probs(cache: VocabCache,
                              sample: float) -> Optional[np.ndarray]:
    """word2vec frequent-word subsampling: keep prob per word index
    (SequenceVectors sampling parity); None when disabled (sample <= 0)."""
    if sample <= 0:
        return None
    total = cache.total_count()
    freqs = np.array([w.count for w in cache.vocab_words],
                     dtype=np.float64) / max(total, 1)
    keep = (np.sqrt(freqs / sample) + 1) * (sample / np.maximum(freqs, 1e-12))
    return np.minimum(keep, 1.0)
