"""GloVe: co-occurrence counting + batched AdaGrad factorization.

Parity: models/glove/Glove.java (429 LoC) + models/embeddings/learning/
impl/elements/GloVe.java (406 LoC) + models/glove/count/ (co-occurrence
counting). Host counts co-occurrences into COO arrays; the device runs the
classic GloVe objective J = f(X_ij)(w_i·w~_j + b_i + b~_j - log X_ij)^2
with per-parameter AdaGrad, one jitted step per shuffled batch.
"""

from __future__ import annotations

from collections import defaultdict
from functools import partial
from typing import Iterable, List

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.vocab import VocabConstructor


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(w, wc, b, bc, hw, hwc, hb, hbc, rows, cols, logx, fx, lr):
    """One AdaGrad batch: w/wc word+context vectors, b/bc biases, h* the
    AdaGrad accumulators."""
    wi = w[rows]
    wj = wc[cols]
    diff = jnp.sum(wi * wj, axis=1) + b[rows] + bc[cols] - logx
    fdiff = fx * diff                                    # [B]
    # gradients
    gw = fdiff[:, None] * wj
    gwc = fdiff[:, None] * wi
    gb = fdiff
    gbc = fdiff
    # AdaGrad scatter updates
    hw = hw.at[rows].add(gw * gw)
    hwc = hwc.at[cols].add(gwc * gwc)
    hb = hb.at[rows].add(gb * gb)
    hbc = hbc.at[cols].add(gbc * gbc)
    w = w.at[rows].add(-lr * gw / jnp.sqrt(hw[rows] + 1e-8))
    wc = wc.at[cols].add(-lr * gwc / jnp.sqrt(hwc[cols] + 1e-8))
    b = b.at[rows].add(-lr * gb / jnp.sqrt(hb[rows] + 1e-8))
    bc = bc.at[cols].add(-lr * gbc / jnp.sqrt(hbc[cols] + 1e-8))
    return w, wc, b, bc, hw, hwc, hb, hbc


class Glove:
    def __init__(self, vector_size: int = 100, window: int = 15,
                 min_word_frequency: int = 1, epochs: int = 25,
                 learning_rate: float = 0.05, x_max: float = 100.0,
                 alpha: float = 0.75, batch_size: int = 4096,
                 symmetric: bool = True, seed: int = 42):
        self.vector_size = vector_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.batch_size = batch_size
        self.symmetric = symmetric
        self.seed = seed
        self.vocab = None
        self.lookup = None

    def fit(self, sequences: Iterable[List[str]]):
        # Materialize one-shot iterators: they must survive both the vocab
        # pass and the co-occurrence pass below.
        if iter(sequences) is sequences:
            sequences = list(sequences)
        self.vocab = VocabConstructor(self.min_word_frequency).build(sequences)
        V, D = len(self.vocab), self.vector_size
        rng = np.random.default_rng(self.seed)

        # ---- co-occurrence counting (models/glove/count parity) ----------
        cooc = defaultdict(float)
        for tokens in sequences:
            idxs = [self.vocab.index_of(t) for t in tokens]
            idxs = [i for i in idxs if i >= 0]
            for pos, wi in enumerate(idxs):
                for off in range(1, self.window + 1):
                    j = pos + off
                    if j >= len(idxs):
                        break
                    wj = idxs[j]
                    weight = 1.0 / off  # distance weighting (GloVe paper)
                    cooc[(wi, wj)] += weight
                    if self.symmetric:
                        cooc[(wj, wi)] += weight
        if not cooc:
            raise ValueError("Empty co-occurrence matrix")
        rows = np.fromiter((k[0] for k in cooc), np.int32, len(cooc))
        cols = np.fromiter((k[1] for k in cooc), np.int32, len(cooc))
        xs = np.fromiter(cooc.values(), np.float32, len(cooc))
        logx = np.log(xs)
        fx = np.minimum((xs / self.x_max) ** self.alpha, 1.0).astype(np.float32)

        # ---- tables + AdaGrad state -------------------------------------
        def init(shape):
            return jnp.asarray((rng.random(shape) - 0.5) / D, jnp.float32)
        w, wc = init((V, D)), init((V, D))
        b, bc = jnp.zeros((V,), jnp.float32), jnp.zeros((V,), jnp.float32)
        hw, hwc = jnp.ones((V, D), jnp.float32), jnp.ones((V, D), jnp.float32)
        hb, hbc = jnp.ones((V,), jnp.float32), jnp.ones((V,), jnp.float32)

        n = len(xs)
        bs = self.batch_size
        for _ in range(self.epochs):
            perm = rng.permutation(n)
            for s in range(0, n - bs + 1, bs):
                sl = perm[s:s + bs]
                w, wc, b, bc, hw, hwc, hb, hbc = _glove_step(
                    w, wc, b, bc, hw, hwc, hb, hbc,
                    jnp.asarray(rows[sl]), jnp.asarray(cols[sl]),
                    jnp.asarray(logx[sl]), jnp.asarray(fx[sl]),
                    self.learning_rate)
            if n % bs:
                sl = perm[n - (n % bs):]
                w, wc, b, bc, hw, hwc, hb, hbc = _glove_step(
                    w, wc, b, bc, hw, hwc, hb, hbc,
                    jnp.asarray(rows[sl]), jnp.asarray(cols[sl]),
                    jnp.asarray(logx[sl]), jnp.asarray(fx[sl]),
                    self.learning_rate)

        # final vectors = w + wc (GloVe paper / reference convention)
        self.lookup = InMemoryLookupTable(self.vocab, D, seed=self.seed,
                                          use_hs=True, negative=0)
        self.lookup.syn0 = w + wc
        self.lookup.syn1 = None
        return self

    def similarity(self, a, b):
        return self.lookup.similarity(a, b)

    def words_nearest(self, word, top_n: int = 10):
        return self.lookup.words_nearest(word, top_n)

    def get_word_vector(self, word):
        return self.lookup.vector(word)
