"""Word2Vec — user-facing API over SequenceVectors.

Parity: models/word2vec/Word2Vec.java (606 LoC): builder-configured,
consumes a SentenceIterator + TokenizerFactory, exposes similarity /
wordsNearest / getWordVector (SURVEY.md §2.6, baseline #4).
"""

from __future__ import annotations

from typing import Iterable, Optional

from deeplearning4j_tpu.nlp.sequence_vectors import (
    SequenceVectors,
    SequenceVectorsConfig,
)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory


class Word2Vec(SequenceVectors):
    """word2vec over sentences.

    >>> w2v = Word2Vec(vector_size=50, window=5, negative=5)
    >>> w2v.fit_sentences(sentence_iterator, DefaultTokenizerFactory())
    >>> w2v.words_nearest("day")
    """

    def __init__(self, config: SequenceVectorsConfig | None = None, **kw):
        super().__init__(config, **kw)
        self._tokenized = None

    def _tokenize_all(self, sentence_iterator, tokenizer_factory):
        tf = tokenizer_factory or DefaultTokenizerFactory()
        out = []
        for sentence in sentence_iterator:
            tokens = tf.create(sentence).get_tokens()
            if tokens:
                out.append(tokens)
        sentence_iterator.reset()
        return out

    def fit_sentences(self, sentence_iterator, tokenizer_factory=None):
        """buildVocab + fit over a SentenceIterator (Word2Vec.fit parity)."""
        self._tokenized = self._tokenize_all(sentence_iterator,
                                             tokenizer_factory)
        self.build_vocab(self._tokenized)
        return self.fit(self._tokenized)
