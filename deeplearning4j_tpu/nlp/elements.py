"""Batched embedding-update steps: SkipGram / CBOW with hierarchical
softmax and negative sampling.

Parity: models/embeddings/learning/impl/elements/{SkipGram, CBOW}.java —
the reference builds native ``AggregateSkipGram`` ops executed JNI-side in
batches (SkipGram.java:224,271-272) under Hogwild threads
(SequenceVectors.java:1101). TPU-native design: the SAME update math
(word2vec.c formulas), but one jitted step applies a whole batch of
(center, target) pairs with gathers + scatter updates — deterministic and
race-free where Hogwild is racy, and batched onto the MXU instead of
per-pair JNI calls.

Duplicate-row handling: a batch hits hot rows (Huffman roots, frequent
words) many times, all computed at the same stale parameters; summing those
updates multiplies the effective learning rate by the duplication count and
diverges on small vocabularies. Updates therefore combine as a per-row MEAN
over each batch (``_scatter_mean``) — equivalent to the sequential update in
expectation, stable at any duplication level, and ~= the plain sum when
duplication is low (large vocab). This is the "statistical, not bitwise"
Hogwild equivalence called out in SURVEY.md §7.

Tables: syn0 [V, D] input vectors; syn1 [V, D] HS inner-node vectors;
syn1neg [V, D] negative-sampling output vectors. No optimizer state —
word2vec's raw SGD, like the reference.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _sigmoid(x):
    return jax.nn.sigmoid(x)


def _scatter_mean(table, idx, updates, weights):
    """table[i] += mean over batch entries with idx==i of updates.

    idx [N], updates [N, D], weights [N] (0 excludes an entry)."""
    acc = jnp.zeros_like(table).at[idx].add(updates * weights[:, None])
    cnt = jnp.zeros((table.shape[0],), table.dtype).at[idx].add(weights)
    return table + acc / jnp.maximum(cnt, 1.0)[:, None]


@partial(jax.jit, donate_argnums=(0, 1))
def skipgram_hs_step(syn0, syn1, centers, points, codes, code_mask, lr):
    """Hierarchical-softmax skipgram batch.

    centers [B]; points/codes/code_mask [B, L] (Huffman rows, 0/1 codes,
    validity mask). Update per word2vec.c: g = (1 - code - sigma(h.v)) * lr.
    """
    h = syn0[centers]                                   # [B, D]
    v = syn1[points]                                    # [B, L, D]
    f = _sigmoid(jnp.einsum("bd,bld->bl", h, v))        # [B, L]
    g = (1.0 - codes - f) * code_mask * lr              # [B, L]
    neu1e = jnp.einsum("bl,bld->bd", g, v)              # [B, D]
    dsyn1 = (g[..., None] * h[:, None, :]).reshape(-1, h.shape[-1])
    syn1 = _scatter_mean(syn1, points.reshape(-1), dsyn1,
                         code_mask.reshape(-1))
    syn0 = _scatter_mean(syn0, centers, neu1e,
                         jnp.ones_like(centers, syn0.dtype))
    return syn0, syn1


@partial(jax.jit, donate_argnums=(0, 1))
def skipgram_ns_step(syn0, syn1neg, centers, targets, labels, lr):
    """Negative-sampling skipgram batch.

    targets [B, 1+K] = positive context + K negatives; labels [B, 1+K] =
    [1, 0, ..., 0]. g = (label - sigma(h.v)) * lr.
    """
    h = syn0[centers]
    v = syn1neg[targets]
    f = _sigmoid(jnp.einsum("bd,bkd->bk", h, v))
    g = (labels - f) * lr
    neu1e = jnp.einsum("bk,bkd->bd", g, v)
    dneg = (g[..., None] * h[:, None, :]).reshape(-1, h.shape[-1])
    syn1neg = _scatter_mean(syn1neg, targets.reshape(-1), dneg,
                            jnp.ones(dneg.shape[0], syn0.dtype))
    syn0 = _scatter_mean(syn0, centers, neu1e,
                         jnp.ones_like(centers, syn0.dtype))
    return syn0, syn1neg


def _cbow_hidden(syn0, context, ctx_mask, extra=None):
    ctx_vecs = syn0[context] * ctx_mask[..., None]      # [B, W, D]
    denom = ctx_mask.sum(axis=1, keepdims=True)
    if extra is not None:
        denom = denom + 1.0
        return (ctx_vecs.sum(axis=1) + extra) / jnp.maximum(denom, 1.0)
    return ctx_vecs.sum(axis=1) / jnp.maximum(denom, 1.0)


def _spread_to_context(syn0, context, ctx_mask, neu1e):
    """Add each row's error to all its (unmasked) context words, averaged
    per table row over the batch."""
    B, W = context.shape
    D = neu1e.shape[-1]
    upd = jnp.broadcast_to(neu1e[:, None, :], (B, W, D)).reshape(-1, D)
    return _scatter_mean(syn0, context.reshape(-1), upd, ctx_mask.reshape(-1))


@partial(jax.jit, donate_argnums=(0, 1))
def cbow_hs_step(syn0, syn1, context, ctx_mask, points, codes, code_mask, lr):
    """CBOW with hierarchical softmax: h = mean of context vectors
    (CBOW.java / word2vec.c cbow with mean), the error adds back to every
    context word."""
    h = _cbow_hidden(syn0, context, ctx_mask)
    v = syn1[points]
    f = _sigmoid(jnp.einsum("bd,bld->bl", h, v))
    g = (1.0 - codes - f) * code_mask * lr
    neu1e = jnp.einsum("bl,bld->bd", g, v)
    dsyn1 = (g[..., None] * h[:, None, :]).reshape(-1, h.shape[-1])
    syn1 = _scatter_mean(syn1, points.reshape(-1), dsyn1,
                         code_mask.reshape(-1))
    syn0 = _spread_to_context(syn0, context, ctx_mask, neu1e)
    return syn0, syn1


@partial(jax.jit, donate_argnums=(0, 1))
def cbow_ns_step(syn0, syn1neg, context, ctx_mask, targets, labels, lr):
    h = _cbow_hidden(syn0, context, ctx_mask)
    v = syn1neg[targets]
    f = _sigmoid(jnp.einsum("bd,bkd->bk", h, v))
    g = (labels - f) * lr
    neu1e = jnp.einsum("bk,bkd->bd", g, v)
    dneg = (g[..., None] * h[:, None, :]).reshape(-1, h.shape[-1])
    syn1neg = _scatter_mean(syn1neg, targets.reshape(-1), dneg,
                            jnp.ones(dneg.shape[0], syn0.dtype))
    syn0 = _spread_to_context(syn0, context, ctx_mask, neu1e)
    return syn0, syn1neg


# ---- paragraph-vector variants (DM.java / DBOW.java parity) ---------------

@partial(jax.jit, donate_argnums=(0, 1, 2))
def dm_hs_step(syn0, syn1, doc_vecs, docs, context, ctx_mask, points, codes,
               code_mask, lr):
    """PV-DM: h = mean(context word vectors + the doc vector); both the
    words and the doc vector receive the error (DM.java parity)."""
    d = doc_vecs[docs]                                   # [B, D]
    h = _cbow_hidden(syn0, context, ctx_mask, extra=d)
    v = syn1[points]
    f = _sigmoid(jnp.einsum("bd,bld->bl", h, v))
    g = (1.0 - codes - f) * code_mask * lr
    neu1e = jnp.einsum("bl,bld->bd", g, v)
    dsyn1 = (g[..., None] * h[:, None, :]).reshape(-1, h.shape[-1])
    syn1 = _scatter_mean(syn1, points.reshape(-1), dsyn1,
                         code_mask.reshape(-1))
    syn0 = _spread_to_context(syn0, context, ctx_mask, neu1e)
    doc_vecs = _scatter_mean(doc_vecs, docs, neu1e,
                             jnp.ones_like(docs, syn0.dtype))
    return syn0, syn1, doc_vecs


def _dbow_core(syn1, doc_vecs, docs, points, codes, code_mask, lr,
               update_syn1):
    h = doc_vecs[docs]
    v = syn1[points]
    f = _sigmoid(jnp.einsum("bd,bld->bl", h, v))
    g = (1.0 - codes - f) * code_mask * lr
    neu1e = jnp.einsum("bl,bld->bd", g, v)
    if update_syn1:
        dsyn1 = (g[..., None] * h[:, None, :]).reshape(-1, h.shape[-1])
        syn1 = _scatter_mean(syn1, points.reshape(-1), dsyn1,
                             code_mask.reshape(-1))
    doc_vecs = _scatter_mean(doc_vecs, docs, neu1e,
                             jnp.ones_like(docs, doc_vecs.dtype))
    return syn1, doc_vecs


@partial(jax.jit, donate_argnums=(0, 1))
def dbow_hs_step(syn1, doc_vecs, docs, points, codes, code_mask, lr):
    """PV-DBOW: the doc vector predicts each word (DBOW.java parity) —
    skipgram with the doc vector as the center; word syn0 is untouched."""
    return _dbow_core(syn1, doc_vecs, docs, points, codes, code_mask, lr,
                      update_syn1=True)


@partial(jax.jit, donate_argnums=(1,))
def dbow_hs_step_frozen(syn1, doc_vecs, docs, points, codes, code_mask, lr):
    """DBOW inference variant: syn1 frozen, only doc vectors update
    (ParagraphVectors.inferVector parity)."""
    _, doc_vecs = _dbow_core(syn1, doc_vecs, docs, points, codes, code_mask,
                             lr, update_syn1=False)
    return doc_vecs


@partial(jax.jit, donate_argnums=(2,))
def dm_hs_step_frozen(syn0, syn1, doc_vecs, docs, context, ctx_mask, points,
                      codes, code_mask, lr):
    """DM inference variant: word tables frozen, only doc vectors update."""
    d = doc_vecs[docs]
    h = _cbow_hidden(syn0, context, ctx_mask, extra=d)
    v = syn1[points]
    f = _sigmoid(jnp.einsum("bd,bld->bl", h, v))
    g = (1.0 - codes - f) * code_mask * lr
    neu1e = jnp.einsum("bl,bld->bd", g, v)
    return _scatter_mean(doc_vecs, docs, neu1e,
                         jnp.ones_like(docs, doc_vecs.dtype))
