"""SequenceVectors — the generic embedding trainer.

Parity: models/sequencevectors/SequenceVectors.java (1,218 LoC; buildVocab
:103, fit :187). The reference's architecture is Hogwild: an AsyncSequencer
producer thread (:996) + N lock-free VectorCalculationsThreads (:1101)
dispatching native AggregateSkipGram ops. TPU-native design: the host
generates (center, target) training pairs in numpy (window sampling,
frequent-word subsampling, linear lr decay — same schedule), accumulates
them into fixed-size batches, and ONE jitted scatter-add step per batch
applies the word2vec update on device (elements.py). Same math, same
hyperparameters, deterministic instead of racy.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List, Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import elements
from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.vocab import (
    VocabCache,
    VocabConstructor,
    make_subsample_keep_probs,
)


@dataclass
class SequenceVectorsConfig:
    vector_size: int = 100
    window: int = 5
    min_word_frequency: int = 1
    epochs: int = 1
    iterations: int = 1          # passes per sequence per epoch
    learning_rate: float = 0.025
    min_learning_rate: float = 1e-4
    negative: int = 0            # 0 => hierarchical softmax
    use_hs: Optional[bool] = None  # default: negative == 0
    sample: float = 0.0          # frequent-word subsampling threshold
    batch_size: int = 1024
    seed: int = 42
    algorithm: str = "skipgram"  # or "cbow"


class SequenceVectors:
    """Train embeddings over an iterable of token sequences."""

    def __init__(self, config: SequenceVectorsConfig | None = None, **kw):
        if config is None:
            config = SequenceVectorsConfig(**kw)
        self.config = config
        if config.use_hs is None:
            config.use_hs = config.negative == 0
        if not config.use_hs and config.negative == 0:
            raise ValueError("Enable hierarchical softmax or negative "
                             "sampling (negative > 0)")
        self.vocab: Optional[VocabCache] = None
        self.lookup: Optional[InMemoryLookupTable] = None
        self._rng = np.random.default_rng(config.seed)

    # ------------------------------------------------------------- vocab
    def build_vocab(self, sequences: Iterable[List[str]]):
        self.vocab = VocabConstructor(
            self.config.min_word_frequency).build(sequences)
        self.lookup = InMemoryLookupTable(
            self.vocab, self.config.vector_size, seed=self.config.seed,
            use_hs=self.config.use_hs, negative=self.config.negative)
        # fixed-width Huffman code arrays for the jitted steps
        if self.config.use_hs:
            L = max((len(w.code) for w in self.vocab.vocab_words), default=1)
            V = len(self.vocab)
            self._codes = np.zeros((V, L), np.float32)
            self._points = np.zeros((V, L), np.int32)
            self._code_mask = np.zeros((V, L), np.float32)
            for w in self.vocab.vocab_words:
                n = len(w.code)
                self._codes[w.index, :n] = w.code
                self._points[w.index, :n] = w.points
                self._code_mask[w.index, :n] = 1.0
        self._keep_probs = make_subsample_keep_probs(self.vocab,
                                                     self.config.sample)
        return self

    # ------------------------------------------------------------ training
    def _sequences_to_indices(self, sequences):
        out = []
        for tokens in sequences:
            idxs = [self.vocab.index_of(t) for t in tokens]
            idxs = [i for i in idxs if i >= 0]
            if len(idxs) >= 2:
                out.append(np.asarray(idxs, np.int32))
        return out

    def _subsample(self, seq):
        if self._keep_probs is None:
            return seq
        keep = self._rng.random(len(seq)) < self._keep_probs[seq]
        return seq[keep]

    def _gen_pairs(self, seq):
        """(center, target) pairs with word2vec's random dynamic window."""
        cfg = self.config
        n = len(seq)
        bs = self._rng.integers(1, cfg.window + 1, size=n)
        pairs_c, pairs_t, ctx_rows = [], [], []
        for pos in range(n):
            b = bs[pos]
            lo, hi = max(0, pos - b), min(n, pos + b + 1)
            ctx = [seq[j] for j in range(lo, hi) if j != pos]
            if not ctx:
                continue
            if cfg.algorithm == "skipgram":
                # predict current word from each context word: the context
                # word's vector updates (SkipGram.java iterateSample parity)
                for c in ctx:
                    pairs_c.append(c)
                    pairs_t.append(seq[pos])
            else:  # cbow
                ctx_rows.append((ctx, seq[pos]))
        return pairs_c, pairs_t, ctx_rows

    def fit(self, sequences: Iterable[List[str]],
            lr_range: Optional[tuple] = None):
        """Train (SequenceVectors.fit :187 parity). ``sequences`` may be any
        re-iterable of token lists.

        ``lr_range=(start, end)`` overrides the learning-rate window this
        call sweeps linearly (floored at min_learning_rate). Default is
        the full word2vec schedule (learning_rate -> 0). A multi-epoch
        driver that calls fit once per epoch (nlp/distributed.py) passes
        successive windows so the GLOBAL schedule matches a single
        multi-epoch call."""
        cfg = self.config
        # Materialize one-shot iterators (iter(x) is x) so they survive the
        # two passes (vocab build + training); re-iterable streaming corpora
        # are left alone.
        if iter(sequences) is sequences:
            sequences = list(sequences)
        if self.vocab is None:
            self.build_vocab(sequences)
        seqs = self._sequences_to_indices(sequences)
        total_words = sum(len(s) for s in seqs) * cfg.epochs * cfg.iterations
        seen = 0
        lr_start, lr_end = (lr_range if lr_range is not None
                            else (cfg.learning_rate, 0.0))
        lr = max(cfg.min_learning_rate, lr_start)

        buf_c, buf_t, buf_ctx = [], [], []
        for _ in range(cfg.epochs):
            order = self._rng.permutation(len(seqs))
            for si in order:
                for _ in range(cfg.iterations):
                    seq = self._subsample(seqs[si])
                    if len(seq) < 2:
                        seen += len(seqs[si])
                        continue
                    pc, pt, ctx = self._gen_pairs(seq)
                    buf_c.extend(pc)
                    buf_t.extend(pt)
                    buf_ctx.extend(ctx)
                    seen += len(seqs[si])
                    frac = seen / max(total_words, 1)
                    lr = max(cfg.min_learning_rate,
                             lr_start + (lr_end - lr_start) * frac)
                    while len(buf_c) >= cfg.batch_size:
                        self._apply_skipgram(buf_c[:cfg.batch_size],
                                             buf_t[:cfg.batch_size], lr)
                        del buf_c[:cfg.batch_size], buf_t[:cfg.batch_size]
                    while len(buf_ctx) >= cfg.batch_size:
                        self._apply_cbow(buf_ctx[:cfg.batch_size], lr)
                        del buf_ctx[:cfg.batch_size]
        # tail flush at the schedule's CURRENT lr (for the default full
        # schedule this is ~min_learning_rate, the old behavior; for a
        # windowed call it must not collapse to the floor mid-training)
        if buf_c:
            self._apply_skipgram(buf_c, buf_t, lr)
        if buf_ctx:
            self._apply_cbow(buf_ctx, lr)
        return self

    # ------------------------------------------------------- batch applies
    def _hs_arrays(self, targets):
        t = np.asarray(targets, np.int32)
        return (jnp.asarray(self._points[t]), jnp.asarray(self._codes[t]),
                jnp.asarray(self._code_mask[t]))

    def _draw_negatives(self, targets):
        cfg = self.config
        t = np.asarray(targets, np.int32)
        neg = self.lookup.neg_table[
            self._rng.integers(0, len(self.lookup.neg_table),
                               size=(len(t), cfg.negative))]
        # avoid sampling the positive as its own negative: resample once
        clash = neg == t[:, None]
        if clash.any():
            neg = np.where(clash, (neg + 1) % len(self.vocab), neg)
        targets_all = np.concatenate([t[:, None], neg], axis=1)
        labels = np.zeros_like(targets_all, np.float32)
        labels[:, 0] = 1.0
        return jnp.asarray(targets_all), jnp.asarray(labels)

    def _apply_skipgram(self, centers, targets, lr):
        lk = self.lookup
        c = jnp.asarray(np.asarray(centers, np.int32))
        if self.config.use_hs:
            points, codes, mask = self._hs_arrays(targets)
            lk.syn0, lk.syn1 = elements.skipgram_hs_step(
                lk.syn0, lk.syn1, c, points, codes, mask, lr)
        if self.config.negative > 0:
            tgt, labels = self._draw_negatives(targets)
            lk.syn0, lk.syn1neg = elements.skipgram_ns_step(
                lk.syn0, lk.syn1neg, c, tgt, labels, lr)

    def _apply_cbow(self, rows, lr):
        lk = self.lookup
        W = max(len(ctx) for ctx, _ in rows)
        B = len(rows)
        ctx_arr = np.zeros((B, W), np.int32)
        ctx_mask = np.zeros((B, W), np.float32)
        targets = np.empty(B, np.int32)
        for i, (ctx, t) in enumerate(rows):
            ctx_arr[i, :len(ctx)] = ctx
            ctx_mask[i, :len(ctx)] = 1.0
            targets[i] = t
        ctx_j = jnp.asarray(ctx_arr)
        mask_j = jnp.asarray(ctx_mask)
        if self.config.use_hs:
            points, codes, cmask = self._hs_arrays(targets)
            lk.syn0, lk.syn1 = elements.cbow_hs_step(
                lk.syn0, lk.syn1, ctx_j, mask_j, points, codes, cmask, lr)
        if self.config.negative > 0:
            tgt, labels = self._draw_negatives(targets)
            lk.syn0, lk.syn1neg = elements.cbow_ns_step(
                lk.syn0, lk.syn1neg, ctx_j, mask_j, tgt, labels, lr)

    # -------------------------------------------------------------- queries
    def similarity(self, a: str, b: str) -> float:
        return self.lookup.similarity(a, b)

    def words_nearest(self, word, top_n: int = 10):
        return self.lookup.words_nearest(word, top_n)

    def get_word_vector(self, word: str):
        return self.lookup.vector(word)
