"""Tokenization / corpus pipeline.

Parity: deeplearning4j-nlp text/tokenization/ (TokenizerFactory ->
Tokenizer -> TokenPreProcess), text/sentenceiterator/ and
text/documentiterator/ (SURVEY.md §2.6). The pipeline shape is identical:
SentenceIterator -> TokenizerFactory.create(sentence) -> tokens ->
preprocessor per token. All host-side (CPU) code — tokenization never
touches the device.
"""

from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional


# ---------------------------------------------------------------------------
# Token preprocessors (text/tokenization/tokenizer/preprocessor/ parity)
# ---------------------------------------------------------------------------

class CommonPreprocessor:
    """Lowercase + strip punctuation (CommonPreprocessor.java parity)."""

    _PUNCT = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class LowCasePreprocessor:
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor:
    """Crude stemmer (EndingPreProcessor.java parity: strips s/ed/ing/ly)."""

    def pre_process(self, token: str) -> str:
        for suffix in ("ing", "ed", "ly", "s"):
            if token.endswith(suffix) and len(token) > len(suffix) + 2:
                return token[: -len(suffix)]
        return token


class StemmerPreProcessor:
    """Porter stemmer as a token pre-process — the StemmerAnnotator tier
    (deeplearning4j-nlp-uima/.../annotator/StemmerAnnotator.java wraps the
    Snowball English stemmer as a UIMA pipeline stage; here the stemmer IS
    the pre-process, pluggable into any TokenizerFactory via
    set_token_pre_processor). Implements the classic Porter algorithm
    (steps 1a-5b) rather than EndingPreProcessor's four-suffix strip."""

    _VOWELS = set("aeiou")

    def _cons(self, w: str, i: int) -> bool:
        c = w[i]
        if c in self._VOWELS:
            return False
        if c == "y":
            return i == 0 or not self._cons(w, i - 1)
        return True

    def _measure(self, stem: str) -> int:
        """Number of VC sequences (the m in Porter's [C](VC)^m[V])."""
        m, prev_v = 0, False
        for i in range(len(stem)):
            v = not self._cons(stem, i)
            if prev_v and not v:
                m += 1
            prev_v = v
        return m

    def _has_vowel(self, stem: str) -> bool:
        return any(not self._cons(stem, i) for i in range(len(stem)))

    def _cvc(self, stem: str) -> bool:
        if len(stem) < 3:
            return False
        return (self._cons(stem, -1 + len(stem)) and
                not self._cons(stem, len(stem) - 2) and
                self._cons(stem, len(stem) - 3) and
                stem[-1] not in "wxy")

    def _repl(self, w, rules, cond=None):
        """First matching (suffix, repl) rule whose stem passes ``cond``."""
        for suf, repl in rules:
            if w.endswith(suf):
                stem = w[: len(w) - len(suf)]
                if cond is None or cond(stem):
                    return stem + repl
                return w
        return w

    def pre_process(self, token: str) -> str:
        w = token.lower()
        if len(w) <= 2:
            return w
        # step 1a
        w = self._repl(w, (("sses", "ss"), ("ies", "i"), ("ss", "ss"),
                           ("s", "")))
        # step 1b
        if w.endswith("eed"):
            stem = w[:-3]
            if self._measure(stem) > 0:
                w = w[:-1]
        else:
            for suf in ("ed", "ing"):
                if w.endswith(suf) and self._has_vowel(w[: -len(suf)]):
                    w = w[: -len(suf)]
                    if w.endswith(("at", "bl", "iz")):
                        w += "e"
                    elif (len(w) > 1 and w[-1] == w[-2]
                          and self._cons(w, len(w) - 1)
                          and w[-1] not in "lsz"):
                        w = w[:-1]
                    elif self._measure(w) == 1 and self._cvc(w):
                        w += "e"
                    break
        # step 1c
        if w.endswith("y") and self._has_vowel(w[:-1]):
            w = w[:-1] + "i"
        # step 2
        w = self._repl(w, (
            ("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
            ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
            ("alli", "al"), ("entli", "ent"), ("eli", "e"), ("ousli", "ous"),
            ("ization", "ize"), ("ation", "ate"), ("ator", "ate"),
            ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
            ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"),
            ("biliti", "ble")), lambda s: self._measure(s) > 0)
        # step 3
        w = self._repl(w, (
            ("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
            ("ical", "ic"), ("ful", ""), ("ness", "")),
            lambda s: self._measure(s) > 0)
        # step 4
        w = self._repl(w, (
            ("al", ""), ("ance", ""), ("ence", ""), ("er", ""), ("ic", ""),
            ("able", ""), ("ible", ""), ("ant", ""), ("ement", ""),
            ("ment", ""), ("ent", ""), ("ou", ""), ("ism", ""), ("ate", ""),
            ("iti", ""), ("ous", ""), ("ive", ""), ("ize", "")),
            lambda s: self._measure(s) > 1)
        if w.endswith(("sion", "tion")) and self._measure(w[:-3]) > 1:
            w = w[:-3]
        # step 5a
        if w.endswith("e"):
            stem = w[:-1]
            m = self._measure(stem)
            if m > 1 or (m == 1 and not self._cvc(stem)):
                w = stem
        # step 5b
        if (len(w) > 1 and w[-1] == "l" and w[-2] == "l"
                and self._measure(w) > 1):
            w = w[:-1]
        return w


# ---------------------------------------------------------------------------
# Tokenizers (text/tokenization/tokenizerfactory/ parity)
# ---------------------------------------------------------------------------

class DefaultTokenizer:
    """Whitespace tokenizer (DefaultTokenizer.java parity)."""

    def __init__(self, text: str, preprocessor=None):
        self._tokens = text.split()
        self._pre = preprocessor

    def get_tokens(self) -> List[str]:
        out = []
        for t in self._tokens:
            if self._pre is not None:
                t = self._pre.pre_process(t)
            if t:
                out.append(t)
        return out


class DefaultTokenizerFactory:
    def __init__(self):
        self._pre = None

    def set_token_pre_processor(self, pre):
        self._pre = pre
        return self

    def create(self, text: str) -> DefaultTokenizer:
        return DefaultTokenizer(text, self._pre)


class NGramTokenizerFactory(DefaultTokenizerFactory):
    """Emits n-grams joined by '_' (NGramTokenizerFactory.java parity)."""

    def __init__(self, n_min: int = 1, n_max: int = 2):
        super().__init__()
        self.n_min, self.n_max = n_min, n_max

    def create(self, text: str):
        base = DefaultTokenizer(text, self._pre).get_tokens()
        grams = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(base) - n + 1):
                grams.append("_".join(base[i:i + n]))

        class _T:
            def get_tokens(self_inner):
                return grams
        return _T()


# ---------------------------------------------------------------------------
# Sentence iterators (text/sentenceiterator/ parity)
# ---------------------------------------------------------------------------

def split_sentences(text: str) -> List[str]:
    """Split raw text into sentences — the SentenceAnnotator tier
    (deeplearning4j-nlp-uima/.../annotator/SentenceAnnotator.java wraps
    the UIMA sentence detector; here a rule-based splitter covering
    Latin terminators, CJK 。！？, and blank-line paragraph breaks).
    Abbreviation-safe for single-letter initials ("J. Smith")."""
    text = text.replace("\r\n", "\n").replace("\r", "\n")
    out: List[str] = []
    buf: List[str] = []
    quote_split = False  # terminator seen, closing quote still pending

    def flush():
        s = "".join(buf).strip()
        if s:
            out.append(s)
        buf.clear()

    for i, ch in enumerate(text):
        if ch == "\n":
            # blank line = hard break; single newline = soft space
            prev = text[i - 1] if i >= 1 else None
            nxt = text[i + 1] if i + 1 < len(text) else None
            quote_split = False
            if prev == "\n" or nxt == "\n":
                flush()
            elif buf and buf[-1] != " ":
                buf.append(" ")
            continue
        buf.append(ch)
        if quote_split:
            quote_split = False
            if ch == '"':  # keep the closing quote with its sentence
                flush()
                continue
        if ch in "。！？":
            flush()
        elif ch in ".!?":
            nxt = text[i + 1] if i + 1 < len(text) else None
            # "J. Smith": a period after a single capital is an initial
            initial = (ch == "." and i >= 1 and text[i - 1].isupper()
                       and (i < 2 or not text[i - 2].isalpha()))
            if initial:
                continue
            if nxt is None or nxt in (" ", "\t", "\n"):
                flush()
            elif nxt == '"':
                quote_split = True
    flush()
    return out


class DocumentSentenceIterator:
    """SentenceIterator over raw DOCUMENTS: each document is segmented by
    ``split_sentences`` (UimaSentenceIterator.java parity — the reference
    feeds documents through the UIMA sentence detector to get the
    sentence stream Word2Vec consumes)."""

    def __init__(self, documents: Iterable[str], splitter=split_sentences):
        self._docs = list(documents)
        self._splitter = splitter
        self._pre: Optional[Callable[[str], str]] = None

    def set_pre_processor(self, fn: Callable[[str], str]):
        self._pre = fn
        return self

    def __iter__(self):
        for doc in self._docs:
            for s in self._splitter(doc):
                yield self._pre(s) if self._pre is not None else s

    def reset(self):
        return self


class CollectionSentenceIterator:
    """Iterate over an in-memory list of sentences
    (CollectionSentenceIterator.java parity)."""

    def __init__(self, sentences: Iterable[str]):
        self._sentences = list(sentences)
        self._pre: Optional[Callable[[str], str]] = None

    def set_pre_processor(self, fn: Callable[[str], str]):
        self._pre = fn
        return self

    def __iter__(self):
        for s in self._sentences:
            yield self._pre(s) if self._pre else s

    def reset(self):
        pass


class BasicLineIterator:
    """One sentence per line from a file (BasicLineIterator.java parity)."""

    def __init__(self, path: str):
        self.path = path
        self._pre = None

    def set_pre_processor(self, fn):
        self._pre = fn
        return self

    def __iter__(self):
        with open(self.path, "r", encoding="utf-8", errors="ignore") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield self._pre(line) if self._pre else line

    def reset(self):
        pass


class LabelAwareIterator:
    """(label, text) document pairs for ParagraphVectors
    (text/documentiterator/LabelAwareIterator.java parity)."""

    def __init__(self, documents: Iterable):
        """documents: iterable of (label, text) or dict {label: text}."""
        if isinstance(documents, dict):
            documents = list(documents.items())
        self._docs = list(documents)

    def __iter__(self):
        return iter(self._docs)

    def labels(self):
        return [l for l, _ in self._docs]

    def reset(self):
        pass


class CJKCharTokenizerFactory(DefaultTokenizerFactory):
    """CJK-aware tokenizer: Han/Kana/Hangul runs are emitted as character
    bigrams (plus single chars for length-1 runs); other runs tokenize as
    whitespace/word tokens.

    Substitution note (SURVEY.md §2.0/§2.6): the reference vendors the
    Kuromoji Japanese morphological analyzer (deeplearning4j-nlp-japanese,
    ~6.8k LoC) and UIMA/Korean annotator plug-ins — host-side text
    plumbing with no TPU relevance. Character n-gram segmentation is the
    standard analyzer-free baseline for CJK embedding training; a real
    analyzer can be plugged in through this same TokenizerFactory seam
    (the reference's own extension point)."""

    _CJK = (
        (0x3040, 0x30FF),   # hiragana + katakana
        (0x4E00, 0x9FFF),   # CJK unified ideographs
        (0x3400, 0x4DBF),   # CJK extension A
        (0xAC00, 0xD7AF),   # hangul syllables
        (0xF900, 0xFAFF),   # CJK compatibility ideographs
    )

    @classmethod
    def _is_cjk(cls, ch: str) -> bool:
        cp = ord(ch)
        return any(lo <= cp <= hi for lo, hi in cls._CJK)

    def create(self, text: str):
        tokens: List[str] = []
        run = []

        def flush_run():
            if not run:
                return
            s = "".join(run)
            if len(s) == 1:
                tokens.append(s)
            else:
                tokens.extend(s[i:i + 2] for i in range(len(s) - 1))
            run.clear()

        word = []

        def flush_word():
            if word:
                tokens.append("".join(word))
                word.clear()

        for ch in text:
            if self._is_cjk(ch):
                flush_word()
                run.append(ch)
            elif ch.isspace() or not (ch.isalnum() or ch in "'-_"):
                flush_run()
                flush_word()
            else:
                flush_run()
                word.append(ch)
        flush_run()
        flush_word()
        tok = DefaultTokenizer("", self._pre)
        tok._tokens = tokens
        return tok
