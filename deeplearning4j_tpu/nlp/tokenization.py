"""Tokenization / corpus pipeline.

Parity: deeplearning4j-nlp text/tokenization/ (TokenizerFactory ->
Tokenizer -> TokenPreProcess), text/sentenceiterator/ and
text/documentiterator/ (SURVEY.md §2.6). The pipeline shape is identical:
SentenceIterator -> TokenizerFactory.create(sentence) -> tokens ->
preprocessor per token. All host-side (CPU) code — tokenization never
touches the device.
"""

from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional


# ---------------------------------------------------------------------------
# Token preprocessors (text/tokenization/tokenizer/preprocessor/ parity)
# ---------------------------------------------------------------------------

class CommonPreprocessor:
    """Lowercase + strip punctuation (CommonPreprocessor.java parity)."""

    _PUNCT = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class LowCasePreprocessor:
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor:
    """Crude stemmer (EndingPreProcessor.java parity: strips s/ed/ing/ly)."""

    def pre_process(self, token: str) -> str:
        for suffix in ("ing", "ed", "ly", "s"):
            if token.endswith(suffix) and len(token) > len(suffix) + 2:
                return token[: -len(suffix)]
        return token


# ---------------------------------------------------------------------------
# Tokenizers (text/tokenization/tokenizerfactory/ parity)
# ---------------------------------------------------------------------------

class DefaultTokenizer:
    """Whitespace tokenizer (DefaultTokenizer.java parity)."""

    def __init__(self, text: str, preprocessor=None):
        self._tokens = text.split()
        self._pre = preprocessor

    def get_tokens(self) -> List[str]:
        out = []
        for t in self._tokens:
            if self._pre is not None:
                t = self._pre.pre_process(t)
            if t:
                out.append(t)
        return out


class DefaultTokenizerFactory:
    def __init__(self):
        self._pre = None

    def set_token_pre_processor(self, pre):
        self._pre = pre
        return self

    def create(self, text: str) -> DefaultTokenizer:
        return DefaultTokenizer(text, self._pre)


class NGramTokenizerFactory(DefaultTokenizerFactory):
    """Emits n-grams joined by '_' (NGramTokenizerFactory.java parity)."""

    def __init__(self, n_min: int = 1, n_max: int = 2):
        super().__init__()
        self.n_min, self.n_max = n_min, n_max

    def create(self, text: str):
        base = DefaultTokenizer(text, self._pre).get_tokens()
        grams = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(base) - n + 1):
                grams.append("_".join(base[i:i + n]))

        class _T:
            def get_tokens(self_inner):
                return grams
        return _T()


# ---------------------------------------------------------------------------
# Sentence iterators (text/sentenceiterator/ parity)
# ---------------------------------------------------------------------------

class CollectionSentenceIterator:
    """Iterate over an in-memory list of sentences
    (CollectionSentenceIterator.java parity)."""

    def __init__(self, sentences: Iterable[str]):
        self._sentences = list(sentences)
        self._pre: Optional[Callable[[str], str]] = None

    def set_pre_processor(self, fn: Callable[[str], str]):
        self._pre = fn
        return self

    def __iter__(self):
        for s in self._sentences:
            yield self._pre(s) if self._pre else s

    def reset(self):
        pass


class BasicLineIterator:
    """One sentence per line from a file (BasicLineIterator.java parity)."""

    def __init__(self, path: str):
        self.path = path
        self._pre = None

    def set_pre_processor(self, fn):
        self._pre = fn
        return self

    def __iter__(self):
        with open(self.path, "r", encoding="utf-8", errors="ignore") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield self._pre(line) if self._pre else line

    def reset(self):
        pass


class LabelAwareIterator:
    """(label, text) document pairs for ParagraphVectors
    (text/documentiterator/LabelAwareIterator.java parity)."""

    def __init__(self, documents: Iterable):
        """documents: iterable of (label, text) or dict {label: text}."""
        if isinstance(documents, dict):
            documents = list(documents.items())
        self._docs = list(documents)

    def __iter__(self):
        return iter(self._docs)

    def labels(self):
        return [l for l, _ in self._docs]

    def reset(self):
        pass


class CJKCharTokenizerFactory(DefaultTokenizerFactory):
    """CJK-aware tokenizer: Han/Kana/Hangul runs are emitted as character
    bigrams (plus single chars for length-1 runs); other runs tokenize as
    whitespace/word tokens.

    Substitution note (SURVEY.md §2.0/§2.6): the reference vendors the
    Kuromoji Japanese morphological analyzer (deeplearning4j-nlp-japanese,
    ~6.8k LoC) and UIMA/Korean annotator plug-ins — host-side text
    plumbing with no TPU relevance. Character n-gram segmentation is the
    standard analyzer-free baseline for CJK embedding training; a real
    analyzer can be plugged in through this same TokenizerFactory seam
    (the reference's own extension point)."""

    _CJK = (
        (0x3040, 0x30FF),   # hiragana + katakana
        (0x4E00, 0x9FFF),   # CJK unified ideographs
        (0x3400, 0x4DBF),   # CJK extension A
        (0xAC00, 0xD7AF),   # hangul syllables
        (0xF900, 0xFAFF),   # CJK compatibility ideographs
    )

    @classmethod
    def _is_cjk(cls, ch: str) -> bool:
        cp = ord(ch)
        return any(lo <= cp <= hi for lo, hi in cls._CJK)

    def create(self, text: str):
        tokens: List[str] = []
        run = []

        def flush_run():
            if not run:
                return
            s = "".join(run)
            if len(s) == 1:
                tokens.append(s)
            else:
                tokens.extend(s[i:i + 2] for i in range(len(s) - 1))
            run.clear()

        word = []

        def flush_word():
            if word:
                tokens.append("".join(word))
                word.clear()

        for ch in text:
            if self._is_cjk(ch):
                flush_word()
                run.append(ch)
            elif ch.isspace() or not (ch.isalnum() or ch in "'-_"):
                flush_run()
                flush_word()
            else:
                flush_run()
                word.append(ch)
        flush_run()
        flush_word()
        tok = DefaultTokenizer("", self._pre)
        tok._tokens = tokens
        return tok
